"""Quickstart: boot a server, ingest via the Influx gateway, query PromQL.

    python examples/quickstart.py [--cpu]
"""

import argparse
import json
import os
import socket
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.client import FiloClient
    from filodb_tpu.config import ServerConfig
    from filodb_tpu.standalone import FiloServer

    tmp = tempfile.mkdtemp(prefix="filodb-quickstart-")
    cfg_path = os.path.join(tmp, "server.json")
    with open(cfg_path, "w") as f:
        json.dump({
            "node_name": "quickstart",
            "data_dir": os.path.join(tmp, "data"),
            "http_port": 0,
            "gateway_port": free_port(),
            "datasets": {"timeseries": {
                "num_shards": 2, "spread": 1,
                "store": {"max_chunk_size": 120, "groups_per_shard": 4}}},
        }, f)

    print("booting server...")
    server = FiloServer(ServerConfig.load(cfg_path)).start()
    try:
        now = int(time.time())
        start = now - 600
        print(f"feeding 10 minutes of Influx-line data for 4 hosts...")
        with socket.create_connection(("127.0.0.1",
                                       server.gateway.port)) as s:
            for i in range(60):
                ts_ns = (start + i * 10) * 1_000_000_000
                for host in range(4):
                    s.sendall(
                        f"cpu_usage,host=h{host},_ws_=demo,_ns_=quick "
                        f"value={50 + host * 10 + (i % 7)} {ts_ns}\n"
                        .encode())
                    s.sendall(
                        f"http_requests,host=h{host},_ws_=demo,_ns_=quick "
                        f"counter={i * (host + 1) * 3} {ts_ns}\n".encode())
        server.gateway.sink.flush()
        time.sleep(0.5)  # let the ingest workers drain the WAL

        client = FiloClient(port=server.http.port)
        print("\n--- avg cpu by host over the window ---")
        for series in client.query_range(
                "avg_over_time(cpu_usage[2m])", start + 120, now, 120):
            host = series["metric"]["host"]
            last = series["values"][-1][1]
            print(f"  host={host}: avg_over_time={last}")

        print("\n--- request rate (sum) ---")
        for series in client.query_range(
                "sum(rate(http_requests[2m]))", start + 120, now, 60):
            print(f"  {len(series['values'])} steps, "
                  f"last={series['values'][-1][1]} req/s")

        print("\n--- top-2 hottest hosts right now ---")
        for series in client.query("topk(2, cpu_usage)", now):
            print(f"  host={series['metric']['host']} "
                  f"value={series['value'][1]}")

        print("\n--- labels ---")
        print(" ", client.label_names())
        print("\n--- cluster ---")
        for st in client.cluster_status():
            print(f"  shard {st['shard']}: {st['status']} on {st['node']}")
        print("\nquickstart OK")
    finally:
        server.shutdown()


if __name__ == "__main__":
    main()
