"""Benchmark: the reference's QueryInMemoryBenchmark workload on TPU.

Reproduces the workload of
``jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:31-35,126-130``:
100 series × 720 samples (2h @ 10s) ingested into a sharded in-memory store;
measures end-to-end PromQL range-query throughput for
``sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))`` (the north-star shape)
— full path: index lookup → chunk decode → batch build → jitted TPU kernels →
aggregated result.

vs_baseline: ratio against an in-process naive per-sample sliding-window
evaluation of the same queries (the reference engine's iteration strategy,
``PeriodicSamplesMapper``/``RangeFunction`` — measured here in numpy/python on
CPU since the JVM reference can't run in this image).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _ensure_backend(probe_timeout_s: int = 600) -> str:
    """Probe the configured accelerator in a subprocess; fall back to CPU if
    backend init doesn't complete (the TPU tunnel can be down) so the bench
    always reports a number."""
    if os.environ.get("FILODB_BENCH_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    try:
        subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); import jax.numpy as jnp; "
             "jnp.arange(4).sum().block_until_ready()"],
            check=True, timeout=probe_timeout_s, capture_output=True)
        import jax
        return jax.devices()[0].platform
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
        sys.stderr.write(f"accelerator probe failed ({type(e).__name__}); "
                         "falling back to CPU\n")
        import jax
        jax.config.update("jax_platforms", "cpu")
        return "cpu"

NUM_SHARDS = 8
NUM_SERIES = 100
NUM_SAMPLES = 720
INTERVAL_MS = 10_000
START_SEC = 1_600_000_000
QUERY = 'sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))'
QUERY_STEP_SEC = 60
N_QUERIES = 100
N_WARMUP = 3


def build_service():
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_stream, counter_series

    keys = counter_series(NUM_SERIES, metric="heap_usage", ns="App-2")
    stream = counter_stream(keys, NUM_SAMPLES, start_ms=START_SEC * 1000,
                            interval_ms=INTERVAL_MS, seed=42)
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=400,
                                              groups_per_shard=8))
    n = ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    assert n == NUM_SERIES * NUM_SAMPLES, n
    return QueryService(ms, "timeseries", NUM_SHARDS, spread=1), keys


def run_queries(svc, n, start_sec, end_sec):
    t0 = time.perf_counter()
    for i in range(n):
        r = svc.query_range(QUERY, start_sec, QUERY_STEP_SEC, end_sec)
        assert r.result.num_series == 1
    return n / (time.perf_counter() - t0)


def naive_baseline_qps(svc, start_sec, end_sec, n_iters=5):
    """Per-sample sliding-window evaluation (the reference's strategy) over
    the same decoded data, including the same index lookup + decode path."""
    from filodb_tpu.core.filters import ColumnFilter, Equals

    filters = [ColumnFilter("_metric_", Equals("heap_usage")),
               ColumnFilter("_ws_", Equals("demo")),
               ColumnFilter("_ns_", Equals("App-2"))]
    window = 300_000
    t0 = time.perf_counter()
    for _ in range(n_iters):
        steps = np.arange(start_sec * 1000, end_sec * 1000 + 1,
                          QUERY_STEP_SEC * 1000)
        total = np.zeros(len(steps))
        count = np.zeros(len(steps), dtype=int)
        for shard in svc.memstore.shards_for("timeseries"):
            for pid in shard.lookup_partitions(
                    filters, start_sec * 1000 - window, end_sec * 1000):
                part = shard.partition(pid)
                t, v = part.read_samples(start_sec * 1000 - window,
                                         end_sec * 1000)
                for k, te in enumerate(steps):
                    m = (t > te - window) & (t <= te)
                    wt, wv = t[m], v[m]
                    if len(wt) < 2:
                        continue
                    corr = np.concatenate(
                        [[0.0], np.cumsum(np.where(np.diff(wv) < 0,
                                                   wv[:-1], 0.0))])
                    cv = wv + corr
                    inc = cv[-1] - cv[0]
                    sampled = (wt[-1] - wt[0]) / 1000.0
                    avg_dur = sampled / (len(wt) - 1)
                    ds = (wt[0] - (te - window)) / 1000.0
                    de = (te - wt[-1]) / 1000.0
                    if inc > 0:
                        ds = min(ds, sampled * wv[0] / inc)
                    th = avg_dur * 1.1
                    ext = sampled + (ds if ds < th else avg_dur / 2) \
                        + (de if de < th else avg_dur / 2)
                    total[k] += inc * (ext / sampled) / (window / 1000.0)
                    count[k] += 1
    return n_iters / (time.perf_counter() - t0)


def main():
    platform = _ensure_backend()
    sys.stderr.write(f"bench backend: {platform}\n")
    svc, _ = build_service()
    start_sec = START_SEC + 1800
    end_sec = START_SEC + 1800 + 30 * 60  # 30-min range, 31 steps

    run_queries(svc, N_WARMUP, start_sec, end_sec)  # compile + warm caches
    qps = run_queries(svc, N_QUERIES, start_sec, end_sec)
    baseline = naive_baseline_qps(svc, start_sec, end_sec)

    print(json.dumps({
        "metric": "promql_sum_rate_range_query_throughput",
        "value": round(qps, 2),
        "unit": "queries/sec",
        "vs_baseline": round(qps / baseline, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
