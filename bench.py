"""Benchmark: the reference's QueryInMemoryBenchmark workload on TPU.

Reproduces the workload of
``jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:31-35,126-130``:
100 series × 720 samples (2h @ 10s) ingested into a sharded in-memory store;
measures end-to-end PromQL range-query throughput for
``sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))`` (the north-star shape)
— full path: index lookup → chunk decode → batch build → jitted TPU kernels →
aggregated result.

Also reports a device-kernel microbench — bit-packed device-page decode →
counter-corrected rate → label-grouped segment sum, the fused hot loop — with
samples/s and an effective-HBM-bandwidth estimate, so there is a pure device
number even when the end-to-end path is host-bound.

vs_baseline: ratio against an in-process naive per-sample sliding-window
evaluation of the same queries (the reference engine's iteration strategy,
``PeriodicSamplesMapper``/``RangeFunction`` — measured here in numpy/python on
CPU since the JVM reference can't run in this image).

The accelerator probe retries with backoff (the TPU tunnel flaps); every
attempt is recorded with a timestamp in the emitted JSON under ``probe`` so a
CPU fallback is auditable. Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline", "platform", "probe",
 "kernel_microbench"}.
"""

import datetime
import json
import os
import subprocess
import sys
import time

import numpy as np

PROBE_CMD = ("import jax; d = jax.devices(); "
             "import jax.numpy as jnp; "
             "jnp.arange(4).sum().block_until_ready(); "
             "print(d[0].platform)")


def _probe_once(timeout_s: int):
    """Probe the configured accelerator in a subprocess (a hung tunnel init
    must never wedge the bench process itself). Returns (platform|None,
    attempt_record)."""
    t0 = time.time()
    rec = {"at": datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")}
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_CMD],
            check=True, timeout=timeout_s, capture_output=True, text=True)
        plat = out.stdout.strip().splitlines()[-1]
        rec.update(outcome="ok", platform=plat,
                   elapsed_s=round(time.time() - t0, 1))
        return plat, rec
    except subprocess.TimeoutExpired:
        rec.update(outcome="timeout", elapsed_s=round(time.time() - t0, 1))
        return None, rec
    except subprocess.CalledProcessError as e:
        tail = (e.stderr or "").strip().splitlines()[-1:] or [""]
        rec.update(outcome="error", elapsed_s=round(time.time() - t0, 1),
                   detail=tail[0][:200])
        return None, rec


PROBE_CACHE_PATH = os.environ.get("FILODB_PROBE_CACHE",
                                  "/tmp/filodb_probe_cache.json")
PROBE_CACHE_TTL_S = int(os.environ.get("FILODB_PROBE_CACHE_TTL", "3600"))
# total wall-clock allowed for probing (attempts + backoffs): BENCH_r05
# burned ~16 minutes on 4 consecutive 120-300s tunnel timeouts before the
# CPU fallback even started. The budget caps the worst case at one long
# attempt plus maybe a short retry; the outcome cache makes every later
# bench invocation (e.g. a --devices sweep's subprocesses) start instantly.
PROBE_BUDGET_S = float(os.environ.get("FILODB_BENCH_PROBE_BUDGET_S", "150"))


def _probe_cache_read(path: str = None, ttl_s: int = None):
    """Last probe outcome, or None when absent/stale/unreadable."""
    path = PROBE_CACHE_PATH if path is None else path
    ttl_s = PROBE_CACHE_TTL_S if ttl_s is None else ttl_s
    try:
        with open(path) as f:
            rec = json.load(f)
        if time.time() - float(rec["ts"]) > ttl_s:
            return None
        return rec
    except Exception:
        return None


def _probe_cache_write(platform, path: str = None) -> None:
    path = PROBE_CACHE_PATH if path is None else path
    try:
        with open(path, "w") as f:
            json.dump({"platform": platform, "ts": time.time()}, f)
    except OSError:
        pass


def _ensure_backend():
    """Probe with retries + backoff under a total time budget; fall back
    to CPU once the budget is spent, so a CPU-only box starts in seconds
    instead of minutes. The first decisive outcome (success or fallback)
    is cached on disk with a TTL, so repeated bench runs skip the probe
    entirely; the JSON probe log still records every attempt (or the cache
    hit) so a CPU fallback stays auditable."""
    if os.environ.get("FILODB_BENCH_CPU"):
        _force_cpu()
        return "cpu", [{"outcome": "skipped", "detail": "FILODB_BENCH_CPU"}]
    cached = _probe_cache_read()
    if cached is not None:
        plat = cached.get("platform")
        if plat is None or plat == "cpu":
            _force_cpu()
            return "cpu", [{"outcome": "cached", "platform": "cpu",
                            "detail": PROBE_CACHE_PATH}]
        return plat, [{"outcome": "cached", "platform": plat,
                       "detail": PROBE_CACHE_PATH}]
    attempts = int(os.environ.get("FILODB_BENCH_PROBE_ATTEMPTS", "4"))
    timeouts = [120, 240, 300, 300] + [300] * max(0, attempts - 4)
    backoffs = [20, 45, 90] + [120] * max(0, attempts - 4)
    deadline = time.time() + PROBE_BUDGET_S
    log = []
    for i in range(attempts):
        remaining = deadline - time.time()
        if remaining <= 1:
            log.append({"outcome": "budget_exhausted",
                        "detail": f"{PROBE_BUDGET_S:.0f}s probe budget"})
            break
        plat, rec = _probe_once(min(timeouts[i], int(remaining)))
        log.append(rec)
        if plat is not None:
            _probe_cache_write(plat)
            return plat, log
        sys.stderr.write(f"accelerator probe attempt {i + 1}/{attempts} "
                         f"failed ({rec['outcome']})\n")
        if i + 1 < attempts:
            backoff = backoffs[min(i, len(backoffs) - 1)]
            if time.time() + backoff >= deadline:
                log.append({"outcome": "budget_exhausted",
                            "detail": f"{PROBE_BUDGET_S:.0f}s probe budget"})
                break
            time.sleep(backoff)
    _force_cpu()
    _probe_cache_write("cpu")
    return "cpu", log


def _force_cpu():
    """CPU fallback that cannot hang on the axon tunnel: the axon PJRT
    factory (registered at interpreter start by sitecustomize) blocks every
    backend init while the tunnel is down, even with jax_platforms=cpu —
    drop it before the first backend initializes."""
    import jax
    import jax._src.xla_bridge as xb
    xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")


NUM_SHARDS = 8
NUM_SERIES = 100
NUM_SAMPLES = 720
INTERVAL_MS = 10_000
START_SEC = 1_600_000_000
QUERY = 'sum(rate(heap_usage{_ws_="demo",_ns_="App-2"}[5m]))'
QUERY_STEP_SEC = 60
N_QUERIES = 100
N_WARMUP = 3
# large-scan section: enough samples that the device lane wins end-to-end
# even through a high-latency tunnel (scan cost ≫ sync floor)
BIG_SERIES = 8192
BIG_SAMPLES = 1440  # 4h @ 10s per series
BIG_QUERY = 'sum(rate(big_counter[10m]))'
BIG_RANGE_SEC = 3 * 3600  # ~9.3M samples scanned per query


def config_default_engine() -> str:
    """The engine a default-config server actually ships with — the bench
    must measure the shape users get, not a hand-picked one."""
    from filodb_tpu.config import DEFAULTS
    return DEFAULTS["datasets"]["timeseries"].get("engine", "mesh")


def build_service(engine: str | None = None):
    engine = engine or config_default_engine()
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_stream, counter_series

    keys = counter_series(NUM_SERIES, metric="heap_usage", ns="App-2")
    stream = counter_stream(keys, NUM_SAMPLES, start_ms=START_SEC * 1000,
                            interval_ms=INTERVAL_MS, seed=42)
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=400,
                                              groups_per_shard=8))
    n = ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    assert n == NUM_SERIES * NUM_SAMPLES, n
    # adaptive two-lane engine (parallel/adaptive.py): device mesh for
    # batch/scan-heavy work, host lane for sync-floor-bound small queries,
    # cost-routed — the TPU-native serving posture behind any link
    return QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                        engine=engine), keys


def build_big_store():
    """Big-scan store, loaded via the bulk chunk path: per-sample Python
    ingest of ~12M records would dominate the bench's wall clock, and this
    section measures QUERY cost (the headline section exercises the real
    ingest path).

    Everything here is seeded/deterministic, so N mesh worker processes
    started with ``--seed bench:build_big_store`` rebuild bit-identical
    per-shard data — benchmarks/multiproc_mesh.py depends on that."""
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.memory.chunk import encode_chunk

    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=400,
                                              groups_per_shard=8,
                                              native_ingest=False))
    rng = np.random.default_rng(11)
    ts = START_SEC * 1000 + np.arange(BIG_SAMPLES, dtype=np.int64) \
        * INTERVAL_MS
    chunk = 400
    for i in range(BIG_SERIES):
        key = PartKey.create("prom-counter", {
            "_metric_": "big_counter", "_ws_": "demo", "_ns_": "Big",
            "instance": f"inst-{i}"})
        shard = ms.get_shard("timeseries", i % NUM_SHARDS)
        part = shard.get_or_create_partition(key, int(ts[0]))
        vals = np.cumsum(rng.integers(0, 20, BIG_SAMPLES)).astype(
            np.float64)
        for c0 in range(0, BIG_SAMPLES, chunk):
            c1 = min(c0 + chunk, BIG_SAMPLES)
            part.chunks.append(encode_chunk(
                part.schema, ts[c0:c1], [vals[c0:c1]], len(part.chunks)))
        shard.stats.rows_ingested.inc(BIG_SAMPLES)  # data_version stamp
    return ms


def build_big_service(engine: str):
    from filodb_tpu.coordinator.query_service import QueryService

    ms = build_big_store()
    return QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                        engine=engine)


def run_queries(svc, n, start_sec, end_sec):
    t0 = time.perf_counter()
    lats = []
    for i in range(n):
        q0 = time.perf_counter()
        r = svc.query_range(QUERY, start_sec, QUERY_STEP_SEC, end_sec)
        lats.append(time.perf_counter() - q0)
        assert r.result.num_series == 1
    qps = n / (time.perf_counter() - t0)
    lats.sort()
    p50 = lats[len(lats) // 2] * 1e3
    p99 = lats[min(int(len(lats) * 0.99), len(lats) - 1)] * 1e3
    return qps, p50, p99


def run_queries_concurrent(svc, n, start_sec, end_sec, workers=16):
    """Throughput with n queries in flight (the JMH workload shape: 100
    concurrent queries per measured op) — overlaps tunnel result fetches."""
    qs = [(QUERY, start_sec, QUERY_STEP_SEC, end_sec)] * n
    t0 = time.perf_counter()
    rs = svc.query_range_many(qs, workers=workers)
    dt = time.perf_counter() - t0
    assert all(r.result.num_series == 1 for r in rs)
    return n / dt


def run_queries_sustained(svc, start_sec, end_sec, threads=4, batch=25,
                          rounds=4):
    """Sustained serving throughput: ``threads`` submitters each pipeline
    ``rounds`` batches of ``batch`` queries (the JMH posture — multiple
    benchmark threads with many in-flight queries per op). Completion
    syncs of different passes overlap, so this measures steady-state
    throughput rather than one pass's latency."""
    import threading

    done = []

    def worker():
        c = 0
        for _ in range(rounds):
            qs = [(QUERY, start_sec, QUERY_STEP_SEC, end_sec)] * batch
            rs = svc.query_range_many(qs)
            assert all(r.result.num_series == 1 for r in rs)
            c += batch
        done.append(c)

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return sum(done) / (time.perf_counter() - t0)


def measure_big_scan():
    """End-to-end lane comparison at scan-heavy scale (~9M samples per
    query): here device compute dominates the sync floor, so the device
    lane must win END-TO-END, tunnel included — the complement of the
    small-scan workload where the floor dominates and the host lane wins."""
    from filodb_tpu.promql.parser import TimeStepParams

    svc = build_big_service("adaptive")
    start_sec = START_SEC + 3600
    end_sec = start_sec + BIG_RANGE_SEC
    eng = svc.mesh_engine
    out = {"engine": "adaptive",  # explicit: this IS the lane comparison
           "series": BIG_SERIES,
           "samples_per_query_approx":
               BIG_SERIES * (BIG_RANGE_SEC + 600) // 10}
    plan = svc._parse_cached(BIG_QUERY, TimeStepParams(
        start_sec, QUERY_STEP_SEC, end_sec))
    host = eng._host()
    lanes = {"device": eng.device_engine}
    if host is not None:
        lanes["host"] = host
    for lane_name, engine in lanes.items():
        lows = [engine._lower(plan)]
        if lows[0] is None:
            continue
        for _ in range(2):  # warm: compile + batch build + upload
            engine.execute_lowered_many(lows, svc.memstore,
                                        "timeseries")[0].materialize()
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            engine.execute_lowered_many(lows, svc.memstore,
                                        "timeseries")[0].materialize()
        out[f"{lane_name}_lane_ms_per_query"] = round(
            (time.perf_counter() - t0) / iters * 1e3, 1)
    d = out.get("device_lane_ms_per_query")
    h = out.get("host_lane_ms_per_query")
    if d and h:
        out["device_speedup_end_to_end"] = round(h / d, 2)
    return out


def naive_baseline_qps(svc, start_sec, end_sec, n_iters=5):
    """Per-sample sliding-window evaluation (the reference's strategy) over
    the same decoded data, including the same index lookup + decode path."""
    from filodb_tpu.core.filters import ColumnFilter, Equals

    filters = [ColumnFilter("_metric_", Equals("heap_usage")),
               ColumnFilter("_ws_", Equals("demo")),
               ColumnFilter("_ns_", Equals("App-2"))]
    window = 300_000
    t0 = time.perf_counter()
    for _ in range(n_iters):
        steps = np.arange(start_sec * 1000, end_sec * 1000 + 1,
                          QUERY_STEP_SEC * 1000)
        total = np.zeros(len(steps))
        count = np.zeros(len(steps), dtype=int)
        for shard in svc.memstore.shards_for("timeseries"):
            for pid in shard.lookup_partitions(
                    filters, start_sec * 1000 - window, end_sec * 1000):
                part = shard.partition(pid)
                t, v = part.read_samples(start_sec * 1000 - window,
                                         end_sec * 1000)
                for k, te in enumerate(steps):
                    m = (t > te - window) & (t <= te)
                    wt, wv = t[m], v[m]
                    if len(wt) < 2:
                        continue
                    corr = np.concatenate(
                        [[0.0], np.cumsum(np.where(np.diff(wv) < 0,
                                                   wv[:-1], 0.0))])
                    cv = wv + corr
                    inc = cv[-1] - cv[0]
                    sampled = (wt[-1] - wt[0]) / 1000.0
                    avg_dur = sampled / (len(wt) - 1)
                    ds = (wt[0] - (te - window)) / 1000.0
                    de = (te - wt[-1]) / 1000.0
                    if inc > 0:
                        ds = min(ds, sampled * wv[0] / inc)
                    th = avg_dur * 1.1
                    ext = sampled + (ds if ds < th else avg_dur / 2) \
                        + (de if de < th else avg_dur / 2)
                    total[k] += inc * (ext / sampled) / (window / 1000.0)
                    count[k] += 1
    return n_iters / (time.perf_counter() - t0)


def kernel_microbench(platform: str, iters: int = 50):
    """Pure device pipeline: bit-packed page decode → rate → segment_sum.

    Shapes follow ``__graft_entry__.entry()`` scaled up one notch (P=512
    series × ~4096 samples × K=128 steps) so the device sees real work.
    Reports fused-pipeline samples/s and an effective-HBM-bandwidth lower
    bound (packed input read + decoded [P,S] write+read once each).
    """
    import jax
    import jax.numpy as jnp
    from filodb_tpu.memory.device_pages import encode_f32_page, encode_ts_page
    from filodb_tpu.query.engine.aggregations import aggregate
    from filodb_tpu.query.engine.device_batch import (
        _assemble,
        pack_series_pages,
    )
    from filodb_tpu.query.engine.kernels import range_eval_masked

    P, S, K, G = 512, 4096, 128, 8
    rng = np.random.default_rng(7)
    per_series = []
    total_samples = 0
    for p in range(P):
        n = S - int(rng.integers(0, 128))
        ts = np.cumsum(rng.integers(8_000, 12_000, n)).astype(np.int64)
        vals = np.cumsum(rng.integers(0, 20, n)).astype(np.float64)
        per_series.append([(encode_ts_page(ts), encode_f32_page(vals),
                            n)])
        total_samples += n
    packed, counts = pack_series_pages(per_series, start=0)
    span = np.int32(int(12_000) * S + 1)
    gids = (np.arange(len(counts)) % G).astype(np.int32)
    last = int(min(c for c in counts if c)) * 8_000
    steps = np.linspace(last // 2, last, K).astype(np.int32)
    window = np.int32(300_000)

    packed_dev = [jnp.asarray(a) for a in packed]
    gids_d, steps_d = jnp.asarray(gids), jnp.asarray(steps)

    def fused(arrs, span_, gids_, steps_, window_):
        ts_d, vals_d, valid_d = _assemble(*arrs, span_)
        rate = range_eval_masked("rate", ts_d, vals_d, valid_d, steps_,
                                 window_, counter=True)
        return aggregate("sum", rate, gids_, G)

    jfused = jax.jit(fused)
    out = jfused(packed_dev, jnp.asarray(span), gids_d, steps_d,
                 jnp.asarray(window))
    out.block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfused(packed_dev, jnp.asarray(span), gids_d, steps_d,
                     jnp.asarray(window))
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters

    from filodb_tpu.memory.device_pages import BLOCK
    packed_bytes = sum(a.nbytes for a in packed)
    Pp, NB = int(packed[0].shape[0]), int(packed[0].shape[1])
    # decoded [P, NB*BLOCK]: int32 ts + f32 vals + bool valid, written then
    # read by the rate kernel → 2 passes
    decoded_bytes = Pp * NB * BLOCK * (4 + 4 + 1)
    traffic = packed_bytes + 2 * decoded_bytes
    v5e_peak_gb_s = 819.0
    gb_s = traffic / dt / 1e9
    out = {
        "shape": {"P": P, "S": S, "K": K, "G": G,
                  "total_samples": int(total_samples)},
        "fused_decode_rate_sum_ms": round(dt * 1000, 3),
        "samples_per_sec": int(total_samples / dt),
        "window_evals_per_sec": int(P * K / dt),
        "packed_mb": round(packed_bytes / 1e6, 1),
        "est_hbm_gb_s": round(gb_s, 1),
        "est_hbm_util_vs_v5e_pct": round(100 * gb_s / v5e_peak_gb_s, 1),
        "platform": platform,
    }
    if platform == "tpu":
        # hand-fused Pallas pipeline (decode+correct+window in VMEM, no
        # [P, S] HBM round trip): measured traffic = packed read + [P, K]
        # write. Interpret-mode-validated (tests/test_pallas_fused.py);
        # guarded — Mosaic lowering falls back to the XLA numbers above.
        try:
            from filodb_tpu.query.engine.pallas_kernels import (
                fused_decode_rate_pallas,
            )
            pf = jax.jit(lambda pk_, st_, w_: aggregate(
                "sum", fused_decode_rate_pallas(pk_, st_, w_), gids_d, G))
            o2 = pf(tuple(packed_dev), steps_d, jnp.asarray(window))
            o2.block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                o2 = pf(tuple(packed_dev), steps_d, jnp.asarray(window))
            o2.block_until_ready()
            dt2 = (time.perf_counter() - t0) / iters
            traffic2 = packed_bytes + Pp * K * 4
            gb2 = traffic2 / dt2 / 1e9
            out["pallas_fused_ms"] = round(dt2 * 1000, 3)
            out["pallas_fused_hbm_gb_s"] = round(gb2, 1)
            out["pallas_fused_hbm_util_vs_v5e_pct"] = round(
                100 * gb2 / v5e_peak_gb_s, 1)
            # cross-check the two pipelines agree on device
            ref = np.asarray(out_ := jfused(
                packed_dev, jnp.asarray(span), gids_d, steps_d,
                jnp.asarray(window)))
            del out_
            np.testing.assert_allclose(np.asarray(o2), ref, rtol=1e-3,
                                       atol=1e-5, equal_nan=True)
            out["pallas_fused_parity"] = "ok"
        except Exception as e:  # noqa: BLE001 — bench must not die on TPU
            out["pallas_fused_error"] = f"{type(e).__name__}: {e}"
    return out


def main():
    platform, probe_log = _ensure_backend()
    sys.stderr.write(f"bench backend: {platform}\n")

    micro = kernel_microbench(platform)
    sys.stderr.write(f"kernel microbench: {json.dumps(micro)}\n")

    engine = config_default_engine()
    sys.stderr.write(f"bench engine (config default): {engine}\n")
    svc, _ = build_service(engine)
    start_sec = START_SEC + 1800
    end_sec = START_SEC + 1800 + 30 * 60  # 30-min range, 31 steps

    run_queries(svc, N_WARMUP, start_sec, end_sec)  # compile + warm caches
    run_queries_concurrent(svc, N_QUERIES, start_sec, end_sec)  # batch compile
    seq_qps, p50_ms, p99_ms = run_queries(svc, N_QUERIES, start_sec, end_sec)
    conc_qps = run_queries_concurrent(svc, N_QUERIES, start_sec, end_sec)
    sustained_qps = run_queries_sustained(svc, start_sec, end_sec)
    qps = max(seq_qps, conc_qps, sustained_qps)
    baseline = naive_baseline_qps(svc, start_sec, end_sec)

    # device-timed breakdown (VERDICT r3 #1): where a single query's
    # latency goes, so the sync-floor-bound sequential number is
    # attributable — floor (one blocking host↔device round trip), pure
    # device kernel time (microbench), and the device lane's end-to-end
    # per-query cost as routed by the adaptive engine
    eng = svc.mesh_engine
    breakdown = {}
    if getattr(eng, "sync_floor_s", None) is not None:
        breakdown["sync_floor_ms"] = round(eng.sync_floor_s * 1e3, 2)
    breakdown["device_kernel_ms"] = micro.get("fused_decode_rate_sum_ms")
    if hasattr(eng, "_cost"):
        breakdown["lane_costs_ms_per_query"] = {
            f"{lane}_bs{b}": round(c.est * 1e3, 2)
            for (lane, b), c in eng._cost.items() if c.est is not None}
        breakdown["routed"] = dict(eng.routed)

    big = measure_big_scan()
    sys.stderr.write(f"big scan: {json.dumps(big)}\n")

    # Honest reference comparison: the JVM reference cannot run in this
    # image (no JVM/sbt, zero egress), so alongside the measured
    # naive-python ratio we publish an ESTIMATE of the reference engine's
    # throughput on this workload, derived in BENCH_LOCAL.md ("Honest
    # baseline"): decode-aware chunked iteration at 10-50ns/sample over
    # ~72k samples/query -> ~280-1400 q/s single-threaded JVM.
    ref_lo, ref_hi = 280, 1400
    print(json.dumps({
        "metric": "promql_sum_rate_range_query_throughput",
        "value": round(qps, 2),
        "unit": "queries/sec",
        "engine": engine,
        # headline comparison first: measured qps against the reasoned
        # JVM-engine estimate band for this exact workload
        "vs_reference_estimate": [round(qps / ref_hi, 2),
                                  round(qps / ref_lo, 2)],
        "reference_jvm_estimated_qps": [ref_lo, ref_hi],
        "sequential_qps": round(seq_qps, 2),
        "latency_p50_ms": round(p50_ms, 2),
        "latency_p99_ms": round(p99_ms, 2),
        "concurrent_qps": round(conc_qps, 2),
        "sustained_qps": round(sustained_qps, 2),
        "latency_breakdown": breakdown,
        "big_scan": big,
        "platform": platform,
        # secondary: ratio against naive per-sample numpy/python iteration
        # of the same queries (NOT the JVM engine)
        "vs_baseline": round(qps / baseline, 2),
        "baseline_note": ("vs_baseline = measured ratio against naive "
                          "per-sample numpy/python iteration; the "
                          "reference comparison is vs_reference_estimate "
                          "(BENCH_LOCAL.md)"),
        "probe": probe_log,
        "kernel_microbench": micro,
    }))


if __name__ == "__main__":
    sys.exit(main())
