// filodb_tpu native runtime: columnar codecs + block arena.
//
// Counterpart of the reference's off-heap "native tier"
// (memory/src/main/scala/filodb.memory: UnsafeUtils + jffi page allocation,
// NibblePack.scala, DeltaDeltaVector.scala, DoubleVector XOR encoding,
// BlockManager.scala) — here as real native code exposed through a C ABI
// consumed via ctypes. Byte-identical wire format with the numpy reference
// implementation in filodb_tpu/memory/nibblepack.py.
//
// Build: make -C native   (produces libfilodb_native.so)

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <atomic>
#include <initializer_list>
#include <new>

extern "C" {

// ---------------------------------------------------------------------------
// zigzag

void zigzag_encode_i64(const int64_t* in, uint64_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t v = in[i];
        out[i] = (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
    }
}

void zigzag_decode_u64(const uint64_t* in, int64_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = in[i];
        out[i] = static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
    }
}

// ---------------------------------------------------------------------------
// NibblePack (see filodb_tpu/memory/nibblepack.py for the format spec)

static inline int nibble_width(uint64_t x) {
    if (x == 0) return 1;
    return (64 - __builtin_clzll(x) + 3) / 4;
}

static inline int trailing_zero_nibbles(uint64_t x) {
    if (x == 0) return 16;
    return __builtin_ctzll(x) / 4;
}

// out must have capacity >= 2 + 8*9 bytes per group of 8 (worst case);
// returns bytes written.
int64_t nibble_pack(const uint64_t* vals, int64_t n, uint8_t* out) {
    uint8_t* p = out;
    for (int64_t g = 0; g < n; g += 8) {
        uint64_t group[8] = {0, 0, 0, 0, 0, 0, 0, 0};
        int64_t cnt = (n - g) < 8 ? (n - g) : 8;
        std::memcpy(group, vals + g, cnt * sizeof(uint64_t));
        uint8_t bitmap = 0;
        for (int i = 0; i < 8; i++)
            if (group[i]) bitmap |= (1u << i);
        *p++ = bitmap;
        if (!bitmap) continue;
        int tz = 16, lead = 1;
        for (int i = 0; i < 8; i++) {
            if (!group[i]) continue;
            int t = trailing_zero_nibbles(group[i]);
            if (t < tz) tz = t;
            int w = nibble_width(group[i]);
            if (w > lead) lead = w;
        }
        int num_nibbles = lead - tz;
        *p++ = static_cast<uint8_t>(((num_nibbles - 1) << 4) | tz);
        // pack nibbles little-endian across nonzero values (128-bit
        // accumulator: up to 64 value bits on top of <8 residual bits)
        unsigned __int128 acc = 0;
        int acc_bits = 0;
        uint64_t mask = (num_nibbles >= 16) ? ~0ULL
                        : ((1ULL << (4 * num_nibbles)) - 1);
        for (int i = 0; i < 8; i++) {
            if (!group[i]) continue;
            uint64_t x = (group[i] >> (4 * tz)) & mask;
            acc |= static_cast<unsigned __int128>(x) << acc_bits;
            acc_bits += 4 * num_nibbles;
            while (acc_bits >= 8) {
                *p++ = static_cast<uint8_t>(acc & 0xFF);
                acc >>= 8;
                acc_bits -= 8;
            }
        }
        if (acc_bits > 0) *p++ = static_cast<uint8_t>(acc & 0xFF);
    }
    return p - out;
}

// returns bytes consumed, or -1 on truncated input.
int64_t nibble_unpack(const uint8_t* in, int64_t in_len, uint64_t* out,
                      int64_t count) {
    const uint8_t* p = in;
    const uint8_t* end = in + in_len;
    int64_t idx = 0;
    while (idx < count) {
        if (p >= end) return -1;
        uint8_t bitmap = *p++;
        if (!bitmap) {
            for (int i = 0; i < 8 && idx + i < count; i++) out[idx + i] = 0;
            idx += 8;
            continue;
        }
        if (p >= end) return -1;
        uint8_t desc = *p++;
        int num_nibbles = (desc >> 4) + 1;
        int tz = desc & 0xF;
        int nnz = __builtin_popcount(bitmap);
        int64_t nbytes = (static_cast<int64_t>(nnz) * num_nibbles + 1) / 2;
        if (p + nbytes > end) return -1;
        uint64_t mask = (num_nibbles >= 16) ? ~0ULL
                        : ((1ULL << (4 * num_nibbles)) - 1);
        // stream nibbles from the byte stream (128-bit accumulator)
        unsigned __int128 acc = 0;
        int acc_bits = 0;
        const uint8_t* q = p;
        for (int i = 0; i < 8; i++) {
            uint64_t v = 0;
            if (bitmap & (1u << i)) {
                while (acc_bits < 4 * num_nibbles && q < p + nbytes) {
                    acc |= static_cast<unsigned __int128>(*q++) << acc_bits;
                    acc_bits += 8;
                }
                v = (static_cast<uint64_t>(acc) & mask) << (4 * tz);
                acc >>= 4 * num_nibbles;
                acc_bits -= 4 * num_nibbles;
            }
            if (idx + i < count) out[idx + i] = v;
        }
        p += nbytes;
        idx += 8;
    }
    return p - in;
}

// ---------------------------------------------------------------------------
// murmur3-32 (x86 variant) — partition-key hashing (reference uses Murmur3
// for BinaryRecord partition hashes; python-side fallback matches bit-exact)

uint32_t murmur3_32(const uint8_t* data, int64_t n, uint32_t seed) {
    const uint32_t c1 = 0xCC9E2D51u, c2 = 0x1B873593u;
    uint32_t h = seed;
    int64_t rounded = n & ~3LL;
    for (int64_t i = 0; i < rounded; i += 4) {
        uint32_t k;
        std::memcpy(&k, data + i, 4);
        k *= c1;
        k = (k << 15) | (k >> 17);
        k *= c2;
        h ^= k;
        h = (h << 13) | (h >> 19);
        h = h * 5 + 0xE6546B64u;
    }
    uint32_t k = 0;
    int64_t tail = n - rounded;
    if (tail >= 3) k ^= static_cast<uint32_t>(data[rounded + 2]) << 16;
    if (tail >= 2) k ^= static_cast<uint32_t>(data[rounded + 1]) << 8;
    if (tail >= 1) {
        k ^= data[rounded];
        k *= c1;
        k = (k << 15) | (k >> 17);
        k *= c2;
        h ^= k;
    }
    h ^= static_cast<uint32_t>(n);
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

// ---------------------------------------------------------------------------
// XOR-double prep

void xor_encode_f64(const double* in, uint64_t* out, int64_t n) {
    uint64_t prev = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t bits;
        std::memcpy(&bits, &in[i], 8);
        out[i] = bits ^ prev;
        prev = bits;
    }
}

void xor_decode_f64(const uint64_t* in, double* out, int64_t n) {
    uint64_t acc = 0;
    for (int64_t i = 0; i < n; i++) {
        acc ^= in[i];
        std::memcpy(&out[i], &acc, 8);
    }
}

// ---------------------------------------------------------------------------
// delta-delta helpers (sloped-line predictor residuals)

// residual[i] = v[i] - (base + slope*i); returns 1 if all residuals zero
int delta_delta_residuals(const int64_t* in, int64_t n, int64_t base,
                          int64_t slope, int64_t* out) {
    int all_zero = 1;
    for (int64_t i = 0; i < n; i++) {
        out[i] = in[i] - (base + slope * i);
        if (out[i] != 0) all_zero = 0;
    }
    return all_zero;
}

void delta_delta_reconstruct(const int64_t* resid, int64_t n, int64_t base,
                             int64_t slope, int64_t* out) {
    for (int64_t i = 0; i < n; i++) out[i] = base + slope * i + resid[i];
}

// ---------------------------------------------------------------------------
// Block arena (reference BlockManager/PageAlignedBlockManager semantics:
// fixed-size page-aligned blocks, owner-tagged, reclaimable lists, stats)

struct Block {
    uint8_t* data;
    int64_t size;
    int64_t used;
    int64_t owner;
    Block* next;
};

struct Arena {
    int64_t block_size;
    std::atomic<int64_t> allocated_blocks;
    std::atomic<int64_t> reclaimed_blocks;
    std::atomic<int64_t> bytes_in_use;
    Block* free_list;
    Block* used_list;
};

void* arena_create(int64_t block_size) {
    Arena* a = new (std::nothrow) Arena();
    if (!a) return nullptr;
    a->block_size = block_size;
    a->allocated_blocks = 0;
    a->reclaimed_blocks = 0;
    a->bytes_in_use = 0;
    a->free_list = nullptr;
    a->used_list = nullptr;
    return a;
}

// allocate one block for an owner; returns block handle (or null)
void* arena_alloc_block(void* arena, int64_t owner) {
    Arena* a = static_cast<Arena*>(arena);
    Block* b = a->free_list;
    if (b) {
        a->free_list = b->next;
    } else {
        b = new (std::nothrow) Block();
        if (!b) return nullptr;
        // page-aligned like the reference's PageAlignedBlockManager
        if (posix_memalign(reinterpret_cast<void**>(&b->data), 4096,
                           a->block_size) != 0) {
            delete b;
            return nullptr;
        }
        b->size = a->block_size;
        a->allocated_blocks++;
    }
    b->used = 0;
    b->owner = owner;
    b->next = a->used_list;
    a->used_list = b;
    a->bytes_in_use += a->block_size;
    return b;
}

// bump-allocate within a block; returns offset or -1 when full
int64_t block_alloc(void* block, int64_t nbytes) {
    Block* b = static_cast<Block*>(block);
    int64_t aligned = (nbytes + 7) & ~7LL;
    if (b->used + aligned > b->size) return -1;
    int64_t off = b->used;
    b->used += aligned;
    return off;
}

uint8_t* block_data(void* block) { return static_cast<Block*>(block)->data; }
int64_t block_remaining(void* block) {
    Block* b = static_cast<Block*>(block);
    return b->size - b->used;
}

// reclaim all blocks of an owner back to the free list; returns count
int64_t arena_reclaim_owner(void* arena, int64_t owner) {
    Arena* a = static_cast<Arena*>(arena);
    Block** prev = &a->used_list;
    int64_t n = 0;
    while (*prev) {
        Block* b = *prev;
        if (b->owner == owner) {
            *prev = b->next;
            b->next = a->free_list;
            a->free_list = b;
            a->bytes_in_use -= a->block_size;
            a->reclaimed_blocks++;
            n++;
        } else {
            prev = &b->next;
        }
    }
    return n;
}

int64_t arena_stats(void* arena, int64_t which) {
    Arena* a = static_cast<Arena*>(arena);
    switch (which) {
        case 0: return a->allocated_blocks.load();
        case 1: return a->reclaimed_blocks.load();
        case 2: return a->bytes_in_use.load();
        default: return -1;
    }
}

void arena_destroy(void* arena) {
    Arena* a = static_cast<Arena*>(arena);
    for (Block* l : {a->free_list, a->used_list}) {
        while (l) {
            Block* nxt = l->next;
            std::free(l->data);
            delete l;
            l = nxt;
        }
    }
    delete a;
}

}  // extern "C"
