// filodb_tpu native runtime: columnar codecs + block arena.
//
// Counterpart of the reference's off-heap "native tier"
// (memory/src/main/scala/filodb.memory: UnsafeUtils + jffi page allocation,
// NibblePack.scala, DeltaDeltaVector.scala, DoubleVector XOR encoding,
// BlockManager.scala) — here as real native code exposed through a C ABI
// consumed via ctypes. Byte-identical wire format with the numpy reference
// implementation in filodb_tpu/memory/nibblepack.py.
//
// Build: make -C native   (produces libfilodb_native.so)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <atomic>
#include <deque>
#include <initializer_list>
#include <limits>
#include <new>
#include <string>
#include <string_view>
#include <memory>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// zigzag

void zigzag_encode_i64(const int64_t* in, uint64_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t v = in[i];
        out[i] = (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
    }
}

void zigzag_decode_u64(const uint64_t* in, int64_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = in[i];
        out[i] = static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
    }
}

// ---------------------------------------------------------------------------
// NibblePack (see filodb_tpu/memory/nibblepack.py for the format spec)

static inline int nibble_width(uint64_t x) {
    if (x == 0) return 1;
    return (64 - __builtin_clzll(x) + 3) / 4;
}

static inline int trailing_zero_nibbles(uint64_t x) {
    if (x == 0) return 16;
    return __builtin_ctzll(x) / 4;
}

// out must have capacity >= 2 + 8*9 bytes per group of 8 (worst case);
// returns bytes written.
int64_t nibble_pack(const uint64_t* vals, int64_t n, uint8_t* out) {
    uint8_t* p = out;
    for (int64_t g = 0; g < n; g += 8) {
        uint64_t group[8] = {0, 0, 0, 0, 0, 0, 0, 0};
        int64_t cnt = (n - g) < 8 ? (n - g) : 8;
        std::memcpy(group, vals + g, cnt * sizeof(uint64_t));
        uint8_t bitmap = 0;
        for (int i = 0; i < 8; i++)
            if (group[i]) bitmap |= (1u << i);
        *p++ = bitmap;
        if (!bitmap) continue;
        int tz = 16, lead = 1;
        for (int i = 0; i < 8; i++) {
            if (!group[i]) continue;
            int t = trailing_zero_nibbles(group[i]);
            if (t < tz) tz = t;
            int w = nibble_width(group[i]);
            if (w > lead) lead = w;
        }
        int num_nibbles = lead - tz;
        *p++ = static_cast<uint8_t>(((num_nibbles - 1) << 4) | tz);
        // pack nibbles little-endian across nonzero values (128-bit
        // accumulator: up to 64 value bits on top of <8 residual bits)
        unsigned __int128 acc = 0;
        int acc_bits = 0;
        uint64_t mask = (num_nibbles >= 16) ? ~0ULL
                        : ((1ULL << (4 * num_nibbles)) - 1);
        for (int i = 0; i < 8; i++) {
            if (!group[i]) continue;
            uint64_t x = (group[i] >> (4 * tz)) & mask;
            acc |= static_cast<unsigned __int128>(x) << acc_bits;
            acc_bits += 4 * num_nibbles;
            while (acc_bits >= 8) {
                *p++ = static_cast<uint8_t>(acc & 0xFF);
                acc >>= 8;
                acc_bits -= 8;
            }
        }
        if (acc_bits > 0) *p++ = static_cast<uint8_t>(acc & 0xFF);
    }
    return p - out;
}

// returns bytes consumed, or -1 on truncated input.
int64_t nibble_unpack(const uint8_t* in, int64_t in_len, uint64_t* out,
                      int64_t count) {
    const uint8_t* p = in;
    const uint8_t* end = in + in_len;
    int64_t idx = 0;
    while (idx < count) {
        if (p >= end) return -1;
        uint8_t bitmap = *p++;
        if (!bitmap) {
            for (int i = 0; i < 8 && idx + i < count; i++) out[idx + i] = 0;
            idx += 8;
            continue;
        }
        if (p >= end) return -1;
        uint8_t desc = *p++;
        int num_nibbles = (desc >> 4) + 1;
        int tz = desc & 0xF;
        int nnz = __builtin_popcount(bitmap);
        int64_t nbytes = (static_cast<int64_t>(nnz) * num_nibbles + 1) / 2;
        if (p + nbytes > end) return -1;
        uint64_t mask = (num_nibbles >= 16) ? ~0ULL
                        : ((1ULL << (4 * num_nibbles)) - 1);
        // stream nibbles from the byte stream (128-bit accumulator)
        unsigned __int128 acc = 0;
        int acc_bits = 0;
        const uint8_t* q = p;
        for (int i = 0; i < 8; i++) {
            uint64_t v = 0;
            if (bitmap & (1u << i)) {
                while (acc_bits < 4 * num_nibbles && q < p + nbytes) {
                    acc |= static_cast<unsigned __int128>(*q++) << acc_bits;
                    acc_bits += 8;
                }
                v = (static_cast<uint64_t>(acc) & mask) << (4 * tz);
                acc >>= 4 * num_nibbles;
                acc_bits -= 4 * num_nibbles;
            }
            if (idx + i < count) out[idx + i] = v;
        }
        p += nbytes;
        idx += 8;
    }
    return p - in;
}

// ---------------------------------------------------------------------------
// murmur3-32 (x86 variant) — partition-key hashing (reference uses Murmur3
// for BinaryRecord partition hashes; python-side fallback matches bit-exact)

uint32_t murmur3_32(const uint8_t* data, int64_t n, uint32_t seed) {
    const uint32_t c1 = 0xCC9E2D51u, c2 = 0x1B873593u;
    uint32_t h = seed;
    int64_t rounded = n & ~3LL;
    for (int64_t i = 0; i < rounded; i += 4) {
        uint32_t k;
        std::memcpy(&k, data + i, 4);
        k *= c1;
        k = (k << 15) | (k >> 17);
        k *= c2;
        h ^= k;
        h = (h << 13) | (h >> 19);
        h = h * 5 + 0xE6546B64u;
    }
    uint32_t k = 0;
    int64_t tail = n - rounded;
    if (tail >= 3) k ^= static_cast<uint32_t>(data[rounded + 2]) << 16;
    if (tail >= 2) k ^= static_cast<uint32_t>(data[rounded + 1]) << 8;
    if (tail >= 1) {
        k ^= data[rounded];
        k *= c1;
        k = (k << 15) | (k >> 17);
        k *= c2;
        h ^= k;
    }
    h ^= static_cast<uint32_t>(n);
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

// ---------------------------------------------------------------------------
// XOR-double prep

void xor_encode_f64(const double* in, uint64_t* out, int64_t n) {
    uint64_t prev = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t bits;
        std::memcpy(&bits, &in[i], 8);
        out[i] = bits ^ prev;
        prev = bits;
    }
}

void xor_decode_f64(const uint64_t* in, double* out, int64_t n) {
    uint64_t acc = 0;
    for (int64_t i = 0; i < n; i++) {
        acc ^= in[i];
        std::memcpy(&out[i], &acc, 8);
    }
}

// ---------------------------------------------------------------------------
// delta-delta helpers (sloped-line predictor residuals)

// residual[i] = v[i] - (base + slope*i); returns 1 if all residuals zero
int delta_delta_residuals(const int64_t* in, int64_t n, int64_t base,
                          int64_t slope, int64_t* out) {
    int all_zero = 1;
    for (int64_t i = 0; i < n; i++) {
        out[i] = in[i] - (base + slope * i);
        if (out[i] != 0) all_zero = 0;
    }
    return all_zero;
}

void delta_delta_reconstruct(const int64_t* resid, int64_t n, int64_t base,
                             int64_t slope, int64_t* out) {
    for (int64_t i = 0; i < n; i++) out[i] = base + slope * i + resid[i];
}

// ---------------------------------------------------------------------------
// Block arena (reference BlockManager/PageAlignedBlockManager semantics:
// fixed-size page-aligned blocks, owner-tagged, reclaimable lists, stats)

struct Block {
    uint8_t* data;
    int64_t size;
    int64_t used;
    int64_t owner;
    Block* next;
};

struct Arena {
    int64_t block_size;
    std::atomic<int64_t> allocated_blocks;
    std::atomic<int64_t> reclaimed_blocks;
    std::atomic<int64_t> bytes_in_use;
    Block* free_list;
    Block* used_list;
};

void* arena_create(int64_t block_size) {
    Arena* a = new (std::nothrow) Arena();
    if (!a) return nullptr;
    a->block_size = block_size;
    a->allocated_blocks = 0;
    a->reclaimed_blocks = 0;
    a->bytes_in_use = 0;
    a->free_list = nullptr;
    a->used_list = nullptr;
    return a;
}

// allocate one block for an owner; returns block handle (or null)
void* arena_alloc_block(void* arena, int64_t owner) {
    Arena* a = static_cast<Arena*>(arena);
    Block* b = a->free_list;
    if (b) {
        a->free_list = b->next;
    } else {
        b = new (std::nothrow) Block();
        if (!b) return nullptr;
        // page-aligned like the reference's PageAlignedBlockManager
        if (posix_memalign(reinterpret_cast<void**>(&b->data), 4096,
                           a->block_size) != 0) {
            delete b;
            return nullptr;
        }
        b->size = a->block_size;
        a->allocated_blocks++;
    }
    b->used = 0;
    b->owner = owner;
    b->next = a->used_list;
    a->used_list = b;
    a->bytes_in_use += a->block_size;
    return b;
}

// bump-allocate within a block; returns offset or -1 when full
int64_t block_alloc(void* block, int64_t nbytes) {
    Block* b = static_cast<Block*>(block);
    int64_t aligned = (nbytes + 7) & ~7LL;
    if (b->used + aligned > b->size) return -1;
    int64_t off = b->used;
    b->used += aligned;
    return off;
}

uint8_t* block_data(void* block) { return static_cast<Block*>(block)->data; }
int64_t block_remaining(void* block) {
    Block* b = static_cast<Block*>(block);
    return b->size - b->used;
}

// reclaim all blocks of an owner back to the free list; returns count
int64_t arena_reclaim_owner(void* arena, int64_t owner) {
    Arena* a = static_cast<Arena*>(arena);
    Block** prev = &a->used_list;
    int64_t n = 0;
    while (*prev) {
        Block* b = *prev;
        if (b->owner == owner) {
            *prev = b->next;
            b->next = a->free_list;
            a->free_list = b;
            a->bytes_in_use -= a->block_size;
            a->reclaimed_blocks++;
            n++;
        } else {
            prev = &b->next;
        }
    }
    return n;
}

int64_t arena_stats(void* arena, int64_t which) {
    Arena* a = static_cast<Arena*>(arena);
    switch (which) {
        case 0: return a->allocated_blocks.load();
        case 1: return a->reclaimed_blocks.load();
        case 2: return a->bytes_in_use.load();
        default: return -1;
    }
}

void arena_destroy(void* arena) {
    Arena* a = static_cast<Arena*>(arena);
    for (Block* l : {a->free_list, a->used_list}) {
        while (l) {
            Block* nxt = l->next;
            std::free(l->data);
            delete l;
            l = nxt;
        }
    }
    delete a;
}

// ---------------------------------------------------------------------------
// Shard ingest core — the native hot loop.
//
// Counterpart of the reference's per-shard ingest path
// (core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:570 →
// TimeSeriesPartition.scala:137 appenders over off-heap buffers): parses
// binary RecordContainer v2 bytes directly (no per-record host-language
// objects), looks partitions up in a native hash map keyed by the canonical
// part-key bytes, appends to growable columnar buffers, and seals full
// buffers into encoded chunks (delta-delta timestamps + XOR-double values,
// byte-identical to the numpy codecs) — the Python layer only sees whole
// sealed chunks and partition-creation events.

namespace {

struct NSealed {
    int64_t id, start, end;
    int32_t nrows;
    std::string ts_bytes;
    std::vector<std::string> col_bytes;
};

// first-class histogram state lives in a side table keyed by pid: only
// histogram partitions pay for it, keeping sizeof(NPart) lean for the
// 1M-series scalar case (the ZeroCopyUTF8String-era memory discipline)
struct HistState {
    int32_t nb = 0;
    std::vector<double> les;
    std::vector<int64_t> rows;  // ts.size() x nb, row-major
};

struct NPart {
    // canonical key bytes (schema_id + label blob) interned in the core's
    // append-only key arena — one copy total (NPart and the by_key map
    // both view it); the reference's zero-copy label tier analog
    std::string_view key;
    uint32_t hash = 0;
    bool alive = true;
    int64_t floor_ts = -1;   // dedup floor (recovery / eviction)
    int64_t first_ts = -1;
    int32_t seq = 0;
    int64_t flushed_id = -1;
    int64_t version = 0;     // bumped on seal/evict (python cache key)
    int64_t samples_sealed = 0;
    std::vector<int64_t> ts;
    std::vector<std::vector<double>> cols;
    std::vector<NSealed> sealed;
    // >=0: the schema column index of this partition's histogram column;
    // bucket state in ShardCore::hist. The cols[] slot carries NaN
    // placeholders so shape invariants (lockstep growth, buf copy) hold.
    int32_t hist_col = -1;

    int64_t latest() const {
        int64_t t = floor_ts;
        if (!ts.empty()) {
            if (ts.back() > t) t = ts.back();
        } else if (!sealed.empty()) {
            if (sealed.back().end > t) t = sealed.back().end;
        }
        return t;
    }
};

struct ShardCore {
    int32_t max_chunk;
    int32_t groups;
    std::vector<int64_t> watermarks;
    std::unordered_map<std::string_view, int32_t> by_key;
    std::deque<NPart> parts;  // stable references; index == pid
    std::unordered_map<int32_t, HistState> hist;  // pid -> hist state
    std::vector<int32_t> new_parts;
    int64_t rows_skipped = 0, rows_ooo = 0, rows_ingested = 0;
    int64_t rows_incompat = 0;  // value shape mismatched the partition
    // key arena: append-only stable storage for interned key bytes (block
    // pointers never move; views into blocks stay valid for the core's
    // lifetime — freed partitions leave small holes until destruction)
    std::vector<std::unique_ptr<char[]>> key_blocks;
    size_t key_block_used = 0;
    // encode scratch (single-writer per shard)
    std::vector<int64_t> resid;
    std::vector<uint64_t> words;
    std::vector<uint8_t> packed;

    static constexpr size_t KEY_BLOCK = 1 << 18;

    std::string_view intern_key(const char* d, size_t len) {
        if (key_blocks.empty()
            || key_block_used + len > KEY_BLOCK) {
            size_t cap = len > KEY_BLOCK ? len : KEY_BLOCK;
            key_blocks.emplace_back(new char[cap]);
            key_block_used = 0;
        }
        char* dst = key_blocks.back().get() + key_block_used;
        std::memcpy(dst, d, len);
        key_block_used += len;
        return std::string_view(dst, len);
    }
};

inline uint16_t rd_u16(const uint8_t* p) {
    uint16_t v; std::memcpy(&v, p, 2); return v;
}
inline uint32_t rd_u32(const uint8_t* p) {
    uint32_t v; std::memcpy(&v, p, 4); return v;
}
inline int64_t rd_i64(const uint8_t* p) {
    int64_t v; std::memcpy(&v, p, 8); return v;
}

inline int64_t floordiv_i64(int64_t a, int64_t b) {
    int64_t q = a / b, r = a % b;
    return (r != 0 && ((r < 0) != (b < 0))) ? q - 1 : q;
}

// delta-delta codec, byte-identical to codecs.encode_delta_delta:
// u8 codec | u32 n | i64 base | i64 slope [| nibble_pack(zigzag(resid))]
void encode_dd(ShardCore* c, const int64_t* v, int64_t n, std::string& out) {
    int64_t base = n ? v[0] : 0;
    int64_t slope = n > 1 ? floordiv_i64(v[n - 1] - base, n - 1) : 0;
    c->resid.resize(n);
    int all_zero = delta_delta_residuals(v, n, base, slope, c->resid.data());
    uint8_t head[21];
    head[0] = (n && !all_zero) ? 1 : 2;  // CODEC_DELTA_DELTA(_CONST)
    uint32_t n32 = (uint32_t)n;
    std::memcpy(head + 1, &n32, 4);
    std::memcpy(head + 5, &base, 8);
    std::memcpy(head + 13, &slope, 8);
    out.assign((char*)head, 21);
    if (n && !all_zero) {
        c->words.resize(n);
        zigzag_encode_i64(c->resid.data(), c->words.data(), n);
        c->packed.resize(16 + n * 9 + 64);
        int64_t m = nibble_pack(c->words.data(), n, c->packed.data());
        out.append((char*)c->packed.data(), m);
    }
}

// XOR-double codec, byte-identical to codecs.encode_xor_double:
// u8 codec=3 | u32 n | nibble_pack(xor-prep)
void encode_xor(ShardCore* c, const double* v, int64_t n, std::string& out) {
    uint8_t head[5];
    head[0] = 3;
    uint32_t n32 = (uint32_t)n;
    std::memcpy(head + 1, &n32, 4);
    out.assign((char*)head, 5);
    c->words.resize(n);
    xor_encode_f64(v, c->words.data(), n);
    c->packed.resize(16 + n * 9 + 64);
    int64_t m = nibble_pack(c->words.data(), n, c->packed.data());
    out.append((char*)c->packed.data(), m);
}

// Hist-2D-delta codec, byte-identical to codecs.encode_hist_2d_delta:
// u8 codec=4 | u32 n | u32 nb | f64*nb les | nibble_pack(zigzag(
//   delta-across-time(delta-across-buckets(rows))))
void encode_hist2d(ShardCore* c, const HistState& hs, int64_t n,
                   std::string& out) {
    uint32_t nb = (uint32_t)hs.nb;
    uint8_t head[9];
    head[0] = 4;
    uint32_t n32 = (uint32_t)n;
    std::memcpy(head + 1, &n32, 4);
    std::memcpy(head + 5, &nb, 4);
    out.assign((char*)head, 9);
    out.append((const char*)hs.les.data(), (size_t)nb * 8);
    int64_t total = n * (int64_t)nb;
    if (!total) return;
    c->resid.resize(total);
    const int64_t* r = hs.rows.data();
    for (int64_t i = 0; i < n; i++) {
        for (int64_t j = 0; j < (int64_t)nb; j++) {
            int64_t bd = r[i * nb + j] - (j ? r[i * nb + j - 1] : 0);
            int64_t pbd = i ? (r[(i - 1) * nb + j]
                               - (j ? r[(i - 1) * nb + j - 1] : 0)) : 0;
            c->resid[i * nb + j] = bd - pbd;
        }
    }
    c->words.resize(total);
    zigzag_encode_i64(c->resid.data(), c->words.data(), total);
    c->packed.resize(16 + total * 9 + 64);
    int64_t m = nibble_pack(c->words.data(), total, c->packed.data());
    out.append((char*)c->packed.data(), m);
}

void seal_part(ShardCore* c, int32_t pid, NPart& p) {
    int64_t n = (int64_t)p.ts.size();
    if (!n) return;
    HistState* hs = nullptr;
    if (p.hist_col >= 0) {
        auto hit = c->hist.find(pid);
        if (hit != c->hist.end()) hs = &hit->second;
    }
    NSealed s;
    s.nrows = (int32_t)n;
    s.start = p.ts[0];
    s.end = p.ts[n - 1];
    s.id = (s.start << 12) | (int64_t)(p.seq & 0xFFF);
    p.seq = (p.seq + 1) & 0xFFF;
    encode_dd(c, p.ts.data(), n, s.ts_bytes);
    s.col_bytes.resize(p.cols.size());
    for (size_t i = 0; i < p.cols.size(); i++) {
        if ((int32_t)i == p.hist_col && hs != nullptr)
            encode_hist2d(c, *hs, n, s.col_bytes[i]);
        else
            encode_xor(c, p.cols[i].data(), n, s.col_bytes[i]);
    }
    p.samples_sealed += n;
    p.sealed.push_back(std::move(s));
    p.ts.clear();
    for (auto& col : p.cols) col.clear();
    if (hs != nullptr) hs->rows.clear();
    p.version++;
}

}  // namespace

void* shard_core_create(int32_t max_chunk_size, int32_t groups) {
    ShardCore* c = new ShardCore();
    c->max_chunk = max_chunk_size;
    c->groups = groups > 0 ? groups : 1;
    c->watermarks.assign(c->groups, -1);
    return c;
}

void shard_core_destroy(void* cp) { delete static_cast<ShardCore*>(cp); }

void shard_core_set_watermark(void* cp, int32_t group, int64_t off) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    if (group >= 0 && group < c->groups) c->watermarks[group] = off;
}

// Parse + ingest one binary RecordContainer (format: core/record.py v2).
// Value shapes covered: scalar f64 (tag 0) and first-class histogram
// les+counts (tag 1, at most one per record — reference multi-schema
// ingest, TimeSeriesShard.scala:570). Returns rows ingested, or -1 on a
// malformed/uncovered container: it is then NOT ingested at all and the
// caller takes the host fallback path. All-or-nothing via a validate pass.
int64_t shard_core_ingest(void* cp, const uint8_t* d, int64_t len,
                          int64_t offset) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    if (len < 5 || d[0] != 2) return -1;
    uint32_t nrec = rd_u32(d + 1);
    // pass 1: validate shapes and bounds
    int64_t off = 5;
    for (uint32_t i = 0; i < nrec; i++) {
        if (off + 4 > len) return -1;
        uint32_t rl = rd_u32(d + off);
        off += 4;
        int64_t end = off + rl;
        if (end > len || rl < 17) return -1;
        int64_t o = off + 14;
        uint16_t nl = rd_u16(d + o);
        o += 2;
        for (uint16_t j = 0; j < nl; j++) {
            if (o + 2 > end) return -1;
            o += 2 + rd_u16(d + o);
            if (o + 2 > end) return -1;
            o += 2 + rd_u16(d + o);
        }
        if (o + 1 > end) return -1;
        uint8_t nv = d[o];
        o += 1;
        if (nv == 0) return -1;
        int hists = 0;
        for (uint8_t j = 0; j < nv; j++) {
            if (o + 1 > end) return -1;
            uint8_t tag = d[o];
            if (tag == 0) {
                if (o + 9 > end) return -1;
                o += 9;
            } else if (tag == 1) {
                if (o + 3 > end) return -1;
                uint16_t nb = rd_u16(d + o + 1);
                if (nb == 0 || nb > 4096) return -1;
                if (o + 3 + (int64_t)nb * 16 > end) return -1;
                o += 3 + (int64_t)nb * 16;
                if (++hists > 1) return -1;  // one hist column per record
            } else {
                return -1;  // strings/other shapes take the host path
            }
        }
        if (o != end) return -1;
        off = end;
    }
    // pass 2: ingest
    int64_t ingested = 0;
    off = 5;
    for (uint32_t i = 0; i < nrec; i++) {
        uint32_t rl = rd_u32(d + off);
        off += 4;
        int64_t end = off + rl;
        uint32_t hash = rd_u32(d + off);
        int64_t ts = rd_i64(d + off + 4);
        int64_t key_off = off + 12;  // schema id + labels = canonical key
        int64_t o = key_off + 2;
        uint16_t nl = rd_u16(d + o);
        o += 2;
        for (uint16_t j = 0; j < nl; j++) {
            o += 2 + rd_u16(d + o);
            o += 2 + rd_u16(d + o);
        }
        int64_t key_len = o - key_off;
        uint8_t nv = d[o];
        o += 1;
        // per-value layout walk (validated in pass 1)
        int64_t voff[256];
        uint8_t vtag[256];
        uint16_t vnb[256];
        int32_t rec_hist = -1;
        {
            int64_t vo = o;
            for (uint16_t j = 0; j < nv; j++) {
                vtag[j] = d[vo];
                voff[j] = vo;
                if (d[vo] == 0) {
                    vnb[j] = 0;
                    vo += 9;
                } else {
                    vnb[j] = rd_u16(d + vo + 1);
                    rec_hist = (int32_t)j;
                    vo += 3 + (int64_t)vnb[j] * 16;
                }
            }
        }
        int32_t group = (int32_t)(hash % (uint32_t)c->groups);
        if (offset <= c->watermarks[group]) {
            c->rows_skipped++;
            off = end;
            continue;
        }
        std::string_view probe((const char*)d + key_off, key_len);
        auto it = c->by_key.find(probe);
        NPart* p;
        int32_t pid;
        if (it == c->by_key.end()) {
            pid = (int32_t)c->parts.size();
            c->parts.emplace_back();
            p = &c->parts.back();
            p->key = c->intern_key((const char*)d + key_off, key_len);
            p->hash = hash;
            p->cols.resize(nv);
            p->ts.reserve(8);
            for (auto& col : p->cols) col.reserve(8);
            if (rec_hist >= 0) {
                p->hist_col = rec_hist;
                HistState& hs = c->hist[pid];
                hs.nb = vnb[rec_hist];
                hs.les.resize(hs.nb);
                std::memcpy(hs.les.data(), d + voff[rec_hist] + 3,
                            (size_t)hs.nb * 8);
            }
            c->by_key.emplace(p->key, pid);
            c->new_parts.push_back(pid);
        } else {
            pid = it->second;
            p = &c->parts[pid];
        }
        // a record whose hist position disagrees with the partition's
        // shape cannot append without desyncing columns — drop it. An
        // EMPTY partition (pre-created via shard_core_create_part or a
        // snapshot bootstrap, which don't know value shapes) adopts the
        // first record's shape instead.
        if (rec_hist != p->hist_col) {
            if (rec_hist >= 0 && p->hist_col < 0 && p->ts.empty()
                    && p->sealed.empty()) {
                p->hist_col = rec_hist;
                HistState& hs = c->hist[pid];
                hs.nb = vnb[rec_hist];
                hs.les.resize(hs.nb);
                std::memcpy(hs.les.data(), d + voff[rec_hist] + 3,
                            (size_t)hs.nb * 8);
            } else {
                c->rows_incompat++;
                off = end;
                continue;
            }
        }
        if (ts <= p->latest()) {
            c->rows_ooo++;
            off = end;
            continue;
        }
        HistState* hsp = nullptr;
        if (p->hist_col >= 0) {
            hsp = &c->hist[pid];  // one lookup per record, reused below
            uint16_t nb = vnb[p->hist_col];
            if ((int32_t)nb != hsp->nb) {
                // bucket-scheme change forces a chunk switch (mirrors
                // TimeSeriesPartition.ingest host semantics)
                if (!p->ts.empty()) seal_part(c, pid, *p);
                hsp->nb = nb;
                hsp->les.resize(nb);
            }
            std::memcpy(hsp->les.data(), d + voff[p->hist_col] + 3,
                        (size_t)nb * 8);
        }
        if (p->first_ts < 0) p->first_ts = ts;
        p->ts.push_back(ts);
        // Every column must grow in lockstep with ts: a crafted container
        // whose later record carries fewer values than the partition's
        // column count would otherwise leave short columns, and seal-time
        // encoders read ts.size() elements (heap OOB). Missing values pad
        // with NaN; extra values are dropped.
        for (size_t j = 0; j < p->cols.size(); j++) {
            double x = std::numeric_limits<double>::quiet_NaN();
            if (j < (size_t)nv && vtag[j] == 0)
                std::memcpy(&x, d + voff[j] + 1, 8);
            p->cols[j].push_back(x);
        }
        if (hsp != nullptr) {
            const uint8_t* counts = d + voff[p->hist_col] + 3
                + (int64_t)hsp->nb * 8;
            size_t base = hsp->rows.size();
            hsp->rows.resize(base + hsp->nb);
            std::memcpy(hsp->rows.data() + base, counts,
                        (size_t)hsp->nb * 8);
        }
        if ((int32_t)p->ts.size() >= c->max_chunk) seal_part(c, pid, *p);
        ingested++;
        off = end;
    }
    c->rows_ingested += ingested;
    return ingested;
}

int64_t shard_core_stat(void* cp, int32_t which) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    switch (which) {
        case 0: return c->rows_ingested;
        case 1: return c->rows_skipped;
        case 2: return c->rows_ooo;
        case 3: return (int64_t)c->parts.size();
        case 4: return (int64_t)c->new_parts.size();
        case 5: return c->rows_incompat;
        default: return -1;
    }
}

int32_t shard_core_drain_new(void* cp, int32_t* out, int32_t cap) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    int32_t n = (int32_t)c->new_parts.size();
    if (n > cap) n = cap;
    for (int32_t i = 0; i < n; i++) out[i] = c->new_parts[i];
    c->new_parts.erase(c->new_parts.begin(), c->new_parts.begin() + n);
    return n;
}

// O(1) part lookup by canonical key bytes; -1 when absent. Restored shards
// need no host-language key dictionary — this map is authoritative.
int32_t shard_core_lookup(void* cp, const uint8_t* key, int32_t key_len) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    std::string_view probe((const char*)key, key_len);
    auto it = c->by_key.find(probe);
    return it == c->by_key.end() ? -1 : it->second;
}

// Bulk restore from an index snapshot: entries laid out as
//   u32 key_len | key bytes | u32 hash | i64 floor | u8 alive | u8 ncols
// pid == entry ordinal; key_len==0 marks a purged tombstone slot.
// Returns entries restored, or -1 on a malformed buffer.
int64_t shard_core_bootstrap(void* cp, const uint8_t* d, int64_t len) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    if (!c->parts.empty()) return -1;  // only into an empty core
    int64_t off = 0, n = 0;
    while (off < len) {
        if (off + 4 > len) return -1;
        uint32_t kl = rd_u32(d + off);
        off += 4;
        if (off + kl + 14 > len) return -1;
        c->parts.emplace_back();
        NPart& p = c->parts.back();
        int64_t key_off2 = off;
        off += kl;
        p.hash = rd_u32(d + off);
        p.floor_ts = rd_i64(d + off + 4);
        p.alive = kl != 0 && d[off + 12] != 0;
        uint8_t ncols = d[off + 13];
        off += 14;
        if (p.alive) {
            // intern only LIVE keys: tombstone bytes would otherwise leak
            // in the append-only arena on every snapshot restore
            p.key = c->intern_key((const char*)d + key_off2, kl);
            p.cols.resize(ncols ? ncols : 1);
            c->by_key.emplace(p.key, (int32_t)(c->parts.size() - 1));
        }
        n++;
    }
    return n;
}

int64_t part_floor(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].floor_ts;
}

// bulk floor export for index snapshots (one call, not one per series)
void shard_core_floors(void* cp, int64_t* out, int64_t cap) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    int64_t n = (int64_t)c->parts.size();
    if (n > cap) n = cap;
    for (int64_t i = 0; i < n; i++) out[i] = c->parts[i].floor_ts;
}

// snapshot export: the exact bootstrap layout, built in one pass in C++
// (key_off/key_len let the host slice key blobs without re-parsing)
int64_t shard_core_export_size(void* cp) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    int64_t total = 0;
    for (auto& p : c->parts) total += 4 + (int64_t)p.key.size() + 14;
    return total;
}

void shard_core_export(void* cp, uint8_t* out, int64_t* key_off,
                       int32_t* key_len) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    int64_t off = 0;
    int64_t i = 0;
    for (auto& p : c->parts) {
        uint32_t kl = (uint32_t)p.key.size();
        std::memcpy(out + off, &kl, 4);
        off += 4;
        key_off[i] = off;
        key_len[i] = (int32_t)kl;
        if (kl) std::memcpy(out + off, p.key.data(), kl);
        off += kl;
        std::memcpy(out + off, &p.hash, 4);
        std::memcpy(out + off + 4, &p.floor_ts, 8);
        out[off + 12] = p.alive ? 1 : 0;
        out[off + 13] = (uint8_t)p.cols.size();
        off += 14;
        i++;
    }
}

// bulk floor seeding (post-bootstrap delta from the column store)
void shard_core_seed_floors(void* cp, const int32_t* pids,
                            const int64_t* floors, int64_t n) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    for (int64_t i = 0; i < n; i++) {
        NPart& p = c->parts[pids[i]];
        if (floors[i] > p.floor_ts) p.floor_ts = floors[i];
    }
}

int32_t shard_core_create_part(void* cp, const uint8_t* key, int32_t key_len,
                               uint32_t hash, int32_t ncols) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    std::string_view probe((const char*)key, key_len);
    auto it = c->by_key.find(probe);
    if (it != c->by_key.end()) return it->second;
    int32_t pid = (int32_t)c->parts.size();
    c->parts.emplace_back();
    NPart& p = c->parts.back();
    p.key = c->intern_key((const char*)key, key_len);
    p.hash = hash;
    p.cols.resize(ncols > 0 ? ncols : 1);
    c->by_key.emplace(p.key, pid);
    return pid;
}

int32_t shard_core_key_len(void* cp, int32_t pid) {
    return (int32_t)static_cast<ShardCore*>(cp)->parts[pid].key.size();
}
void shard_core_key_copy(void* cp, int32_t pid, uint8_t* out) {
    std::string_view k = static_cast<ShardCore*>(cp)->parts[pid].key;
    std::memcpy(out, k.data(), k.size());
}
uint32_t shard_core_part_hash(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].hash;
}

int64_t part_append(void* cp, int32_t pid, int64_t ts, const double* vals,
                    int32_t nvals) {
    // host-fallback single append: the CALLER counts drops (returning 0
    // here already feeds stats.out_of_order_dropped; bumping rows_ooo too
    // would double-count when the delta sync runs)
    ShardCore* c = static_cast<ShardCore*>(cp);
    NPart& p = c->parts[pid];
    if (ts <= p.latest()) return 0;
    // a histogram partition must take part_append_hist: fabricating an
    // all-zero cumulative bucket row here would read as a counter reset
    // and corrupt every later rate()/increase() window
    if (p.hist_col >= 0) return 0;
    if (p.first_ts < 0) p.first_ts = ts;
    p.ts.push_back(ts);
    for (int32_t j = 0; j < nvals && j < (int32_t)p.cols.size(); j++)
        p.cols[j].push_back(vals[j]);
    if ((int32_t)p.ts.size() >= c->max_chunk) seal_part(c, pid, p);
    c->rows_ingested++;
    return 1;
}

// Host-fallback single append for histogram partitions: ``dvals`` carries
// every value column in schema order (the entry at the hist column is
// ignored); les+counts carry the bucket scheme and cumulative counts.
int64_t part_append_hist(void* cp, int32_t pid, int64_t ts,
                         const double* dvals, int32_t ndv,
                         const double* les, const int64_t* counts,
                         int32_t nb, int32_t hist_col) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    NPart& p = c->parts[pid];
    if (nb <= 0 || nb > 4096 || hist_col < 0) return 0;
    if (p.hist_col < 0 && p.ts.empty() && p.sealed.empty()) {
        p.hist_col = hist_col;  // first sample fixes the hist column
        HistState& hs0 = c->hist[pid];
        hs0.nb = nb;
        hs0.les.assign(les, les + nb);
    }
    if (hist_col != p.hist_col) return 0;
    if (ts <= p.latest()) return 0;
    HistState& hs = c->hist[pid];
    if (nb != hs.nb) {
        if (!p.ts.empty()) seal_part(c, pid, p);
        hs.nb = nb;
        hs.les.resize(nb);
    }
    hs.les.assign(les, les + nb);
    if (p.first_ts < 0) p.first_ts = ts;
    p.ts.push_back(ts);
    for (int32_t j = 0; j < (int32_t)p.cols.size(); j++)
        p.cols[j].push_back(
            j < ndv && j != hist_col
                ? dvals[j] : std::numeric_limits<double>::quiet_NaN());
    size_t base = hs.rows.size();
    hs.rows.resize(base + nb);
    std::memcpy(hs.rows.data() + base, counts, (size_t)nb * 8);
    if ((int32_t)p.ts.size() >= c->max_chunk) seal_part(c, pid, p);
    c->rows_ingested++;
    return 1;
}

int32_t part_hist_col(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].hist_col;
}
int32_t part_hist_nb(void* cp, int32_t pid) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    auto it = c->hist.find(pid);
    return it == c->hist.end() ? 0 : it->second.nb;
}
void part_hist_les(void* cp, int32_t pid, double* out) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    auto it = c->hist.find(pid);
    if (it != c->hist.end())
        std::memcpy(out, it->second.les.data(), it->second.les.size() * 8);
}
// copies up to n buffer rows of bucket counts, row-major [n][nb]
int32_t part_buf_hist_copy(void* cp, int32_t pid, int32_t n, int64_t* out) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    auto it = c->hist.find(pid);
    if (it == c->hist.end() || it->second.nb <= 0) return 0;
    HistState& hs = it->second;
    int32_t have = (int32_t)(hs.rows.size() / hs.nb);
    if (n > have) n = have;
    std::memcpy(out, hs.rows.data(), (size_t)n * hs.nb * 8);
    return n;
}

int64_t part_latest_ts(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].latest();
}
int64_t part_first_ts(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].first_ts;
}
int64_t part_earliest_ts(void* cp, int32_t pid) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    if (!p.sealed.empty()) return p.sealed.front().start;
    if (!p.ts.empty()) return p.ts.front();
    return -1;
}
int64_t part_num_samples(void* cp, int32_t pid) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    return p.samples_sealed + (int64_t)p.ts.size();
}
int64_t part_version(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].version;
}
int32_t part_buf_count(void* cp, int32_t pid) {
    return (int32_t)static_cast<ShardCore*>(cp)->parts[pid].ts.size();
}
int32_t part_ncols(void* cp, int32_t pid) {
    return (int32_t)static_cast<ShardCore*>(cp)->parts[pid].cols.size();
}
// copies up to n rows (snapshot prefix); cols_out laid out column-major
// [ncols][n]
int32_t part_buf_copy(void* cp, int32_t pid, int32_t n, int64_t* ts_out,
                      double* cols_out) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    int32_t have = (int32_t)p.ts.size();
    if (n > have) n = have;
    std::memcpy(ts_out, p.ts.data(), n * 8);
    for (size_t ci = 0; ci < p.cols.size(); ci++)
        std::memcpy(cols_out + ci * n, p.cols[ci].data(), n * 8);
    return n;
}

int32_t part_seal_buffer(void* cp, int32_t pid) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    NPart& p = c->parts[pid];
    if (p.ts.empty()) return 0;
    seal_part(c, pid, p);
    return 1;
}

int32_t part_num_sealed(void* cp, int32_t pid) {
    return (int32_t)static_cast<ShardCore*>(cp)->parts[pid].sealed.size();
}
void part_sealed_meta(void* cp, int32_t pid, int32_t idx, int64_t* out4) {
    NSealed& s = static_cast<ShardCore*>(cp)->parts[pid].sealed[idx];
    out4[0] = s.id;
    out4[1] = s.start;
    out4[2] = s.end;
    out4[3] = s.nrows;
}
int64_t part_sealed_veclen(void* cp, int32_t pid, int32_t idx, int32_t col) {
    NSealed& s = static_cast<ShardCore*>(cp)->parts[pid].sealed[idx];
    if (col == 0) return (int64_t)s.ts_bytes.size();
    return (int64_t)s.col_bytes[col - 1].size();
}
void part_sealed_veccopy(void* cp, int32_t pid, int32_t idx, int32_t col,
                         uint8_t* out) {
    NSealed& s = static_cast<ShardCore*>(cp)->parts[pid].sealed[idx];
    const std::string& b = col == 0 ? s.ts_bytes : s.col_bytes[col - 1];
    std::memcpy(out, b.data(), b.size());
}

void part_mark_flushed(void* cp, int32_t pid, int64_t up_to_id) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    if (up_to_id > p.flushed_id) p.flushed_id = up_to_id;
}
int64_t part_flushed_id(void* cp, int32_t pid) {
    return static_cast<ShardCore*>(cp)->parts[pid].flushed_id;
}

int32_t part_evict_flushed(void* cp, int32_t pid) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    int32_t dropped = 0;
    int64_t floor = p.floor_ts;
    std::vector<NSealed> keep;
    for (auto& s : p.sealed) {
        if (s.id <= p.flushed_id) {
            if (s.end > floor) floor = s.end;
            dropped++;
        } else {
            keep.push_back(std::move(s));
        }
    }
    if (dropped) {
        p.sealed = std::move(keep);
        p.floor_ts = floor;
        p.version++;
    }
    return dropped;
}

void part_seed_floor(void* cp, int32_t pid, int64_t ts) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    if (ts > p.floor_ts) p.floor_ts = ts;
}

// whole-shard encoded chunk footprint in one call (the flush scheduler
// checks the memory budget every tick; per-partition calls would be O(n)
// FFI round trips at high cardinality)
int64_t shard_core_chunk_bytes(void* cp) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    int64_t total = 0;
    for (auto& p : c->parts) {
        for (auto& s : p.sealed) {
            total += (int64_t)s.ts_bytes.size();
            for (auto& cb : s.col_bytes) total += (int64_t)cb.size();
        }
    }
    return total;
}

int64_t part_chunk_bytes(void* cp, int32_t pid) {
    NPart& p = static_cast<ShardCore*>(cp)->parts[pid];
    int64_t n = 0;
    for (auto& s : p.sealed) {
        n += (int64_t)s.ts_bytes.size();
        for (auto& cb : s.col_bytes) n += (int64_t)cb.size();
    }
    return n;
}

void part_free(void* cp, int32_t pid) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    NPart& p = c->parts[pid];
    if (!p.alive) return;
    c->by_key.erase(p.key);
    c->hist.erase(pid);
    p.alive = false;
    p.key = std::string_view();  // arena bytes leak until core teardown
    p.ts.clear();
    p.ts.shrink_to_fit();
    p.cols.clear();
    p.cols.shrink_to_fit();
    p.sealed.clear();
    p.sealed.shrink_to_fit();
}

// ---------------------------------------------------------------------------
// TagIndex: native part-key inverted index hot paths.
//
// Counterpart of the reference's PartKeyLuceneIndex postings + query ops
// (core/src/main/scala/filodb.core/memstore/PartKeyLuceneIndex.scala:455,494)
// and its JMH PartKeyIndexBenchmark. Two tiers, mirroring the Python
// structure in filodb_tpu/core/memstore/index.py:
//   - frozen: per label, a sorted value table (offset-indexed bytes) and a
//     flat pid array — bulk-loaded from index snapshots, binary-searched;
//   - tail: per label, value -> pid vector (pids ascend with creation order).
// Liveness/tombstones and [start,end] time bounds stay on the Python side
// (numpy masks); this structure is postings only.

namespace {

struct FrozenLab {
    std::vector<uint32_t> voff;  // [nv+1]
    std::string vblob;
    std::vector<int64_t> poff;   // [nv+1]
    std::vector<int32_t> pids;   // sorted within each value slice

    int64_t nv() const {
        return voff.empty() ? 0 : (int64_t)voff.size() - 1;
    }
    int64_t find(const char* v, int64_t len) const {
        int64_t lo = 0, hi = nv();
        while (lo < hi) {
            int64_t mid = (lo + hi) / 2;
            const char* mv = vblob.data() + voff[mid];
            int64_t ml = (int64_t)voff[mid + 1] - voff[mid];
            int cmp = std::memcmp(mv, v, ml < len ? ml : len);
            bool less = cmp < 0 || (cmp == 0 && ml < len);
            if (less) lo = mid + 1; else hi = mid;
        }
        if (lo < nv()) {
            const char* mv = vblob.data() + voff[lo];
            int64_t ml = (int64_t)voff[lo + 1] - voff[lo];
            if (ml == len && std::memcmp(mv, v, len) == 0) return lo;
        }
        return -1;
    }
};

struct TagLab {
    FrozenLab frozen;
    std::unordered_map<std::string, std::vector<int32_t>> tail;
};

struct TagIndex {
    std::unordered_map<std::string, int32_t> label_ids;
    std::vector<std::string> label_names;
    std::vector<TagLab> labs;
    // merged-export staging (sizes call builds; export call copies+clears)
    FrozenLab exp_tmp;
    std::vector<int32_t> scratch;

    TagLab* find_lab(const char* name, int64_t len) {
        auto it = label_ids.find(std::string(name, len));
        return it == label_ids.end() ? nullptr : &labs[it->second];
    }
    TagLab& get_lab(const std::string& name) {
        auto it = label_ids.find(name);
        if (it != label_ids.end()) return labs[it->second];
        label_ids.emplace(name, (int32_t)labs.size());
        label_names.push_back(name);
        labs.emplace_back();
        return labs.back();
    }
};

// merge two sorted unique ranges into out (unique)
static int64_t merge2(const int32_t* a, int64_t na, const int32_t* b,
                      int64_t nb, int32_t* out) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        int32_t x = a[i], y = b[j];
        if (x < y) { out[k++] = x; i++; }
        else if (y < x) { out[k++] = y; j++; }
        else { out[k++] = x; i++; j++; }
    }
    while (i < na) out[k++] = a[i++];
    while (j < nb) out[k++] = b[j++];
    return k;
}

// postings of (lab, value) merged across tiers into vec (sorted unique)
static void equals_into(TagLab* lab, const char* v, int64_t vl,
                        std::vector<int32_t>& vec) {
    vec.clear();
    const int32_t* fp = nullptr;
    int64_t fn = 0;
    int64_t vi = lab->frozen.find(v, vl);
    if (vi >= 0) {
        fp = lab->frozen.pids.data() + lab->frozen.poff[vi];
        fn = lab->frozen.poff[vi + 1] - lab->frozen.poff[vi];
    }
    auto it = lab->tail.find(std::string(v, vl));
    const int32_t* tp = nullptr;
    int64_t tn = 0;
    if (it != lab->tail.end()) {
        tp = it->second.data();
        tn = (int64_t)it->second.size();
    }
    vec.resize(fn + tn);
    vec.resize(merge2(fp, fn, tp, tn, vec.data()));
}

static int64_t copy_out(const std::vector<int32_t>& vec, int32_t* out,
                        int64_t cap) {
    int64_t n = (int64_t)vec.size();
    if (n > cap) return -n;  // caller re-calls with a bigger buffer
    std::memcpy(out, vec.data(), n * sizeof(int32_t));
    return n;
}

}  // namespace

void* tagindex_create() { return new TagIndex(); }
void tagindex_destroy(void* h) { delete static_cast<TagIndex*>(h); }

// key blob: [u16 schema][u16 nl][(u16 kl, k bytes)(u16 vl, v bytes)]*
// (canonical part-key layout shared with ShardCore records)
int32_t tagindex_add(void* h, int32_t pid, const uint8_t* key, int32_t len) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    if (len < 4) return -1;
    int64_t o = 2;
    uint16_t nl = rd_u16(key + o);
    o += 2;
    for (uint16_t i = 0; i < nl; i++) {
        if (o + 2 > len) return -1;
        uint16_t kl = rd_u16(key + o);
        o += 2;
        if (o + kl + 2 > len) return -1;
        std::string name((const char*)key + o, kl);
        o += kl;
        uint16_t vl = rd_u16(key + o);
        o += 2;
        if (o + vl > len) return -1;
        TagLab& lab = ix->get_lab(name);
        auto& vec = lab.tail[std::string((const char*)key + o, vl)];
        o += vl;
        if (vec.empty() || vec.back() < pid) {
            vec.push_back(pid);
        } else if (vec.back() != pid) {  // out-of-order (restore/readd)
            auto it = std::lower_bound(vec.begin(), vec.end(), pid);
            if (it == vec.end() || *it != pid) vec.insert(it, pid);
        }
    }
    return 0;
}

// remove pid from every posting list (rare: pid re-created after eviction
// with a different key; normal removals are Python-side tombstones).
// Frozen arrays are physically compacted to keep every slice sorted+unique.
void tagindex_purge_pid(void* h, int32_t pid) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    for (auto& lab : ix->labs) {
        for (auto& kv : lab.tail) {
            auto& vec = kv.second;
            auto it = std::lower_bound(vec.begin(), vec.end(), pid);
            if (it != vec.end() && *it == pid) vec.erase(it);
        }
        auto& fr = lab.frozen;
        bool hit = false;
        for (int64_t vi = 0; vi < fr.nv() && !hit; vi++) {
            const int32_t* b = fr.pids.data() + fr.poff[vi];
            const int32_t* e = fr.pids.data() + fr.poff[vi + 1];
            const int32_t* it = std::lower_bound(b, e, pid);
            hit = it != e && *it == pid;
        }
        if (!hit) continue;
        int64_t w = 0;
        std::vector<int64_t> npoff(1, 0);
        for (int64_t vi = 0; vi < fr.nv(); vi++) {
            for (int64_t k = fr.poff[vi]; k < fr.poff[vi + 1]; k++)
                if (fr.pids[k] != pid) fr.pids[w++] = fr.pids[k];
            npoff.push_back(w);
        }
        fr.pids.resize(w);
        fr.poff = std::move(npoff);
    }
}

int64_t tagindex_equals(void* h, const char* labn, int64_t ll,
                        const char* v, int64_t vl, int32_t* out,
                        int64_t cap) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab* lab = ix->find_lab(labn, ll);
    if (!lab) return 0;
    equals_into(lab, v, vl, ix->scratch);
    return copy_out(ix->scratch, out, cap);
}

// pairs: [(u16 kl, k)(u16 vl, v)]*; intersection of equals postings.
// Zero-materialization: each filter's postings stay as its (frozen, tail)
// sorted range pair; the smallest filter's merged enumeration is membership-
// checked against every other filter's two ranges with resumable cursors.
int64_t tagindex_intersect_equals(void* h, const uint8_t* pairs,
                                  int32_t npairs, int32_t* out, int64_t cap) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    struct Ranges {
        const int32_t* fp; int64_t fn;  // frozen slice
        const int32_t* tp; int64_t tn;  // tail vector
        int64_t fi = 0, ti = 0;         // resumable cursors
        int64_t total() const { return fn + tn; }
        bool contains(int32_t x) {
            // ascending probes: cursors only move forward
            int64_t step = 1;
            while (fi + step < fn && fp[fi + step] < x) step <<= 1;
            int64_t hi2 = fi + step < fn ? fi + step : fn;
            fi = std::lower_bound(fp + fi, fp + hi2, x) - fp;
            if (fi < fn && fp[fi] == x) return true;
            step = 1;
            while (ti + step < tn && tp[ti + step] < x) step <<= 1;
            hi2 = ti + step < tn ? ti + step : tn;
            ti = std::lower_bound(tp + ti, tp + hi2, x) - tp;
            return ti < tn && tp[ti] == x;
        }
    };
    std::vector<Ranges> rs(npairs);
    int64_t o = 0;
    for (int32_t i = 0; i < npairs; i++) {
        uint16_t kl = rd_u16(pairs + o);
        o += 2;
        const char* k = (const char*)pairs + o;
        o += kl;
        uint16_t vl = rd_u16(pairs + o);
        o += 2;
        const char* v = (const char*)pairs + o;
        o += vl;
        TagLab* lab = ix->find_lab(k, kl);
        if (!lab) return 0;
        Ranges& r = rs[i];
        r.fp = nullptr; r.fn = 0; r.tp = nullptr; r.tn = 0;
        int64_t vi = lab->frozen.find(v, vl);
        if (vi >= 0) {
            r.fp = lab->frozen.pids.data() + lab->frozen.poff[vi];
            r.fn = lab->frozen.poff[vi + 1] - lab->frozen.poff[vi];
        }
        auto it = lab->tail.find(std::string(v, vl));
        if (it != lab->tail.end()) {
            r.tp = it->second.data();
            r.tn = (int64_t)it->second.size();
        }
        if (r.total() == 0) return 0;
    }
    // smallest filter drives the enumeration
    int32_t si = 0;
    for (int32_t i = 1; i < npairs; i++)
        if (rs[i].total() < rs[si].total()) si = i;
    Ranges& s = rs[si];
    std::vector<int32_t>& res = ix->scratch;
    res.clear();
    int64_t fi = 0, ti = 0;
    while (fi < s.fn || ti < s.tn) {
        int32_t x;
        if (fi < s.fn && (ti >= s.tn || s.fp[fi] <= s.tp[ti])) {
            x = s.fp[fi];
            if (ti < s.tn && s.tp[ti] == x) ti++;
            fi++;
        } else {
            x = s.tp[ti++];
        }
        if (x == INT32_MIN) continue;  // purge sentinel
        bool all = true;
        for (int32_t i = 0; i < npairs && all; i++)
            if (i != si) all = rs[i].contains(x);
        if (all) res.push_back(x);
    }
    return copy_out(res, out, cap);
}

// batch add: pids[n], concatenated key blobs with offsets[n+1]
int32_t tagindex_add_batch(void* h, const int32_t* pids, int64_t n,
                           const uint8_t* blobs, const int64_t* offsets) {
    for (int64_t i = 0; i < n; i++) {
        int32_t rc = tagindex_add(h, pids[i], blobs + offsets[i],
                                  (int32_t)(offsets[i + 1] - offsets[i]));
        if (rc != 0) return rc;
    }
    return 0;
}

// one-shot: equals intersection + time-overlap predicate
// (starts[pid] <= end_t && ends[pid] >= start_t), the full
// partIdsFromFilters fast path in a single native call.
int64_t tagindex_query_equals(void* h, const uint8_t* pairs, int32_t npairs,
                              const int64_t* starts, const int64_t* ends,
                              int64_t bounds_len, int64_t start_t,
                              int64_t end_t, int32_t* out, int64_t cap) {
    int64_t n = tagindex_intersect_equals(h, pairs, npairs, out, cap);
    if (n < 0) return n;  // caller re-buffers; scratch still holds result
    int64_t w = 0;
    for (int64_t i = 0; i < n; i++) {
        int32_t pid = out[i];
        // pids beyond the caller's bounds snapshot (added concurrently)
        // are not visible to this query
        if (pid < bounds_len && starts[pid] <= end_t
            && ends[pid] >= start_t)
            out[w++] = pid;
    }
    return w;
}

// query_equals + an extra sorted allow-list intersected in the same pass —
// the regex fast path: equals postings ∩ cached regex postings ∩ time
// predicate, all in one call (no per-query numpy round trips host-side)
int64_t tagindex_query_equals_allow(void* h, const uint8_t* pairs,
                                    int32_t npairs, const int32_t* allow,
                                    int64_t allow_len, const int64_t* starts,
                                    const int64_t* ends, int64_t bounds_len,
                                    int64_t start_t, int64_t end_t,
                                    int32_t* out, int64_t cap) {
    int64_t n;
    if (npairs > 0) {
        n = tagindex_intersect_equals(h, pairs, npairs, out, cap);
        if (n < 0) return n;
    } else {
        // no equals filters: the allow list IS the candidate set
        n = allow_len < cap ? allow_len : cap;
        if (allow_len > cap) return -allow_len;
        std::memcpy(out, allow, n * 4);
    }
    int64_t w = 0, a = 0;
    for (int64_t i = 0; i < n; i++) {
        int32_t pid = out[i];
        if (npairs > 0) {  // gallop the sorted allow list alongside
            while (a < allow_len && allow[a] < pid) a++;
            if (a >= allow_len) break;
            if (allow[a] != pid) continue;
        }
        if (pid < bounds_len && starts[pid] <= end_t
            && ends[pid] >= start_t)
            out[w++] = pid;
    }
    return w;
}

// union of every posting of a label ("has this label at all")
int64_t tagindex_label_all(void* h, const char* labn, int64_t ll,
                           int32_t* out, int64_t cap) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab* lab = ix->find_lab(labn, ll);
    if (!lab) return 0;
    std::vector<int32_t>& res = ix->scratch;
    res.clear();
    res.insert(res.end(), lab->frozen.pids.begin(), lab->frozen.pids.end());
    for (auto& kv : lab->tail)
        res.insert(res.end(), kv.second.begin(), kv.second.end());
    std::sort(res.begin(), res.end());
    res.erase(std::unique(res.begin(), res.end()), res.end());
    if (!res.empty() && res.front() == INT32_MIN)
        res.erase(res.begin());
    return copy_out(res, out, cap);
}

// value enumeration: frozen values first (vid 0..nv-1), then tail values in
// map order (vid nv..). Stable between a values() call and a following
// union_values() call as long as no adds happen in between.
int64_t tagindex_values_size(void* h, const char* labn, int64_t ll) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab* lab = ix->find_lab(labn, ll);
    if (!lab) return 0;
    int64_t sz = 0;
    for (int64_t vi = 0; vi < lab->frozen.nv(); vi++)
        sz += 4 + (lab->frozen.voff[vi + 1] - lab->frozen.voff[vi]);
    for (auto& kv : lab->tail) sz += 4 + (int64_t)kv.first.size();
    return sz;
}

void tagindex_values(void* h, const char* labn, int64_t ll, uint8_t* out) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab* lab = ix->find_lab(labn, ll);
    if (!lab) return;
    uint8_t* p = out;
    for (int64_t vi = 0; vi < lab->frozen.nv(); vi++) {
        uint32_t n = lab->frozen.voff[vi + 1] - lab->frozen.voff[vi];
        std::memcpy(p, &n, 4);
        p += 4;
        std::memcpy(p, lab->frozen.vblob.data() + lab->frozen.voff[vi], n);
        p += n;
    }
    for (auto& kv : lab->tail) {
        uint32_t n = (uint32_t)kv.first.size();
        std::memcpy(p, &n, 4);
        p += 4;
        std::memcpy(p, kv.first.data(), n);
        p += n;
    }
}

// union postings of the vids listed (vid space as enumerated above)
int64_t tagindex_union_values(void* h, const char* labn, int64_t ll,
                              const int32_t* vids, int64_t n, int32_t* out,
                              int64_t cap) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab* lab = ix->find_lab(labn, ll);
    if (!lab) return 0;
    int64_t nfrozen = lab->frozen.nv();
    std::vector<int32_t>& res = ix->scratch;
    res.clear();
    std::vector<const std::vector<int32_t>*> tails;
    tails.reserve(lab->tail.size());
    for (auto& kv : lab->tail) tails.push_back(&kv.second);
    for (int64_t i = 0; i < n; i++) {
        int64_t vi = vids[i];
        if (vi < nfrozen) {
            res.insert(res.end(),
                       lab->frozen.pids.begin() + lab->frozen.poff[vi],
                       lab->frozen.pids.begin() + lab->frozen.poff[vi + 1]);
        } else if (vi - nfrozen < (int64_t)tails.size()) {
            const auto& t = *tails[vi - nfrozen];
            res.insert(res.end(), t.begin(), t.end());
        }
    }
    std::sort(res.begin(), res.end());
    res.erase(std::unique(res.begin(), res.end()), res.end());
    if (!res.empty() && res.front() == INT32_MIN)
        res.erase(res.begin());
    return copy_out(res, out, cap);
}

int64_t tagindex_num_labels(void* h) {
    return (int64_t)static_cast<TagIndex*>(h)->label_names.size();
}

int64_t tagindex_labels_size(void* h) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    int64_t sz = 0;
    for (auto& n : ix->label_names) sz += 4 + (int64_t)n.size();
    return sz;
}

void tagindex_labels(void* h, uint8_t* out) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    uint8_t* p = out;
    for (auto& nm : ix->label_names) {
        uint32_t n = (uint32_t)nm.size();
        std::memcpy(p, &n, 4);
        p += 4;
        std::memcpy(p, nm.data(), n);
        p += n;
    }
}

// ---- snapshot export/load -------------------------------------------------
// Export merges frozen + tail, drops `deleted` pids (sorted array) and the
// INT32_MIN purge sentinels, and produces the snapshot array layout.
// Two-phase: sizes() builds into exp_tmp, export() copies it out.

int64_t tagindex_export_sizes(void* h, const char* labn, int64_t ll,
                              const int32_t* deleted, int64_t ndel,
                              int64_t* out3) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab* lab = ix->find_lab(labn, ll);
    FrozenLab& t = ix->exp_tmp;
    t.voff.assign(1, 0);
    t.vblob.clear();
    t.poff.assign(1, 0);
    t.pids.clear();
    if (lab) {
        auto keep = [&](int32_t pid) {
            if (pid == INT32_MIN) return false;
            if (!ndel) return true;
            const int32_t* e = deleted + ndel;
            const int32_t* it = std::lower_bound(deleted, e, pid);
            return !(it != e && *it == pid);
        };
        // ordered value walk: frozen table is sorted; tail keys must be
        // sorted and merged with it
        std::vector<std::pair<std::string, const std::vector<int32_t>*>>
            tails;
        tails.reserve(lab->tail.size());
        for (auto& kv : lab->tail) tails.emplace_back(kv.first, &kv.second);
        std::sort(tails.begin(), tails.end(),
                  [](const auto& a, const auto& b) {
                      return a.first < b.first;
                  });
        int64_t fi = 0, ti = 0;
        int64_t nf = lab->frozen.nv();
        std::vector<int32_t> merged;
        while (fi < nf || ti < (int64_t)tails.size()) {
            std::string fv;
            bool use_f = false, use_t = false;
            if (fi < nf) {
                fv.assign(lab->frozen.vblob.data() + lab->frozen.voff[fi],
                          lab->frozen.voff[fi + 1] - lab->frozen.voff[fi]);
            }
            if (fi < nf && ti < (int64_t)tails.size()) {
                int c = fv.compare(tails[ti].first);
                use_f = c <= 0;
                use_t = c >= 0;
            } else if (fi < nf) {
                use_f = true;
            } else {
                use_t = true;
            }
            const std::string& vname = use_f ? fv : tails[ti].first;
            merged.clear();
            if (use_f) {
                for (int64_t k = lab->frozen.poff[fi];
                     k < lab->frozen.poff[fi + 1]; k++) {
                    int32_t pid = lab->frozen.pids[k];
                    if (keep(pid)) merged.push_back(pid);
                }
                fi++;
            }
            if (use_t) {
                size_t base = merged.size();
                for (int32_t pid : *tails[ti].second)
                    if (keep(pid)) merged.push_back(pid);
                if (base && merged.size() > base)
                    std::inplace_merge(merged.begin(),
                                       merged.begin() + base, merged.end());
                ti++;
            }
            merged.erase(std::unique(merged.begin(), merged.end()),
                         merged.end());
            if (merged.empty()) continue;
            t.vblob += vname;
            t.voff.push_back((uint32_t)t.vblob.size());
            t.pids.insert(t.pids.end(), merged.begin(), merged.end());
            t.poff.push_back((int64_t)t.pids.size());
        }
    }
    out3[0] = t.nv();
    out3[1] = (int64_t)t.vblob.size();
    out3[2] = (int64_t)t.pids.size();
    return 0;
}

void tagindex_export_label(void* h, uint32_t* voff, uint8_t* vblob,
                           int64_t* poff, int32_t* pids) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    FrozenLab& t = ix->exp_tmp;
    std::memcpy(voff, t.voff.data(), t.voff.size() * 4);
    std::memcpy(vblob, t.vblob.data(), t.vblob.size());
    std::memcpy(poff, t.poff.data(), t.poff.size() * 8);
    std::memcpy(pids, t.pids.data(), t.pids.size() * 4);
}

void tagindex_load_label(void* h, const char* labn, int64_t ll,
                         const uint32_t* voff, int64_t nv,
                         const uint8_t* vblob, int64_t vlen,
                         const int64_t* poff, const int32_t* pids,
                         int64_t npids) {
    TagIndex* ix = static_cast<TagIndex*>(h);
    TagLab& lab = ix->get_lab(std::string(labn, ll));
    lab.frozen.voff.assign(voff, voff + nv + 1);
    lab.frozen.vblob.assign((const char*)vblob, vlen);
    lab.frozen.poff.assign(poff, poff + nv + 1);
    lab.frozen.pids.assign(pids, pids + npids);
}

// ---------------------------------------------------------------------------
// batched write-buffer window fold (aggregate-sidecar query lane)
//
// For each pid and each window (t0[w], t1[w]], folds the buffer samples of
// value column `col` (index into NPart::cols) into a 12-double stats row:
//   [count, sum, sumsq, min, max, first_ts, first_val, last_ts, last_val,
//    resets, corr, changes]
// NaN samples are skipped; accumulation is strictly sequential, matching
// the numpy cumsum semantics of memory/chunk.summarize_values bit for bit.
// flags_out[i]: bit0 = buffer timestamps non-monotone (caller must bypass),
// bit1 = a sealed chunk overlaps (min t0, max t1] (buffer-only fold is
// incomplete for this partition).
int32_t shard_buf_fold(void* cp, const int32_t* pids, int32_t npids,
                       const int64_t* t0s, const int64_t* t1s, int32_t nwin,
                       int32_t col, double* out, int32_t* flags_out) {
    ShardCore* c = static_cast<ShardCore*>(cp);
    const double qnan = std::numeric_limits<double>::quiet_NaN();
    int64_t g0 = INT64_MAX, g1 = INT64_MIN;
    for (int32_t w = 0; w < nwin; w++) {
        if (t0s[w] < g0) g0 = t0s[w];
        if (t1s[w] > g1) g1 = t1s[w];
    }
    for (int32_t i = 0; i < npids; i++) {
        NPart& p = c->parts[pids[i]];
        int32_t flags = 0;
        for (auto& s : p.sealed)
            if (s.end > g0 && s.start <= g1) { flags |= 2; break; }
        size_t n = p.ts.size();
        if (col < 0 || (size_t)col >= p.cols.size()) flags |= 1;
        for (size_t k = 1; k < n; k++)
            if (p.ts[k] < p.ts[k - 1]) { flags |= 1; break; }
        flags_out[i] = flags;
        double* rows = out + (size_t)i * nwin * 12;
        if (flags & 1) continue;
        const int64_t* ts = p.ts.data();
        const double* vals = p.cols[col].data();
        for (int32_t w = 0; w < nwin; w++) {
            double* r = rows + (size_t)w * 12;
            size_t lo = std::upper_bound(ts, ts + n, t0s[w]) - ts;
            size_t hi = std::upper_bound(ts, ts + n, t1s[w]) - ts;
            double cnt = 0, sum = 0, sumsq = 0, mn = qnan, mx = qnan;
            double fts = qnan, fv = qnan, lts = qnan, lv = qnan;
            double resets = 0, corr = 0, changes = 0;
            bool have_prev = false;
            double prev = 0;
            for (size_t k = lo; k < hi; k++) {
                double v = vals[k];
                if (v != v) continue;
                cnt += 1;
                sum += v;
                sumsq += v * v;
                if (!have_prev) {
                    mn = mx = v;
                    fts = (double)ts[k];
                    fv = v;
                } else {
                    if (v < mn) mn = v;
                    if (v > mx) mx = v;
                    if (v < prev) { resets += 1; corr += prev; }
                    if (v != prev) changes += 1;
                }
                lts = (double)ts[k];
                lv = v;
                prev = v;
                have_prev = true;
            }
            r[0] = cnt; r[1] = sum; r[2] = sumsq; r[3] = mn; r[4] = mx;
            r[5] = fts; r[6] = fv; r[7] = lts; r[8] = lv;
            r[9] = resets; r[10] = corr; r[11] = changes;
        }
    }
    return 0;
}

}  // extern "C"
