"""PromQL front end: lexer, parser, AST → LogicalPlan.

Counterpart of reference ``prometheus/`` module (ANTLR grammar
``prometheus/src/main/java/filodb/prometheus/antlr/PromQL.g4``, legacy parser
``parse/LegacyParser.scala``, AST package ``ast/``).
"""

from filodb_tpu.promql.parser import parse_query  # noqa: F401
