"""PromQL parser: query text → LogicalPlan.

Counterpart of the reference's parser facade and ANTLR grammar
(``prometheus/src/main/scala/filodb/prometheus/parse/Parser.scala:13-48``,
``AntlrParser.scala``, grammar ``antlr/PromQL.g4``, AST lowering in
``prometheus/src/main/scala/filodb/prometheus/ast/``). A hand-written
recursive-descent parser (no parser generator dependency) covering:

- selectors with label matchers (=, !=, =~, !~), metric names, ``__name__``
- matrix selectors ``[5m]``, offsets ``offset 5m``, subqueries ``[1h:5m]``
- step-multiple durations ``[5i]`` (reference README.md:429-460: ``i`` =
  publish/step interval multiples)
- full operator precedence: or < and/unless < comparisons < +- < */% <
  ^ < unary, with ``bool`` modifier and vector matching (on/ignoring/
  group_left/group_right)
- aggregations with by/without (prefix or suffix), topk/quantile/
  count_values parameters
- range/instant/misc functions incl. ``histogram_quantile``,
  ``label_replace``, ``absent``, ``vector``/``scalar``/``time``

The metric name maps to the ``_metric_`` label filter, matching the
reference's partition-key convention (``Schemas`` metric column).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from filodb_tpu.core.filters import (
    ColumnFilter,
    Equals,
    EqualsRegex,
    NotEquals,
    NotEqualsRegex,
)
from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.query import logical as lp

DEFAULT_STALENESS_MS = 300_000  # prometheus 5m staleness lookback


class ParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# lexer

_TOKEN_RE = re.compile(r"""
      (?P<WS>\s+)
    | (?P<COMMENT>\#[^\n]*)
    | (?P<DURATION>[0-9]+(?:\.[0-9]+)?(?:ms|s|m|h|d|w|y|i)(?:[0-9]+(?:ms|s|m|h|d|w|y))*)
    | (?P<NUMBER>0x[0-9a-fA-F]+|(?:[0-9]*\.[0-9]+|[0-9]+\.?)(?:[eE][+-]?[0-9]+)?|[Ii][Nn][Ff](?![a-zA-Z0-9_:])|[Nn][Aa][Nn](?![a-zA-Z0-9_:]))
    | (?P<IDENT>[a-zA-Z_][a-zA-Z0-9_:]*)
    | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*'|`[^`]*`)
    | (?P<OP>=~|!~|==|!=|<=|>=|<|>|=|\+|-|\*|/|%|\^|\(|\)|\{|\}|\[|\]|,|:|@)
""", re.VERBOSE)

_DUR_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000,
           "w": 604_800_000, "y": 31_536_000_000}

_KEYWORDS = {"and", "or", "unless", "by", "without", "on", "ignoring",
             "group_left", "group_right", "offset", "bool", "atan2"}


# required scalar-parameter counts for instant functions (exact, or
# (min, max) range) — the reference parser validates arity in the grammar;
# here it's a table check at plan construction
_INSTANT_FN_PARAMS = {
    "clamp": 2, "clamp_max": 1, "clamp_min": 1,
    "histogram_quantile": 1, "histogram_max_quantile": 1,
    "round": (0, 1),
    "abs": 0, "ceil": 0, "floor": 0, "exp": 0, "ln": 0, "log2": 0,
    "log10": 0, "sqrt": 0, "sgn": 0, "deg": 0, "rad": 0,
    "acos": 0, "asin": 0, "atan": 0, "cos": 0, "cosh": 0, "sin": 0,
    "sinh": 0, "tan": 0, "tanh": 0, "asinh": 0, "acosh": 0, "atanh": 0,
    "hour": 0, "minute": 0, "month": 0, "year": 0, "day_of_month": 0,
    "day_of_week": 0, "day_of_year": 0, "days_in_month": 0,
    "timestamp": 0,
}


@dataclass
class Token:
    kind: str
    text: str
    pos: int


def tokenize(text: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise ParseError(f"unexpected character {text[pos]!r} at {pos}")
        kind = m.lastgroup
        if kind not in ("WS", "COMMENT"):
            tok_text = m.group()
            if kind == "IDENT" and tok_text in _KEYWORDS:
                kind = "KEYWORD"
            out.append(Token(kind, tok_text, pos))
        pos = m.end()
    out.append(Token("EOF", "", pos))
    return out


def parse_duration_ms(text: str, step_ms: int = 0) -> int:
    """Parse '5m', '1h30m', or step-multiple '5i' into millis."""
    if text.endswith("i"):
        mult = float(text[:-1])
        if step_ms <= 0:
            raise ParseError("step-multiple duration used without a step")
        return int(mult * step_ms)
    total = 0
    for num, unit in re.findall(r"([0-9]+(?:\.[0-9]+)?)(ms|s|m|h|d|w|y)", text):
        total += int(float(num) * _DUR_MS[unit])
    return total


def _unquote(s: str) -> str:
    body = s[1:-1]
    if s[0] == "`":
        return body  # raw string: no escape processing (PromQL backticks)
    return (body.replace("\\\\", "\x00").replace('\\"', '"')
            .replace("\\'", "'").replace("\\n", "\n").replace("\\t", "\t")
            .replace("\x00", "\\"))


# ---------------------------------------------------------------------------
# time params

@dataclass(frozen=True)
class TimeStepParams:
    """Query range params (epoch seconds, like the HTTP API)."""

    start: int
    step: int
    end: int

    @property
    def start_ms(self) -> int:
        return self.start * 1000

    @property
    def end_ms(self) -> int:
        return self.end * 1000

    @property
    def step_ms(self) -> int:
        return self.step * 1000


def instant_params(time_sec: int) -> TimeStepParams:
    return TimeStepParams(time_sec, 0, time_sec)


# ---------------------------------------------------------------------------
# parser

class Parser:
    def __init__(self, text: str, params: TimeStepParams,
                 lookback_ms: int = DEFAULT_STALENESS_MS):
        self.toks = tokenize(text)
        self.i = 0
        self.params = params
        self.lookback = lookback_ms

    # -- token helpers --

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        if self.i >= len(self.toks):
            raise ParseError("unexpected end of query")
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind: str, text: str | None = None) -> Token:
        t = self.next()
        if t.kind != kind or (text is not None and t.text != text):
            raise ParseError(f"expected {text or kind}, got {t.text!r} at {t.pos}")
        return t

    def accept(self, kind: str, text: str | None = None) -> Token | None:
        t = self.peek()
        if t.kind == kind and (text is None or t.text == text):
            return self.next()
        return None

    # -- entry --

    def parse(self) -> lp.LogicalPlan:
        plan = self.parse_or()
        if self.peek().kind != "EOF":
            t = self.peek()
            raise ParseError(f"unexpected trailing input {t.text!r} at {t.pos}")
        return self._finalize(plan)

    def _finalize(self, plan) -> lp.LogicalPlan:
        """Wrap a bare selector / range expr into its periodic form."""
        if isinstance(plan, _Selector):
            return self._periodicize(plan)
        if isinstance(plan, _RangeExpr):
            raise ParseError("range expression must be wrapped in a function")
        return plan

    # -- precedence climbing --

    def parse_or(self):
        left = self.parse_and()
        while self.accept("KEYWORD", "or"):
            matching = self._vector_matching()
            right = self.parse_and()
            left = self._binary("or", left, right, matching)
        return left

    def parse_and(self):
        left = self.parse_comparison()
        while True:
            t = self.peek()
            if t.kind == "KEYWORD" and t.text in ("and", "unless"):
                self.next()
                matching = self._vector_matching()
                right = self.parse_comparison()
                left = self._binary(t.text, left, right, matching)
            else:
                return left

    def parse_comparison(self):
        left = self.parse_addsub()
        while self.peek().kind == "OP" and self.peek().text in (
                "==", "!=", "<", ">", "<=", ">="):
            op = self.next().text
            bool_mode = self.accept("KEYWORD", "bool") is not None
            matching = self._vector_matching()
            right = self.parse_addsub()
            left = self._binary(op, left, right, matching, bool_mode)
        return left

    def parse_addsub(self):
        left = self.parse_muldiv()
        while self.peek().kind == "OP" and self.peek().text in ("+", "-"):
            op = self.next().text
            matching = self._vector_matching()
            right = self.parse_muldiv()
            left = self._binary(op, left, right, matching)
        return left

    def parse_muldiv(self):
        left = self.parse_power()
        while ((self.peek().kind == "OP" and self.peek().text in ("*", "/", "%"))
               or (self.peek().kind == "KEYWORD" and self.peek().text == "atan2")):
            op = self.next().text
            matching = self._vector_matching()
            right = self.parse_power()
            left = self._binary(op, left, right, matching)
        return left

    def parse_power(self):
        left = self.parse_unary()
        if self.peek().kind == "OP" and self.peek().text == "^":
            self.next()
            matching = self._vector_matching()
            right = self.parse_power()  # right-associative
            left = self._binary("^", left, right, matching)
        return left

    def parse_unary(self):
        if self.peek().kind == "OP" and self.peek().text in ("+", "-"):
            op = self.next().text
            operand = self.parse_unary()
            if op == "-":
                return self._binary("*", _Scalar(-1.0), operand, None)
            return operand
        return self.parse_postfix()

    # -- atoms & postfix ([range], [sub:step], offset) --

    def parse_postfix(self):
        e = self.parse_atom()
        while True:
            if self.accept("OP", "["):
                first = self.expect("DURATION").text
                if self.accept("OP", ":"):
                    # subquery [window:step]
                    step_tok = self.accept("DURATION")
                    sub_step = (parse_duration_ms(step_tok.text,
                                                  self.params.step_ms)
                                if step_tok else 0)
                    self.expect("OP", "]")
                    window = parse_duration_ms(first, self.params.step_ms)
                    e = _Subquery(self._finalize(e), window, sub_step)
                else:
                    self.expect("OP", "]")
                    if not isinstance(e, _Selector):
                        raise ParseError("range selector on non-selector")
                    e = _RangeExpr(e, parse_duration_ms(first,
                                                        self.params.step_ms))
            elif self.accept("KEYWORD", "offset"):
                neg = self.accept("OP", "-") is not None
                d = parse_duration_ms(self.expect("DURATION").text,
                                      self.params.step_ms)
                d = -d if neg else d
                if isinstance(e, _Selector):
                    e = _Selector(e.filters, e.offset + d, e.at_ms, e.column)
                elif isinstance(e, _RangeExpr):
                    e = _RangeExpr(_Selector(e.sel.filters, e.sel.offset + d,
                                             e.sel.at_ms, e.sel.column),
                                   e.window)
                elif isinstance(e, _Subquery):
                    e = _Subquery(e.inner, e.window, e.step, e.offset + d,
                                  e.at_ms)
                else:
                    raise ParseError("offset on non-selector")
            elif self.accept("OP", "@"):
                at_ms = self._at_modifier()
                if isinstance(e, _Selector):
                    e = _Selector(e.filters, e.offset, at_ms, e.column)
                elif isinstance(e, _RangeExpr):
                    e = _RangeExpr(_Selector(e.sel.filters, e.sel.offset,
                                             at_ms, e.sel.column), e.window)
                elif isinstance(e, _Subquery):
                    e = _Subquery(e.inner, e.window, e.step, e.offset, at_ms)
                else:
                    raise ParseError("@ on non-selector")
            else:
                return e

    def _at_modifier(self) -> int:
        """Parse the @ timestamp: unix seconds, start(), or end()."""
        t = self.next()
        if t.kind == "NUMBER":
            return int(self._num(t.text) * 1000)
        if t.kind == "IDENT" and t.text in ("start", "end"):
            self.expect("OP", "(")
            self.expect("OP", ")")
            return (self.params.start_ms if t.text == "start"
                    else self.params.end_ms)
        raise ParseError(f"bad @ modifier {t.text!r} at {t.pos}")

    def parse_atom(self):
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return _Scalar(self._num(t.text))
        if t.kind == "DURATION":
            # bare durations act as second-scalars (promql extension)
            self.next()
            return _Scalar(parse_duration_ms(t.text, self.params.step_ms) / 1000.0)
        if t.kind == "STRING":
            self.next()
            return _Str(_unquote(t.text))
        if t.kind == "OP" and t.text == "(":
            self.next()
            inner = self.parse_or()
            self.expect("OP", ")")
            return inner
        if t.kind == "OP" and t.text == "{":
            return self._selector(None)
        if t.kind == "IDENT":
            name = self.next().text
            if name in lp.AGGREGATION_OPERATORS:
                return self._aggregation(name)
            if self.peek().kind == "OP" and self.peek().text == "(":
                return self._function(name)
            return self._selector(name)
        if t.kind == "KEYWORD" and t.text in ("and", "or", "unless"):
            # metric named like keyword — not supported, clearer error
            raise ParseError(f"unexpected keyword {t.text!r} at {t.pos}")
        raise ParseError(f"unexpected token {t.text!r} at {t.pos}")

    @staticmethod
    def _num(text: str) -> float:
        tl = text.lower()
        if tl == "inf":
            return float("inf")
        if tl == "nan":
            return float("nan")
        if tl.startswith("0x"):
            return float(int(text, 16))
        return float(text)

    # -- selectors --

    def _selector(self, metric: str | None):
        filters: list[ColumnFilter] = []
        column = None
        if metric is not None and "::" in metric:
            # filodb extension: metric::column selects a value column
            # (e.g. ds rollup columns min/max/sum/count/avg)
            metric, column = metric.split("::", 1)
        if metric is not None:
            filters.append(ColumnFilter(METRIC_LABEL, Equals(metric)))
        if self.accept("OP", "{"):
            while not self.accept("OP", "}"):
                label = self.next()
                if label.kind not in ("IDENT", "KEYWORD"):
                    raise ParseError(f"bad label name {label.text!r}")
                op = self.next().text
                val = _unquote(self.expect("STRING").text)
                lname = METRIC_LABEL if label.text == "__name__" else label.text
                if op == "=":
                    filters.append(ColumnFilter(lname, Equals(val)))
                elif op == "!=":
                    filters.append(ColumnFilter(lname, NotEquals(val)))
                elif op == "=~":
                    filters.append(ColumnFilter(lname, EqualsRegex(val)))
                elif op == "!~":
                    filters.append(ColumnFilter(lname, NotEqualsRegex(val)))
                else:
                    raise ParseError(f"bad matcher op {op!r}")
                if not self.accept("OP", ","):
                    self.expect("OP", "}")
                    break
        if not filters:
            raise ParseError("empty selector")
        return _Selector(tuple(filters), column=column)

    # -- vector matching clauses --

    def _vector_matching(self):
        on = None
        ignoring: tuple[str, ...] = ()
        card = "one-to-one"
        include: tuple[str, ...] = ()
        t = self.peek()
        if t.kind == "KEYWORD" and t.text in ("on", "ignoring"):
            self.next()
            labels = self._label_list()
            if t.text == "on":
                on = labels
            else:
                ignoring = labels
            t2 = self.peek()
            if t2.kind == "KEYWORD" and t2.text in ("group_left", "group_right"):
                self.next()
                card = ("many-to-one" if t2.text == "group_left"
                        else "one-to-many")
                if self.peek().kind == "OP" and self.peek().text == "(":
                    include = self._label_list()
            return (on, ignoring, card, include)
        return None

    def _label_list(self) -> tuple[str, ...]:
        self.expect("OP", "(")
        labels = []
        while not self.accept("OP", ")"):
            tok = self.next()
            if tok.kind not in ("IDENT", "KEYWORD"):
                raise ParseError(f"bad label {tok.text!r}")
            labels.append(tok.text)
            if not self.accept("OP", ","):
                self.expect("OP", ")")
                break
        return tuple(labels)

    # -- aggregations --

    def _aggregation(self, op: str):
        by: tuple[str, ...] = ()
        without: tuple[str, ...] = ()
        # prefix clause: sum by (x) (...)
        t = self.peek()
        if t.kind == "KEYWORD" and t.text in ("by", "without"):
            self.next()
            labels = self._label_list()
            if t.text == "by":
                by = labels
            else:
                without = labels
        self.expect("OP", "(")
        args = [self.parse_or()]
        while self.accept("OP", ","):
            args.append(self.parse_or())
        self.expect("OP", ")")
        # suffix clause
        t = self.peek()
        if t.kind == "KEYWORD" and t.text in ("by", "without"):
            self.next()
            labels = self._label_list()
            if t.text == "by":
                by = labels
            else:
                without = labels
        params: tuple = ()
        if op in ("topk", "bottomk", "quantile", "count_values"):
            if len(args) != 2:
                raise ParseError(f"{op} expects 2 arguments")
            p = args[0]
            if isinstance(p, _Scalar):
                params = (p.value,)
            elif isinstance(p, _Str):
                params = (p.value,)
            else:
                params = (p,)
            vec = args[1]
        else:
            if len(args) != 1:
                raise ParseError(f"{op} expects 1 argument")
            vec = args[0]
        return lp.Aggregate(op, self._finalize(vec), params, by, without)

    # -- functions --

    def _function(self, name: str):
        self.expect("OP", "(")
        args = []
        if not (self.peek().kind == "OP" and self.peek().text == ")"):
            args.append(self.parse_or())
            while self.accept("OP", ","):
                args.append(self.parse_or())
        self.expect("OP", ")")
        return self._build_function(name, args)

    def _build_function(self, name: str, args: list):
        p = self.params
        # range functions over a matrix/subquery argument
        if name in lp.RANGE_FUNCTIONS:
            if (name in ("timestamp", "last_over_time", "absent_over_time")
                    and len(args) == 1 and isinstance(args[0], _Selector)):
                # instant-vector forms: window = staleness lookback
                sel = args[0]
                raw = self._raw(sel, self.lookback)
                return lp.PeriodicSeriesWithWindowing(
                    raw, p.start_ms, p.step_ms, p.end_ms, self.lookback,
                    name, (), sel.offset)
            scalars_front: list[float] = []
            scalars_back: list[float] = []
            range_arg = None
            for a in args:
                if isinstance(a, (_RangeExpr, _Subquery)):
                    range_arg = a
                elif isinstance(a, _Scalar):
                    (scalars_front if range_arg is None
                     else scalars_back).append(a.value)
                else:
                    raise ParseError(f"{name}: unsupported argument")
            if range_arg is None:
                # last_over_time-style defaulting doesn't exist; timestamp()
                # takes an instant vector
                if name == "timestamp" and len(args) == 1 and isinstance(
                        args[0], _Selector):
                    sel = args[0]
                    raw = self._raw(sel, self.lookback)
                    return lp.PeriodicSeriesWithWindowing(
                        raw, p.start_ms, p.step_ms, p.end_ms, self.lookback,
                        "timestamp", (), sel.offset)
                raise ParseError(f"{name} needs a range-vector argument")
            fn_params = tuple(scalars_front + scalars_back)
            required = {"quantile_over_time": 1, "holt_winters": 2,
                        "predict_linear": 1}.get(name, 0)
            if len(fn_params) != required:
                raise ParseError(
                    f"{name} expects {required} scalar parameter(s), "
                    f"got {len(fn_params)}")
            if isinstance(range_arg, _Subquery):
                sub_step = range_arg.step or p.step_ms or 60_000
                return lp.SubqueryWithWindowing(
                    range_arg.inner, p.start_ms, p.step_ms, p.end_ms, name,
                    fn_params, range_arg.window, sub_step, range_arg.offset)
            sel = range_arg.sel
            raw = self._raw(sel, range_arg.window)
            psww = lp.PeriodicSeriesWithWindowing(
                raw, p.start_ms, p.step_ms, p.end_ms, range_arg.window,
                "present_over_time" if name == "absent_over_time" else name,
                fn_params, sel.offset, sel.at_ms)
            if name == "absent_over_time":
                # promql: 1 when NO matching series has samples in the window
                # (combine across series, like absent())
                return lp.ApplyAbsentFunction(
                    psww, sel.filters, p.start_ms, p.step_ms or 1000,
                    p.end_ms)
            return psww

        if name in lp.INSTANT_FUNCTIONS:
            if not args and name in ("hour", "minute", "month", "year",
                                     "day_of_month", "day_of_week",
                                     "day_of_year", "days_in_month"):
                # promql: zero-arg form defaults to vector(time())
                t = lp.ScalarTimeBasedPlan("time", p.start_ms,
                                           p.step_ms or 1000, p.end_ms)
                return lp.ApplyInstantFunction(lp.VectorPlan(t), name, ())
            vec = None
            fargs: list = []
            for a in args:
                if isinstance(a, _Scalar):
                    fargs.append(a.value)
                elif vec is None and isinstance(
                        a, (_Selector, lp.LogicalPlan, _Subquery)):
                    vec = a
                else:
                    # a second vector, or a string where a scalar parameter
                    # belongs: reject at parse time (the reference grammar
                    # types function params as scalars)
                    raise ParseError(
                        f"{name}: expected scalar parameter, got "
                        f"{type(a).__name__}")
            if vec is None:
                raise ParseError(f"{name} needs a vector argument")
            need = _INSTANT_FN_PARAMS.get(name)
            if need is not None:
                lo_n, hi_n = need if isinstance(need, tuple) else (need, need)
                if not lo_n <= len(fargs) <= hi_n:
                    raise ParseError(
                        f"{name} expects {need} parameter(s), "
                        f"got {len(fargs)}")
            return lp.ApplyInstantFunction(self._finalize(vec), name,
                                           tuple(fargs))

        if name == "absent":
            vec = self._finalize(args[0])
            filters = (args[0].filters if isinstance(args[0], _Selector)
                       else ())
            return lp.ApplyAbsentFunction(vec, filters, p.start_ms,
                                          p.step_ms or 1000, p.end_ms)
        if name in ("sort", "sort_desc"):
            return lp.ApplySortFunction(self._finalize(args[0]),
                                        name == "sort_desc")
        if name in ("label_replace", "label_join"):
            vec = self._finalize(args[0])
            fargs = tuple(a.value for a in args[1:]
                          if isinstance(a, (_Str, _Scalar)))
            if name == "label_replace" and len(fargs) != 4:
                raise ParseError("label_replace expects "
                                 "(v, dst, replacement, src, regex)")
            if name == "label_join" and len(fargs) < 2:
                raise ParseError("label_join expects "
                                 "(v, dst, sep, src...)")
            return lp.ApplyMiscellaneousFunction(vec, name, fargs)
        if name == "scalar":
            if not args:
                raise ParseError("scalar expects one vector argument")
            return lp.ScalarVaryingDoublePlan(self._finalize(args[0]))
        if name == "vector":
            if not args:
                raise ParseError("vector expects one scalar argument")
            sc = args[0]
            if isinstance(sc, _Scalar):
                sc = lp.ScalarFixedDoublePlan(sc.value, p.start_ms,
                                              p.step_ms or 1000, p.end_ms)
            return lp.VectorPlan(sc)
        if name == "time":
            return lp.ScalarTimeBasedPlan("time", p.start_ms,
                                          p.step_ms or 1000, p.end_ms)
        if name == "pi":
            return lp.ScalarFixedDoublePlan(3.141592653589793, p.start_ms,
                                            p.step_ms or 1000, p.end_ms)
        if name == "limit":  # filodb extension
            return lp.ApplyLimitFunction(self._finalize(args[1]),
                                         int(args[0].value))
        raise ParseError(f"unknown function {name!r}")

    # -- plan construction helpers --

    def _raw(self, sel: "_Selector", lookback: int) -> lp.RawSeries:
        p = self.params
        if sel.at_ms is not None:
            # @ pins evaluation: the chunk range collapses to that instant
            return lp.RawSeries(sel.filters, sel.at_ms, sel.at_ms, lookback,
                                sel.offset, sel.column)
        return lp.RawSeries(sel.filters, p.start_ms, p.end_ms, lookback,
                            sel.offset, sel.column)

    def _periodicize(self, sel: "_Selector") -> lp.PeriodicSeries:
        p = self.params
        return lp.PeriodicSeries(self._raw(sel, self.lookback), p.start_ms,
                                 p.step_ms, p.end_ms, sel.offset, sel.at_ms)

    def _binary(self, op, left, right, matching, bool_mode: bool = False):
        on, ignoring, card, include = matching or (None, (), "one-to-one", ())
        lscalar = isinstance(left, (_Scalar, lp.ScalarFixedDoublePlan,
                                    lp.ScalarTimeBasedPlan,
                                    lp.ScalarVaryingDoublePlan,
                                    lp.ScalarBinaryOperation))
        rscalar = isinstance(right, (_Scalar, lp.ScalarFixedDoublePlan,
                                     lp.ScalarTimeBasedPlan,
                                     lp.ScalarVaryingDoublePlan,
                                     lp.ScalarBinaryOperation))
        p = self.params
        if lscalar and rscalar:
            lv = (left.value if isinstance(left, (_Scalar,
                                                  lp.ScalarFixedDoublePlan))
                  else left)
            rv = (right.value if isinstance(right, (_Scalar,
                                                    lp.ScalarFixedDoublePlan))
                  else right)
            if isinstance(lv, float) and isinstance(rv, float):
                from filodb_tpu.query.engine.instantfns import apply_binary_op
                import numpy as np
                out = float(np.asarray(apply_binary_op(
                    op, np.float64(lv), np.float64(rv), bool_mode)))
                return lp.ScalarFixedDoublePlan(out, p.start_ms,
                                                p.step_ms or 1000, p.end_ms)
            return lp.ScalarBinaryOperation(op, lv, rv, p.start_ms,
                                            p.step_ms or 1000, p.end_ms)
        if lscalar or rscalar:
            scalar = left if lscalar else right
            vector = right if lscalar else left
            if isinstance(scalar, _Scalar):
                scalar = lp.ScalarFixedDoublePlan(scalar.value, p.start_ms,
                                                  p.step_ms or 1000, p.end_ms)
            return lp.ScalarVectorBinaryOperation(
                op, scalar, self._finalize(vector), scalar_is_lhs=lscalar,
                bool_mode=bool_mode)
        if op in ("and", "or", "unless"):
            card = "many-to-many"
        return lp.BinaryJoin(self._finalize(left), op, self._finalize(right),
                             card, on, ignoring, include, bool_mode)


# -- intermediate parse nodes (not logical plans) --


@dataclass(frozen=True)
class _Scalar:
    value: float


@dataclass(frozen=True)
class _Str:
    value: str


@dataclass(frozen=True)
class _Selector:
    filters: tuple[ColumnFilter, ...]
    offset: int = 0
    at_ms: "int | None" = None
    column: "str | None" = None


@dataclass(frozen=True)
class _RangeExpr:
    sel: _Selector
    window: int


@dataclass(frozen=True)
class _Subquery:
    inner: lp.LogicalPlan
    window: int
    step: int
    offset: int = 0
    at_ms: "int | None" = None


# ---------------------------------------------------------------------------

def parse_query(text: str, params: TimeStepParams,
                lookback_ms: int = DEFAULT_STALENESS_MS) -> lp.LogicalPlan:
    """Parse a PromQL query into a LogicalPlan for the given time params
    (reference ``Parser.queryRangeToLogicalPlan``; ``lookback_ms`` is the
    instant-selector staleness window, reference QueryConfig
    ``staleSampleAfterMs``)."""
    return Parser(text, params, lookback_ms).parse()


def parse_instant_query(text: str, time_sec: int) -> lp.LogicalPlan:
    return parse_query(text, instant_params(time_sec))
