"""Ingestion routing helpers: record streams → owning shards.

Counterpart of the reference's gateway shard routing + IngestionActor
plumbing (``ShardMapper.ingestionShard``, ``IngestionActor.scala:43-57``):
computes each record's shard from its partition key and feeds per-shard
containers into the memstore.
"""

from __future__ import annotations

from collections import defaultdict

from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.partkey import ingestion_shard
from filodb_tpu.core.record import RecordContainer, SomeData


def route_container(container: RecordContainer, num_shards: int, spread: int,
                    shard_key_labels=("_ws_", "_ns_", "_metric_")
                    ) -> dict[int, RecordContainer]:
    """Split one container into per-shard containers by partition-key hash."""
    out: dict[int, RecordContainer] = defaultdict(RecordContainer)
    for rec in container:
        skh = rec.part_key.shard_key_hash(shard_key_labels)
        shard = ingestion_shard(skh, rec.part_key.part_hash, num_shards,
                                spread)
        out[shard].add(rec)
    return out


def ingest_routed(memstore: TimeSeriesMemStore, dataset: str, stream,
                  num_shards: int, spread: int = 0) -> int:
    """Ingest a SomeData stream, routing records to the owning shards
    (gateway-equivalent path for in-process tests/benchmarks)."""
    total = 0
    for data in stream:
        for shard, container in route_container(data.container, num_shards,
                                                spread).items():
            total += memstore.ingest(dataset, shard,
                                     SomeData(container, data.offset))
    return total
