"""LongTimeRangePlanner: route/split queries between raw and downsampled data.

Counterpart of reference ``queryplanner/LongTimeRangePlanner.scala:1-135``:
queries entirely within raw retention go to the raw cluster planner; queries
entirely older go to the downsample planner; straddling queries split at the
earliest-raw-time step boundary and the two ExecPlans are stitched
(``StitchRvsExec``).

Range functions are rewritten for the ds-gauge rollup schema (reference: the
downsample schema's column selection): min/max/sum_over_time read the
corresponding rollup column; count_over_time sums the count column.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass

from filodb_tpu.coordinator.planner import QueryPlanner, SingleClusterPlanner, _retime
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec.plan import ExecPlan, StitchRvsExec
from filodb_tpu.query.model import QueryContext

# range fn -> (ds column, replacement fn)
_DS_FN_MAP = {
    "min_over_time": ("min", "min_over_time"),
    "max_over_time": ("max", "max_over_time"),
    "sum_over_time": ("sum", "sum_over_time"),
    "count_over_time": ("count", "sum_over_time"),
}


def rewrite_for_downsample(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        if plan.function == "avg_over_time" and plan.raw.column is None:
            # EXACT average over rollups: Σ(sum col) / Σ(count col)
            # (reference dAvgAc: average carries its count)
            num = dataclasses.replace(
                plan, raw=dataclasses.replace(plan.raw, column="sum"),
                function="sum_over_time")
            den = dataclasses.replace(
                plan, raw=dataclasses.replace(plan.raw, column="count"),
                function="sum_over_time")
            return lp.BinaryJoin(num, "/", den)
        m = _DS_FN_MAP.get(plan.function)
        if m is not None and plan.raw.column is None:
            col, fn = m
            raw = dataclasses.replace(plan.raw, column=col)
            return dataclasses.replace(plan, raw=raw, function=fn)
        return plan
    if dataclasses.is_dataclass(plan):
        changes = {}
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, lp.LogicalPlan):
                changes[f.name] = rewrite_for_downsample(v)
        if changes:
            return dataclasses.replace(plan, **changes)
    return plan


def _plan_times(plan: lp.LogicalPlan):
    """(start, step, end, max_lookback) over the plan tree."""
    lo, st, hi, lb = [], [], [], [0]

    def walk(p):
        if isinstance(p, (lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing,
                          lp.SubqueryWithWindowing)):
            lo.append(p.start)
            st.append(p.step)
            hi.append(p.end)
            if isinstance(p, lp.PeriodicSeriesWithWindowing):
                lb.append(p.window + p.offset)
            elif isinstance(p, lp.SubqueryWithWindowing):
                lb.append(p.subquery_window + p.offset)
            else:
                lb.append(300_000 + p.offset)
        if dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)

    walk(plan)
    if not lo:
        return None
    return min(lo), max(st), max(hi), max(lb)


@dataclass
class LongTimeRangePlanner(QueryPlanner):
    raw_planner: SingleClusterPlanner
    ds_planner: SingleClusterPlanner
    raw_retention_ms: int
    now_ms: "callable" = lambda: int(time.time() * 1000)

    def mem_only(self, plan: lp.LogicalPlan) -> bool:
        """True when the whole range (incl. lookback) is served from raw
        memstore data — the mesh engine may bypass tier routing only then."""
        times = _plan_times(plan)
        if times is None:
            return True
        start, _step, _end, lookback = times
        return start - lookback >= self.now_ms() - self.raw_retention_ms

    def cost_hint(self, plan: lp.LogicalPlan):
        """Governor cost class: touching the downsample tier pages chunks
        from the column store, so class it EXPENSIVE regardless of shape."""
        if self.mem_only(plan):
            return None
        from filodb_tpu.utils.governor import EXPENSIVE
        return EXPENSIVE

    def materialize(self, plan: lp.LogicalPlan,
                    qcontext: QueryContext | None = None) -> ExecPlan:
        qcontext = qcontext or QueryContext()
        times = _plan_times(plan)
        if times is None:
            return self.raw_planner.materialize(plan, qcontext)
        start, step, end, lookback = times
        earliest_raw = self.now_ms() - self.raw_retention_ms
        if start - lookback >= earliest_raw:
            return self.raw_planner.materialize(plan, qcontext)
        if end < earliest_raw:
            return self.ds_planner.materialize(rewrite_for_downsample(plan),
                                               qcontext)
        # straddling: first step whose full window lies in raw data
        step = max(step, 1)
        boundary = start
        while boundary - lookback < earliest_raw and boundary <= end:
            boundary += step
        ds_end = boundary - step
        parts = []
        if ds_end >= start:
            ds_plan = rewrite_for_downsample(_retime(plan, start, step,
                                                     ds_end))
            parts.append(self.ds_planner.materialize(ds_plan, qcontext))
        if boundary <= end:
            raw_plan = _retime(plan, boundary, step, end)
            parts.append(self.raw_planner.materialize(raw_plan, qcontext))
        if len(parts) == 1:
            return parts[0]
        return StitchRvsExec(children_plans=parts)
