"""Cluster bootstrap: seed discovery and remote-node membership.

Counterpart of reference ``akka-bootstrapper``
(``akka-bootstrapper/.../AkkaBootstrapper.scala:1-104``; strategies: explicit
list, Consul, DNS SRV — ``DnsSrvClusterSeedDiscovery.scala``) plus the piece
Akka gave the reference for free: remote membership. Discovery yields seed
addresses; a joining server calls ``join`` on a seed's control port; the
coordinator (the seed's ``FilodbCluster``) tracks the member as a
``RemoteNodeHandle`` and drives its shard lifecycle over the same TCP channel
used for plan shipping (start_shard / shard_status / ping).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from filodb_tpu.coordinator.remote import RemotePlanDispatcher
from filodb_tpu.coordinator.shardmapper import ShardStatus

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# seed discovery (reference: ClusterSeedDiscovery strategies)

class SeedDiscovery:
    def discover(self) -> list[tuple[str, int]]:
        raise NotImplementedError


@dataclass
class ExplicitListDiscovery(SeedDiscovery):
    """Reference ``ExplicitListClusterSeedDiscovery``: static seed list."""

    seeds: list[str] = field(default_factory=list)  # "host:port"

    def discover(self):
        out = []
        for s in self.seeds:
            host, port = s.rsplit(":", 1)
            out.append((host, int(port)))
        return out


@dataclass
class FileDiscovery(SeedDiscovery):
    """Shared-file membership registry (the single-host / shared-volume
    analog of Consul registration)."""

    path: str = ""

    def discover(self):
        import os
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    host, port = line.rsplit(":", 1)
                    out.append((host, int(port)))
        return out

    def register(self, host: str, port: int) -> None:
        with open(self.path, "a") as f:
            f.write(f"{host}:{port}\n")


@dataclass
class DnsSrvDiscovery(SeedDiscovery):
    """Reference ``DnsSrvClusterSeedDiscovery``: resolve SRV records via the
    built-in wire-format resolver (``utils/dns_srv.py`` — no dnspython in
    the image). ``server``/``port`` pin a resolver (tests use a stub);
    otherwise /etc/resolv.conf or $FILODB_DNS_SERVER decides. Resolution
    failure logs and yields no seeds (bootstrap retries, as the reference's
    retry loop does)."""

    srv_name: str = ""
    server: str | None = None
    port: int | None = None

    def discover(self):
        from filodb_tpu.utils.dns_srv import DnsError, resolve_srv
        try:
            records = resolve_srv(self.srv_name, server=self.server,
                                  port=self.port)
        except (DnsError, OSError) as e:
            log.warning("DNS SRV discovery for %s failed: %s",
                        self.srv_name, e)
            return []
        return [(r.target, r.port) for r in records]


@dataclass
class ConsulDiscovery(SeedDiscovery):
    """Reference ``ConsulClient.scala`` + the Consul seed strategy of
    ``ClusterSeedDiscovery``: nodes register themselves with the local
    Consul agent (PUT ``/v1/agent/service/register``) and discover seeds
    from the health endpoint (GET ``/v1/health/service/<name>?passing``).
    Speaks Consul's actual HTTP API via urllib — point it at a real agent
    or the protocol-level fake in tests."""

    host: str = "127.0.0.1"
    port: int = 8500
    service_name: str = "filodb"
    timeout: float = 5.0

    def _url(self, path: str) -> str:
        return f"http://{self.host}:{self.port}{path}"

    def discover(self):
        import json
        import urllib.request
        try:
            with urllib.request.urlopen(
                    self._url(f"/v1/health/service/{self.service_name}"
                              "?passing=true"),
                    timeout=self.timeout) as r:
                entries = json.loads(r.read())
        except OSError as e:
            log.warning("consul discovery for %s failed: %s",
                        self.service_name, e)
            return []
        out = []
        for e in entries:
            svc = e.get("Service", {})
            addr = svc.get("Address") or e.get("Node", {}).get("Address")
            port = svc.get("Port")
            if addr and port:
                out.append((addr, int(port)))
        # deterministic seed order (the reference sorts addresses so all
        # nodes elect the same head seed)
        return sorted(out)

    def register(self, service_id: str, host: str, port: int) -> None:
        import json
        import urllib.request
        payload = json.dumps({
            "ID": service_id, "Name": self.service_name,
            "Address": host, "Port": port}).encode()
        req = urllib.request.Request(
            self._url("/v1/agent/service/register"), data=payload,
            method="PUT", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            if r.status >= 300:
                raise OSError(f"consul register failed: {r.status}")

    def deregister(self, service_id: str) -> None:
        import urllib.request
        req = urllib.request.Request(
            self._url(f"/v1/agent/service/deregister/{service_id}"),
            data=b"", method="PUT")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            if r.status >= 300:
                raise OSError(f"consul deregister failed: {r.status}")


# ---------------------------------------------------------------------------
# remote membership

class RemoteNodeHandle:
    """A cluster member in another process, driven over its control port.
    Duck-types the in-process ``Node`` API the cluster uses."""

    def __init__(self, name: str, host: str, control_port: int):
        self.name = name
        self.host = host
        self.executor_port = control_port
        self._client = RemotePlanDispatcher(host, control_port)

    @property
    def alive(self) -> bool:
        return self._client.ping()

    def start_shard(self, dataset: str, shard: int, config=None,
                    shard_log=None, on_status=None) -> None:
        self._client.call("start_shard", dataset, shard)
        if on_status:
            # remote recovery progress is polled via shard_status
            on_status(shard, ShardStatus.RECOVERY, 0)

    def stop_shard(self, dataset: str, shard: int) -> None:
        try:
            self._client.call("stop_shard", dataset, shard)
        except (ConnectionError, OSError, RuntimeError):
            pass

    def shard_status(self, dataset: str) -> list[tuple[int, str]]:
        return self._client.call("shard_status", dataset)

    def prepare_handoff(self, dataset: str, shard: int) -> int:
        """Migration SYNC on a remote source: flush, drain durable
        writes, snapshot the index; returns the shard's replay offset."""
        return self._client.call("prepare_handoff", dataset, shard)

    def shard_offset(self, dataset: str, shard: int) -> int:
        try:
            return self._client.call("shard_offset", dataset, shard)
        except (ConnectionError, OSError, RuntimeError):
            return -1

    def owned_shards(self, dataset: str) -> list[int]:
        try:
            return [s for s, _ in self.shard_status(dataset)]
        except (ConnectionError, OSError, RuntimeError):
            return []

    def kill(self) -> None:  # coordinator-side bookkeeping only
        pass


class ShardUpdateSubscriber:
    """Member-side mirror of the coordinator's shard map (reference
    ``StatusActor`` subscriber with ack/resync): polls the sequenced event
    feed, applying deltas to a local ``ShardMapper``; a feed gap triggers a
    full-snapshot resync. The member acks implicitly with its next poll's
    ``since_seq``."""

    def __init__(self, dataset: str, num_shards: int, dispatcher):
        from filodb_tpu.coordinator.shardmapper import ShardMapper
        self.dataset = dataset
        self.dispatcher = dispatcher
        self.mapper = ShardMapper(num_shards)
        self.last_seq = 0
        self.epoch = None  # feed-generation token; change forces resync
        self.resyncs = 0

    def poll(self) -> int:
        """One poll cycle; returns events applied. The follower echoes the
        feed epoch it last saw: a restarted coordinator (new epoch) always
        answers with a snapshot, even when the stale ack happens to land
        inside the new feed's sequence range."""
        from filodb_tpu.coordinator.shardmapper import (
            ShardEvent,
            ShardMapper,
            ShardStatus,
        )
        events, seq, resynced, epoch = self.dispatcher.call(
            "shard_events", self.dataset, self.last_seq, self.epoch)
        if resynced:
            self.mapper = ShardMapper(self.mapper.num_shards)
            self.resyncs += 1
        for shard, status_name, node, progress, *rest in events:
            # rest = (replica, watermark) since replica sets; *rest keeps
            # this reader compatible with further wire growth
            replica = bool(rest[0]) if len(rest) > 0 else False
            watermark = int(rest[1]) if len(rest) > 1 else -1
            self.mapper.apply(ShardEvent(int(shard),
                                         ShardStatus[status_name], node,
                                         int(progress), replica=replica,
                                         watermark=watermark))
        self.last_seq = seq
        self.epoch = epoch
        return len(events)


def poll_remote_statuses(cluster, dataset: str) -> None:
    """Pull shard statuses from remote members into the shard manager
    (stands in for the reference's status events over Akka)."""
    sm = cluster.shard_managers.get(dataset)
    if sm is None:
        return
    for name, node in list(cluster.nodes.items()):
        if not isinstance(node, RemoteNodeHandle):
            continue
        try:
            statuses = node.shard_status(dataset)
        except (ConnectionError, OSError, RuntimeError):
            continue
        for shard, status in statuses:
            if sm.mapper.node_for(shard) != name:
                continue
            if status == "active" and sm.mapper.statuses[shard] != \
                    ShardStatus.ACTIVE:
                sm.shard_active(shard, name)
            elif status == "recovery" and sm.mapper.statuses[shard] == \
                    ShardStatus.ASSIGNED:
                sm.shard_recovery(shard, name, 0)


# ---------------------------------------------------------------------------
# member registry + coordinator failover

class MemberRegistry:
    """Append-only shared membership file: ``role,name,host,port`` lines.
    The coordinator role is the LAST coord line whose process still answers
    pings — the deterministic election substrate for singleton failover
    (reference: Akka cluster-singleton hand-off,
    ``ClusterSingletonFailoverSpec``)."""

    def __init__(self, path: str):
        import os
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def register(self, role: str, name: str, host: str, port: int) -> None:
        with open(self.path, "a") as f:
            f.write(f"{role},{name},{host},{port}\n")

    def read(self) -> list[tuple[str, str, str, int]]:
        import os
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                role, name, host, port = line.split(",")
                out.append((role, name, host, int(port)))
        return out

    def members(self) -> dict[str, tuple[str, str, int]]:
        """name -> (role, host, port); later lines win."""
        out = {}
        for role, name, host, port in self.read():
            out[name] = (role, host, port)
        return out

    def current_coordinator(self) -> str | None:
        coord = None
        for role, name, _, _ in self.read():
            if role == "coord":
                coord = name
        return coord


def alive_members(registry: MemberRegistry,
                  exclude: str | None = None) -> dict[str, tuple[str, int]]:
    """Ping every registered member; returns name -> (host, port) of the
    ones answering."""
    out = {}
    for name, (_, host, port) in registry.members().items():
        if name == exclude:
            continue
        if RemotePlanDispatcher(host, port, timeout=1.0).ping():
            out[name] = (host, port)
    return out
