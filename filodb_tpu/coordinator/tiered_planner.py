"""TieredPlanner: route one query_range across memstore, object-store
history, and the downsample tier.

Generalizes :class:`LongTimeRangePlanner` (raw vs downsample, two tiers)
to the three-tier retention layout (ROADMAP open item 3):

- ``memstore``   — raw data resident in memory, newest.
- ``objectstore``— raw data older than memory retention but inside raw
  retention: served by a :class:`ColdTierStore` facade whose chunks page
  in through ranged GETs into the ODP cache.
- ``downsample`` — rollups older than raw retention, with the
  ``LongTimeRangePlanner`` column rewrites reused verbatim.

Each tier's sub-plan is wrapped in a :class:`TierExec` (per-tier
QueryStats attribution) and the parts are stitched with
``StitchRvsExec`` — the same seam semantics as the two-tier planner:
``route_tiers`` assigns every step to exactly one tier and satisfies
lookback windows across seams, so nothing is double-counted or dropped.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from filodb_tpu.coordinator.longtime_planner import (
    _plan_times,
    rewrite_for_downsample,
)
from filodb_tpu.coordinator.planner import (
    QueryPlanner,
    SingleClusterPlanner,
    _retime,
)
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec.plan import ExecPlan, StitchRvsExec
from filodb_tpu.query.federation import (
    DOWNSAMPLE,
    MEMSTORE,
    OBJECTSTORE,
    ColdTierStore,
    TierExec,
    fed_queries,
    route_tiers,
)
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.governor import EXPENSIVE


@dataclass
class TieredPlanner(QueryPlanner):
    """Retention-tier router; drop-in for ``LongTimeRangePlanner``."""

    raw_planner: SingleClusterPlanner
    cold_planner: SingleClusterPlanner
    ds_planner: "SingleClusterPlanner | None" = None
    # data floors, as retention relative to now_ms(): memory keeps
    # mem_retention_ms of raw data; the durable store keeps
    # raw_retention_ms of raw data (older exists only downsampled).
    mem_retention_ms: int = 0
    raw_retention_ms: "int | None" = None
    now_ms: "callable" = field(
        default=lambda: int(time.time() * 1000))

    def _floors(self) -> tuple[int, "int | None"]:
        now = self.now_ms()
        raw_floor = None if self.raw_retention_ms is None \
            or self.ds_planner is None else now - self.raw_retention_ms
        return now - self.mem_retention_ms, raw_floor

    # -- admission hooks (coordinator/query_service.py) -------------------

    def mem_only(self, plan: lp.LogicalPlan) -> bool:
        """True when the memstore tier alone serves the whole plan —
        the mesh engine may bypass tier routing only then."""
        times = _plan_times(plan)
        if times is None:
            return True
        start, _, _, lookback = times
        mem_floor, _ = self._floors()
        return start - lookback >= mem_floor

    def cost_hint(self, plan: lp.LogicalPlan) -> "str | None":
        """Cold-tier sub-queries are EXPENSIVE for the governor no
        matter their shape: even an instant query that pages object
        store segments sheds before CHEAP memstore traffic."""
        return None if self.mem_only(plan) else EXPENSIVE

    def version_token(self) -> int:
        """Cache-key token folded into the result cache's plan
        signature: bumps when the cold/ds part-key indexes grow, so
        settled extents don't outlive tier membership changes."""
        tok = 0
        for planner in (self.cold_planner, self.ds_planner):
            store = getattr(planner, "store", None)
            if store is None:
                continue
            for s in store.shards_for(store.dataset):
                tok += s.data_version
        return tok

    # -- status introspection ---------------------------------------------

    def tier_detail(self) -> dict:
        mem_floor, raw_floor = self._floors()
        tiers = []
        cold_store = getattr(self.cold_planner, "store", None)
        if isinstance(cold_store, ColdTierStore):
            tiers.append({"tier": OBJECTSTORE, "floorMs": raw_floor,
                          "ceilMs": mem_floor, **cold_store.tier_stats()})
        ds_store = getattr(self.ds_planner, "store", None) \
            if self.ds_planner is not None else None
        if ds_store is not None:
            shards = ds_store.shards_for(ds_store.dataset)
            for s in shards:  # index bootstraps lazily; a status probe
                if not getattr(s, "_refreshed", True):  # should see data
                    s.refresh_index()
            series = sum(getattr(s, "num_partitions", 0) for s in shards)
            entry = {"tier": DOWNSAMPLE, "series": series, "bytes": None,
                     "floorMs": None, "ceilMs": raw_floor,
                     "resolutionMs": getattr(ds_store, "resolution_ms",
                                             None)}
            stats_fn = getattr(ds_store.column_store, "dataset_stats", None)
            if stats_fn is not None:
                entry["bytes"] = stats_fn(
                    getattr(ds_store, "ds_dataset",
                            ds_store.dataset)).get("bytes")
            tiers.append(entry)
        return {"memFloorMs": mem_floor, "rawFloorMs": raw_floor,
                "tiers": tiers}

    # -- materialization --------------------------------------------------

    def materialize(self, plan: lp.LogicalPlan,
                    qcontext: QueryContext | None = None) -> ExecPlan:
        qcontext = qcontext or QueryContext()
        times = _plan_times(plan)
        if times is None:  # metadata plans: fan out over the raw tier
            return self.raw_planner.materialize(plan, qcontext)
        start, step, end, lookback = times
        mem_floor, raw_floor = self._floors()
        ranges = route_tiers(start, step, end, lookback, mem_floor,
                             raw_floor)
        if len(ranges) == 1 and ranges[0].tier == MEMSTORE:
            # hot path untouched: no retime, no TierExec indirection
            return self.raw_planner.materialize(plan, qcontext)
        fed_queries.inc()
        parts: list[ExecPlan] = []
        for r in ranges:
            sub = plan if (r.start == start and r.end == end) \
                else _retime(plan, r.start, step, r.end)
            if r.tier == MEMSTORE:
                ep = self.raw_planner.materialize(sub, qcontext)
            elif r.tier == OBJECTSTORE:
                ep = self.cold_planner.materialize(sub, qcontext)
            else:
                ep = self.ds_planner.materialize(
                    rewrite_for_downsample(sub), qcontext)
            parts.append(TierExec(tier=r.tier, children_plans=[ep]))
        if len(parts) == 1:
            return parts[0]
        return StitchRvsExec(children_plans=parts)


def build_tiered_planner(raw_planner: SingleClusterPlanner,
                         column_store, dataset: str, num_shards: int,
                         spread: int = 0, *,
                         mem_retention_ms: int,
                         raw_retention_ms: "int | None" = None,
                         ds_planner: "SingleClusterPlanner | None" = None,
                         odp_max_chunks: int = 10_000,
                         refresh_s: float = 60.0,
                         schemas=None,
                         now_ms=None) -> TieredPlanner:
    """Wire the cold (object-store history) tier and return the planner.
    ``ds_planner`` is the downsample tier from the existing wiring; pass
    None for a two-tier memstore/objectstore layout."""
    cold_store = ColdTierStore(column_store, dataset, num_shards,
                               schemas=schemas,
                               odp_max_chunks=odp_max_chunks,
                               refresh_s=refresh_s)
    cold_planner = SingleClusterPlanner(dataset, num_shards, spread,
                                        store=cold_store)
    kw = {} if now_ms is None else {"now_ms": now_ms}
    return TieredPlanner(raw_planner, cold_planner, ds_planner,
                         mem_retention_ms=mem_retention_ms,
                         raw_retention_ms=raw_retention_ms, **kw)
