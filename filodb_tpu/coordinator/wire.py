"""Typed wire codec for plan shipping — the Kryo replacement, without pickle.

Counterpart of the reference's Kryo serializer registration
(``coordinator/src/main/scala/filodb.coordinator/client/Serializer.scala:
23-64``, ``FiloKryoSerializers.scala``): a closed registry of serializable
classes (exec plans, transformers, filters, query model, results) encoded as
a tagged binary tree. Decoding instantiates ONLY registered classes — unlike
pickle, a hostile peer cannot execute code, and frames are length-capped.

Format (little-endian): one tagged value.
    N/T/F  none/true/false            I i64     F f64
    S/B    u32 len + utf8/bytes       L/U u32 count + values (list/tuple)
    D      u32 count + (key, value)*
    A      dtype str | u8 ndim | i64 shape* | raw bytes
    O      class-name str | u16 nfields | (name str, value)*
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

MAX_FRAME = 256 * 1024 * 1024  # hard cap on any frame (DoS guard)


def _build_registry() -> dict[str, type]:
    """All classes allowed on the wire. Subclass walks keep the registry in
    step with new exec nodes/transformers/filters automatically."""
    # import every module that defines wire classes BEFORE walking
    # subclasses — the registry must not depend on process import order
    from filodb_tpu.coordinator import cluster  # noqa: F401
    from filodb_tpu.coordinator import remote  # noqa: F401
    from filodb_tpu.coordinator.mesh_cluster import LoweredDescriptor
    from filodb_tpu.core.filters import ColumnFilter, Filter
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.memory.chunk import Chunk, ColumnSummary
    from filodb_tpu.memory.codecs import HistogramColumn
    from filodb_tpu.query import exec as _exec  # noqa: F401
    from filodb_tpu.query.exec import binaryjoin  # noqa: F401
    from filodb_tpu.query.exec import remote_exec  # noqa: F401
    from filodb_tpu.query.exec import transformers as _tr
    from filodb_tpu.query.exec.plan import ExecPlan, PlanDispatcher
    from filodb_tpu.query.model import (
        PlannerParams,
        QueryContext,
        QueryResult,
        QueryStats,
        RangeVectorKey,
        ScalarResult,
        StepMatrix,
        TraceContext,
    )
    from filodb_tpu.coordinator.migration import MigrationManifest
    from filodb_tpu.utils.governor import QueryBudget

    reg: dict[str, type] = {}

    def walk(base):
        for cls in base.__subclasses__():
            reg[cls.__name__] = cls
            walk(cls)

    for base in (ExecPlan, PlanDispatcher, Filter,
                 _tr.RangeVectorTransformer):
        reg[base.__name__] = base
        walk(base)
    for cls in (ColumnFilter, PartKey, Chunk, ColumnSummary, HistogramColumn,
                LoweredDescriptor, MigrationManifest, PlannerParams,
                QueryBudget, QueryContext, QueryResult, QueryStats,
                RangeVectorKey, ScalarResult, StepMatrix, TraceContext):
        reg[cls.__name__] = cls
    return reg


_REGISTRY: dict[str, type] | None = None


def registry() -> dict[str, type]:
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _build_registry()
    return _REGISTRY


# ---------------------------------------------------------------------------
# encode

def encode(obj) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def _enc_str(s: str, out: bytearray) -> None:
    b = s.encode()
    out += struct.pack("<I", len(b))
    out += b


def _enc(obj, out: bytearray) -> None:  # noqa: C901
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif isinstance(obj, int):
        out += b"I"
        out += struct.pack("<q", obj)
    elif isinstance(obj, float):
        out += b"f"
        out += struct.pack("<d", obj)
    elif isinstance(obj, str):
        out += b"S"
        _enc_str(obj, out)
    elif isinstance(obj, bytes):
        out += b"B"
        out += struct.pack("<I", len(obj))
        out += obj
    elif isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        out += b"A"
        _enc_str(a.dtype.str, out)
        out += struct.pack("<B", a.ndim)
        out += struct.pack(f"<{a.ndim}q", *a.shape)
        out += a.tobytes()
    elif isinstance(obj, (np.integer,)):
        out += b"I"
        out += struct.pack("<q", int(obj))
    elif isinstance(obj, (np.floating,)):
        out += b"f"
        out += struct.pack("<d", float(obj))
    elif isinstance(obj, list):
        out += b"L"
        out += struct.pack("<I", len(obj))
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, tuple):
        out += b"U"
        out += struct.pack("<I", len(obj))
        for x in obj:
            _enc(x, out)
    elif isinstance(obj, (set, frozenset)):
        out += b"Z"
        out += struct.pack("<I", len(obj))
        for x in sorted(obj, key=repr):
            _enc(x, out)
    elif isinstance(obj, dict):
        out += b"D"
        out += struct.pack("<I", len(obj))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    else:
        cls = type(obj)
        name = cls.__name__
        if registry().get(name) is not cls:
            raise TypeError(f"{name} is not wire-serializable (register it)")
        fields = _wire_fields(cls, obj)
        out += b"O"
        _enc_str(name, out)
        out += struct.pack("<H", len(fields))
        for fname, val in fields:
            _enc_str(fname, out)
            _enc(val, out)


def _wire_fields(cls, obj) -> list[tuple[str, object]]:
    if dataclasses.is_dataclass(cls):
        return [(f.name, getattr(obj, f.name)) for f in
                dataclasses.fields(cls) if f.init]
    # non-dataclass registered classes expose __wire_fields__
    names = getattr(cls, "__wire_fields__", None)
    if names is None:
        raise TypeError(f"{cls.__name__} has no wire fields")
    return [(n, getattr(obj, n)) for n in names]


# ---------------------------------------------------------------------------
# decode

def decode(data: bytes):
    obj, off = _dec(data, 0)
    if off != len(data):
        raise ValueError(f"trailing bytes after wire value: {len(data) - off}")
    return obj


def _need(data: bytes, off: int, n: int) -> None:
    if off + n > len(data):
        raise ValueError(f"wire frame truncated: need {n} at {off}, "
                         f"have {len(data) - off}")


def _dec_str(data: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    _need(data, off, n)
    return data[off : off + n].decode(), off + n


def _dec(data: bytes, off: int):  # noqa: C901
    tag = data[off : off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"I":
        (v,) = struct.unpack_from("<q", data, off)
        return v, off + 8
    if tag == b"f":
        (v,) = struct.unpack_from("<d", data, off)
        return v, off + 8
    if tag == b"S":
        return _dec_str(data, off)
    if tag == b"B":
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        _need(data, off, n)
        return data[off : off + n], off + n
    if tag == b"A":
        dt, off = _dec_str(data, off)
        ndim = data[off]
        off += 1
        shape = struct.unpack_from(f"<{ndim}q", data, off)
        off += 8 * ndim
        dtype = np.dtype(dt)
        count = int(np.prod(shape)) if ndim else 1
        nbytes = count * dtype.itemsize
        _need(data, off, nbytes)
        arr = np.frombuffer(data, dtype, count=count,
                            offset=off).reshape(shape).copy()
        return arr, off + nbytes
    if tag in (b"L", b"U"):
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        items = []
        for _ in range(n):
            x, off = _dec(data, off)
            items.append(x)
        return (items if tag == b"L" else tuple(items)), off
    if tag == b"Z":
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        items = []
        for _ in range(n):
            x, off = _dec(data, off)
            items.append(x)
        return frozenset(items), off
    if tag == b"D":
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(data, off)
            v, off = _dec(data, off)
            d[k] = v
        return d, off
    if tag == b"O":
        name, off = _dec_str(data, off)
        cls = registry().get(name)
        if cls is None:
            raise ValueError(f"unknown wire class {name!r}")
        (nf,) = struct.unpack_from("<H", data, off)
        off += 2
        kwargs = {}
        for _ in range(nf):
            fname, off = _dec_str(data, off)
            val, off = _dec(data, off)
            kwargs[fname] = val
        return cls(**kwargs), off
    raise ValueError(f"bad wire tag {tag!r} at {off - 1}")
