"""Continuous shard replication: follower tails, in-sync watermarks,
hedged replica reads, divergence checking.

Generalizes the one-shot migration sync/catchup (coordinator/migration.py)
into standing replicas: a :class:`ReplicaSyncer` runs per (follower node,
dataset, shard), bootstrapping a warm read-only memstore image from the
durable tier (the migration destination's recovery path) and then tailing
the shard's WAL — publishing FOLLOWING / IN_SYNC / LAGGING replica states
and an applied-offset watermark through ``ShardManager`` as sequenced
``ShardEvent``s. The reference's shard recovery treats the ingestion log as
the source of truth (``doc/sharding.md:158``); a follower is simply a
second consumer of that log that never writes the durable tier.

Failover is a map flip, not a cold recovery: ``ShardManager.remove_member``
promotes the highest-watermark in-sync follower with ONE sequenced ACTIVE
event, and ``Node.promote_shard`` starts the ingest worker at the
follower's applied offset — no manifest re-read, no sealed-segment replay,
zero object-store GETs on the flip. Cold recovery remains the fallback
when no in-sync replica exists.

Reads scatter-gather to any in-sync replica through
:class:`ReplicaDispatcher`: candidates are ordered by EWMA dispatch
latency (``utils.resilience.peer_latency``), a candidate with an open
breaker falls to the back, and a hedge request is launched onto the next
candidate when the primary's hedge timer fires (reference
``HighAvailabilityPlanner`` routing-around-failure, plus tail-latency
hedging). Writes still route to the leader only — followers never append.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from filodb_tpu.coordinator.shardmapper import ShardStatus
from filodb_tpu.kafka.log_server import LogOpError
from filodb_tpu.query.exec.plan import PlanDispatcher
from filodb_tpu.utils.metrics import (
    GaugeFn,
    get_counter,
    get_gauge,
)
from filodb_tpu.utils.resilience import (
    CircuitOpenError,
    FaultInjector,
    breaker_for,
    peer_latency,
    record_peer_latency,
)

log = logging.getLogger(__name__)

# registered at import so the families render at zero before any replica
# exists (cluster.py imports this module; standalone imports cluster)
PROMOTIONS = get_counter(
    "filodb_replica_promotions",
    help="in-sync followers promoted to shard leader")
DIVERGENCE = get_counter(
    "filodb_replica_divergence",
    help="leader/follower state mismatches found by replicacheck")
FOLLOWER_READS = get_counter(
    "filodb_replica_follower_reads",
    help="read dispatches served by a follower replica")
HEDGED = get_counter(
    "filodb_hedged_reads",
    help="hedge requests launched onto a second replica")
HEDGED_WON = get_counter(
    "filodb_hedged_reads_won",
    help="hedge requests that returned before the primary")
# untagged family anchors; runtime series carry dataset/shard/node tags
get_gauge("filodb_replica_lag",
          help="log records behind the leader, per follower replica")
get_gauge("filodb_replica_watermark",
          help="follower applied log offset, per replica")


class _FollowerTail(threading.Thread):
    """Per-replica tail thread: bootstrap the follower image from the
    durable tier, then tail the shard's WAL into it (a read-only
    ``_IngestWorker`` sibling — never registered with the node's flush
    scheduler, so the follower neither flushes nor checkpoints nor
    truncates the shared log)."""

    # consecutive deterministic log errors tolerated before the replica
    # drops to LAGGING and the tail backs off (mirror of _IngestWorker)
    MAX_SERVER_ERRORS = 5

    def __init__(self, syncer: "ReplicaSyncer",
                 poll_interval: float = 0.01,
                 durable_sync_interval_s: float = 5.0):
        super().__init__(daemon=True,
                         name=f"replica-{syncer.dataset}-{syncer.shard_num}"
                              f"@{syncer.node.name}")
        self.syncer = syncer
        self.poll_interval = poll_interval
        self.durable_sync_interval_s = durable_sync_interval_s
        self._stop_ev = threading.Event()
        self._last_durable_sync = 0.0
        self._last_report = 0.0

    def run(self):
        sy = self.syncer
        # the bootstrap's cold load IS the first durable sync — start the
        # cadence clock here so the first loop pass doesn't re-GET the
        # manifest it just read
        self._last_durable_sync = time.monotonic()
        try:
            sy._bootstrap()
        except Exception:
            log.exception("replica bootstrap failed for %s/%d on %s",
                          sy.dataset, sy.shard_num, sy.node.name)
            sy._report(ShardStatus.LAGGING)
            return
        sy._report(ShardStatus.FOLLOWING)
        server_errors = 0
        while not self._stop_ev.is_set() and sy.node.alive:
            try:
                FaultInjector.fire("replica.tail", node=sy.node.name,
                                   dataset=sy.dataset, shard=sy.shard_num)
            except Exception:
                sy._report(ShardStatus.LAGGING)
                self._stop_ev.wait(min(self.poll_interval * 100, 1.0))
                continue
            progressed = False
            it = sy.log.read_from(sy.applied + 1)
            failed = False
            while True:
                try:
                    sd = next(it)
                except StopIteration:
                    server_errors = 0
                    break
                except LogOpError:
                    server_errors += 1
                    if server_errors >= self.MAX_SERVER_ERRORS:
                        log.error("replica %s/%d@%s: persistent log "
                                  "errors; marking LAGGING", sy.dataset,
                                  sy.shard_num, sy.node.name, exc_info=True)
                        sy._report(ShardStatus.LAGGING)
                        server_errors = 0
                    self._stop_ev.wait(min(self.poll_interval * 100, 1.0))
                    failed = True
                    break
                except (ConnectionError, OSError, RuntimeError):
                    self._stop_ev.wait(min(self.poll_interval * 100, 1.0))
                    failed = True
                    break
                if self._stop_ev.is_set() or not sy.node.alive:
                    return
                try:
                    sy.shard.ingest(sd)
                except Exception:
                    # poison record: the LEADER surfaces it; the follower
                    # just stops advancing and shows LAGGING
                    log.exception("replica %s/%d@%s ingest failed at "
                                  "offset %d", sy.dataset, sy.shard_num,
                                  sy.node.name, sd.offset)
                    sy._report(ShardStatus.LAGGING)
                    return
                sy.applied = sd.offset
                progressed = True
                server_errors = 0
            if failed:
                continue
            now = time.monotonic()
            # sealed-segment tail: keep the follower's durable-tier view
            # (and its segment sequence) current, off the hot loop
            if now - self._last_durable_sync >= self.durable_sync_interval_s:
                self._last_durable_sync = now
                sy._sync_durable()
            if progressed or now - self._last_report >= 0.1:
                self._last_report = now
                sy._report_lag()
            if not progressed:
                # interruptible idle wait: a promotion (stop + join) must
                # not sit out the poll interval — failover handoff latency
                # is bounded by this wait
                self._stop_ev.wait(self.poll_interval)

    def stop(self):
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout=5)


@dataclass
class ReplicaSyncer:
    """One follower replica of one shard: owns the bootstrap, the WAL
    tail thread, and the replica-state reporting. Created and tracked by
    ``FilodbCluster.ensure_replicas``; ``promote()`` hands the warm image
    to ``Node.promote_shard`` on failover."""

    node: object                      # follower Node (in-process)
    dataset: str
    shard_num: int
    config: object                    # IngestionConfig
    log: object                       # the shard's ReplayLog
    sm: object                        # ShardManager
    in_sync_lag: int = 0              # max offset lag still counted in-sync
    poll_interval: float = 0.01
    durable_sync_interval_s: float = 5.0
    applied: int = -1                 # last WAL offset applied (watermark)
    shard: object = None              # follower's memstore shard image
    _tail: _FollowerTail | None = None
    _status: ShardStatus | None = None
    _was_in_sync: bool = False
    _lock: object = field(init=False, repr=False, default=None)

    def __post_init__(self):
        self._lock = threading.Lock()

    def start(self) -> "ReplicaSyncer":
        """Launch the tail thread (bootstrap runs on it, so membership
        threads never block on durable-tier reads)."""
        if self._tail is None:
            self._tail = _FollowerTail(
                self, self.poll_interval, self.durable_sync_interval_s)
            self._tail.start()
        return self

    def _bootstrap(self) -> None:
        """Build the warm read-only image exactly like a migration
        destination: refresh the durable view, recover the index, read
        checkpoints — then tail from min(checkpoint), the same dedup the
        leader's own restart uses (rows at/below a group watermark are
        skipped on ingest)."""
        cs = self.node.memstore.column_store
        refresh = getattr(cs, "refresh_shard", None)
        if callable(refresh):
            refresh(self.dataset, self.shard_num)
        try:
            self.node.memstore.setup(self.dataset, self.shard_num,
                                     self.config.store)
        except ValueError:
            pass  # already set up (rejoin as follower)
        self.shard = self.node.memstore.get_shard(self.dataset,
                                                  self.shard_num)
        self.shard.recover_index()
        self.applied = self.shard.setup_watermarks_for_recovery()
        tags = {"dataset": self.dataset, "shard": str(self.shard_num),
                "node": self.node.name}
        GaugeFn("filodb_replica_lag",
                lambda: float(self.log.offset_lag(self.applied))
                if self._tail is not None else None, tags)
        GaugeFn("filodb_replica_watermark",
                lambda: float(self.applied)
                if self._tail is not None else None, tags)

    def _sync_durable(self) -> None:
        """Apply newly-sealed segments to the follower's durable-tier
        view (objectstore ``sync_shard`` — incremental, GETs only unseen
        segments). No-op on backends without the API."""
        sync = getattr(self.node.memstore.column_store, "sync_shard", None)
        if not callable(sync):
            return
        try:
            sync(self.dataset, self.shard_num)
        except Exception:
            log.warning("durable sync failed for replica %s/%d@%s",
                        self.dataset, self.shard_num, self.node.name,
                        exc_info=True)

    def _report_lag(self) -> None:
        lag = self.log.offset_lag(self.applied)
        if lag <= self.in_sync_lag:
            self._was_in_sync = True
            self._report(ShardStatus.IN_SYNC)
        elif self._was_in_sync:
            self._report(ShardStatus.LAGGING)
        else:
            self._report(ShardStatus.FOLLOWING)

    def _report(self, status: ShardStatus) -> None:
        with self._lock:
            if self._tail is None:
                return  # stopped/promoted: never resurrect the entry
            self._status = status
        try:
            self.sm.replica_update(self.shard_num, self.node.name, status,
                                   watermark=self.applied)
        except Exception:
            log.exception("replica state publish failed for %s/%d@%s",
                          self.dataset, self.shard_num, self.node.name)

    @property
    def status(self) -> ShardStatus | None:
        return self._status

    def stop(self) -> None:
        """Stop tailing. The memstore image is left in place — a promotion
        or a rejoin-as-follower reuses it warm."""
        with self._lock:
            tail, self._tail = self._tail, None
        if tail is not None:
            tail.stop()

    def promote(self) -> int:
        """Failover handoff: stop the tail and return the applied offset —
        the exact point ``Node.promote_shard`` resumes ingestion from."""
        self.stop()
        return self.applied


@dataclass
class ReplicaCandidate:
    """One routing choice for a shard read: a dispatcher plus the
    breaker/latency key it is accounted under. ``guard`` wraps the call in
    this breaker (in-process dispatchers have none of their own);
    ``RemotePlanDispatcher`` already breaker-guards per peer."""

    key: str
    dispatcher: PlanDispatcher
    follower: bool = False
    guard: bool = True


class ReplicaDispatcher(PlanDispatcher):
    """Read-path scatter over a shard's replica set.

    Candidates (leader first, then in-sync followers) are ordered by EWMA
    dispatch latency; candidates with open breakers drop to the back.
    The best candidate runs first; when its hedge timer fires before it
    returns — or it fails outright — the next candidate is launched and
    the first success wins. Writes never route here: ingestion targets
    the leader's log, and followers are read-only by construction."""

    def __init__(self, shard: int, candidates: list[ReplicaCandidate],
                 hedge_timeout_s: float = 0.05):
        self.shard = shard
        self.candidates = candidates
        self.hedge_timeout_s = hedge_timeout_s

    def _ordered(self) -> list[ReplicaCandidate]:
        def lat(c):
            v = peer_latency(c.key)
            # unknown latency keeps construction order (leader first)
            return (v is None, v or 0.0)
        by_latency = sorted(self.candidates, key=lat)
        closed = [c for c in by_latency if not breaker_for(c.key).is_open]
        opened = [c for c in by_latency if breaker_for(c.key).is_open]
        return closed + opened

    def _call(self, cand: ReplicaCandidate, plan, ctx):
        FaultInjector.fire("replica.dispatch", node=cand.key,
                           shard=self.shard)
        t0 = time.perf_counter()
        if cand.guard:
            with breaker_for(cand.key).calling():
                result = cand.dispatcher.dispatch(plan, ctx)
        else:
            result = cand.dispatcher.dispatch(plan, ctx)
        record_peer_latency(cand.key, time.perf_counter() - t0)
        if cand.follower:
            FOLLOWER_READS.inc()
        return result

    def dispatch(self, plan, ctx):
        order = self._ordered()
        if not order:
            raise ConnectionError(
                f"shard {self.shard}: no live replica to dispatch to")
        if len(order) == 1:
            return self._call(order[0], plan, ctx)
        cond = threading.Condition()
        state = {"result": None, "won": None, "errors": [], "launched": 0,
                 "finished": 0}

        def run(cand, hedged):
            try:
                r = self._call(cand, plan, ctx)
            except Exception as e:
                with cond:
                    state["finished"] += 1
                    state["errors"].append(e)
                    cond.notify_all()
                return
            with cond:
                state["finished"] += 1
                if state["won"] is None:
                    state["won"] = cand
                    state["result"] = r
                    if hedged:
                        HEDGED_WON.inc()
                cond.notify_all()

        def launch(i, hedged):
            state["launched"] += 1
            threading.Thread(
                target=run, args=(order[i], hedged), daemon=True,
                name=f"replica-read-{self.shard}-{order[i].key}").start()

        with cond:
            launch(0, False)
            next_i = 1
            while True:
                settled = (lambda: state["won"] is not None
                           or state["finished"] >= state["launched"])
                timeout = self.hedge_timeout_s \
                    if next_i < len(order) else None
                timer_fired = not cond.wait_for(settled, timeout=timeout)
                if state["won"] is not None:
                    return state["result"]
                all_failed = state["finished"] >= state["launched"]
                if next_i < len(order) and (timer_fired or all_failed):
                    # timer → a hedge (primary still in flight);
                    # failure → plain failover, not counted as hedged
                    hedged = not all_failed
                    if hedged:
                        HEDGED.inc()
                    launch(next_i, hedged)
                    next_i += 1
                    continue
                if all_failed and next_i >= len(order):
                    errors = state["errors"]
                    for e in errors:
                        if not isinstance(e, CircuitOpenError):
                            raise e
                    raise errors[-1]


# ---------------------------------------------------------------------------
# divergence checking (filo-cli replicacheck + chaos-test teardown)


def check_replicas(cluster, dataset: str, max_lag: int = 0) -> list[dict]:
    """Compare each shard's leader against its follower images. A
    follower counts as divergent when its applied offset trails the
    leader's covered offset by more than ``max_lag``, or — once fully
    caught up — when its ``max_ingested_ts`` / partition count disagree
    with the leader's. Raw ``data_version`` is deliberately NOT compared:
    a follower only replays rows above its recovered watermark, so its
    ingest counters legitimately differ. Each divergence increments
    ``filodb_replica_divergence_total``."""
    issues = []
    sm = cluster.shard_managers.get(dataset)
    if sm is None:
        return issues
    for shard in range(sm.num_shards):
        owner = sm.mapper.node_for(shard)
        leader = cluster.nodes.get(owner) if owner else None
        if leader is None or getattr(leader, "memstore", None) is None:
            continue
        try:
            lshard = leader.memstore.get_shard(dataset, shard)
        except KeyError:
            continue
        covered = leader.shard_offset(dataset, shard)
        for name, st in sm.mapper.replicas_of(shard).items():
            if st.status != ShardStatus.IN_SYNC:
                continue
            follower = cluster.nodes.get(name)
            if follower is None or \
                    getattr(follower, "memstore", None) is None:
                continue
            try:
                fshard = follower.memstore.get_shard(dataset, shard)
            except KeyError:
                issues.append({"shard": shard, "follower": name,
                               "kind": "missing_image"})
                continue
            sy = cluster.replica_syncers.get((dataset, shard, name))
            applied = sy.applied if sy is not None else st.watermark
            if covered - applied > max_lag:
                issues.append({"shard": shard, "follower": name,
                               "kind": "watermark_lag",
                               "leader_offset": covered,
                               "follower_offset": applied})
                continue
            if applied >= covered:
                # a follower whose image came entirely from the durable
                # tier (every WAL row below its recovered watermark) has
                # ingested nothing this process lifetime: its -1 high-water
                # ts is not comparable, and its state trivially equals the
                # leader's flushed state
                if fshard.max_ingested_ts >= 0 and \
                        fshard.max_ingested_ts != lshard.max_ingested_ts:
                    issues.append({
                        "shard": shard, "follower": name,
                        "kind": "max_ingested_ts",
                        "leader": lshard.max_ingested_ts,
                        "follower_value": fshard.max_ingested_ts})
                if fshard.num_partitions != lshard.num_partitions:
                    issues.append({
                        "shard": shard, "follower": name,
                        "kind": "num_partitions",
                        "leader": lshard.num_partitions,
                        "follower_value": fshard.num_partitions})
    DIVERGENCE.inc(len(issues))
    return issues


def assert_no_divergence(cluster, dataset: str, timeout_s: float = 10.0,
                         max_lag: int = 0) -> None:
    """Chaos-test teardown gate: wait for follower tails to drain, then
    assert zero divergence (the replication analog of a filolint pass)."""
    deadline = time.monotonic() + timeout_s
    issues = check_replicas(cluster, dataset, max_lag)
    while issues and time.monotonic() < deadline:
        time.sleep(0.05)
        issues = check_replicas(cluster, dataset, max_lag)
    assert not issues, f"replica divergence in {dataset}: {issues}"


__all__ = [
    "ReplicaCandidate",
    "ReplicaDispatcher",
    "ReplicaSyncer",
    "assert_no_divergence",
    "check_replicas",
]
