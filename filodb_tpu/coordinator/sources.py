"""Ingestion stream sources.

Counterpart of reference ``IngestionStream(Factory)`` SPI
(``coordinator/src/main/scala/filodb.coordinator/IngestionStream.scala``)
and the ``CsvStream`` test source (``sources/CsvStream.scala:1-124``): a
source yields SomeData containers for one shard. The production source is a
``ReplayLog`` (``kafka/log.py``); these adapters turn external data into
container streams.
"""

from __future__ import annotations

import csv
from collections.abc import Iterator

from filodb_tpu.core.partkey import METRIC_LABEL, PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData


def csv_stream(path: str, metric: str, schema: str = "gauge",
               batch: int = 100, default_labels: dict | None = None
               ) -> Iterator[SomeData]:
    """CSV rows → containers. Row format:
    ``timestamp_ms,value[,label=value,...]`` (reference CsvStream)."""
    container = RecordContainer()
    offset = 0
    with open(path) as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#"):
                continue
            ts, value = int(row[0]), float(row[1])
            labels = {METRIC_LABEL: metric, **(default_labels or {})}
            for pair in row[2:]:
                k, v = pair.split("=", 1)
                labels[k] = v
            container.add(IngestRecord(PartKey.create(schema, labels), ts,
                                       (value,)))
            if len(container) >= batch:
                yield SomeData(container, offset)
                offset += 1
                container = RecordContainer()
    if len(container):
        yield SomeData(container, offset)


def influx_file_stream(path: str, default_labels: dict | None = None,
                       batch: int = 100) -> Iterator[SomeData]:
    """Influx line-protocol file → containers (gateway-format replay)."""
    from filodb_tpu.gateway.influx import InfluxParseError, parse_influx_line

    container = RecordContainer()
    offset = 0
    with open(path) as f:
        for line in f:
            try:
                for rec in parse_influx_line(line, default_labels):
                    container.add(rec)
            except InfluxParseError:
                continue
            if len(container) >= batch:
                yield SomeData(container, offset)
                offset += 1
                container = RecordContainer()
    if len(container):
        yield SomeData(container, offset)
