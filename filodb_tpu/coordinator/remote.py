"""Plan shipping over TCP: the distributed dispatch transport.

Counterpart of the reference's Akka-remoting + Kryo plan shipping
(``PlanDispatcher.scala:31`` ``ActorPlanDispatcher``, ``client/Serializer.
scala:23-64``): ExecPlan subtrees are serialized and executed on the node
owning the target shard; results (StepMatrix batches) return on the same
connection. Serialization is the typed wire codec (``coordinator/wire.py``)
— a closed class registry, so a hostile peer cannot execute code — with a
hard frame-size cap and an optional shared-secret handshake
(``FILODB_CLUSTER_SECRET``): connections must authenticate before any other
message when the server has a secret configured.

Control messages (ping/shard-status) share the channel — the cluster's
failure detector rides the same transport.
"""

from __future__ import annotations

import hmac
import logging
import os
import socket
import socketserver
import struct
import threading
import time
import zlib

from filodb_tpu.coordinator.wire import MAX_FRAME, decode, encode
from filodb_tpu.query.exec.plan import ExecContext, PlanDispatcher
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.metrics import GaugeFn, get_counter
from filodb_tpu.utils.resilience import (
    FaultInjector,
    breaker_for,
    default_retry_policy,
    record_peer_latency,
)
from filodb_tpu.utils.tracing import graft_spans, span, start_trace

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# frame compression. The length word's high bit flags a zlib-compressed
# payload (MAX_FRAME < 2^31 keeps the bit free); both sides always DECODE
# compressed frames, but only SEND them after the ("hello", {"compress":
# True}) capability exchange, so a pre-compression peer never receives a
# frame it cannot parse — its reply to the hello is ("err", ...), which the
# dialer records as "no compression" and the connection stays usable.

_FLAG_COMPRESSED = 0x8000_0000
WIRE_COMPRESS_MIN = 4096  # frames below this aren't worth the zlib cycles
WIRE_COMPRESS_LEVEL = 3  # favor throughput; payloads are pickled arrays

FRAMES_COMPRESSED = get_counter("filodb_wire_frames_compressed")
FRAMES_RAW = get_counter("filodb_wire_frames_raw")
COMPRESS_BYTES_IN = get_counter("filodb_wire_compress_bytes_in")
COMPRESS_BYTES_OUT = get_counter("filodb_wire_compress_bytes_out")
BYTES_SENT = get_counter("filodb_remote_bytes_sent")
BYTES_RECEIVED = get_counter("filodb_remote_bytes_received")

GaugeFn("filodb_wire_compression_ratio",
        lambda: (COMPRESS_BYTES_IN.value / COMPRESS_BYTES_OUT.value)
        if COMPRESS_BYTES_OUT.value else None)

# per-peer capability memo (keyed (host, port)): False once a peer rejects
# the hello, so later dials skip the doomed exchange. Sockets can't carry
# the flag themselves (socket.socket defines __slots__).
_peer_caps: dict[tuple[str, int], bool] = {}


def cluster_secret() -> str | None:
    return os.environ.get("FILODB_CLUSTER_SECRET") or None


def make_authed_handler(get_secret, handle, log_label: str):
    """Build a socketserver handler enforcing the framed auth protocol:
    pre-auth frames capped at AUTH_FRAME_CAP, ("auth", secret) handshake
    via constant-time compare, connection dropped on failure. ``handle``
    maps a decoded message to a response tuple. Shared by the plan
    executor and the log server so the protocol cannot drift."""

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            secret = get_secret()
            authed = secret is None
            compress = False  # per-connection: set by the hello exchange
            try:
                while True:
                    msg = _recv_msg(self.request,
                                    MAX_FRAME if authed else AUTH_FRAME_CAP)
                    if not authed:
                        if msg[0] == "auth" and len(msg) == 2 \
                                and isinstance(msg[1], str) \
                                and hmac.compare_digest(msg[1], secret):
                            authed = True
                            _send_msg(self.request, ("ok", True))
                            continue
                        _send_msg(self.request, ("err", "auth required"))
                        return  # drop the unauthenticated connection
                    if msg[0] == "hello" and len(msg) == 2 \
                            and isinstance(msg[1], dict):
                        # capability exchange (shared by every framed
                        # server so the protocol cannot drift); the reply
                        # itself is never compressed — the client only
                        # learns our capability from it
                        compress = bool(msg[1].get("compress"))
                        _send_msg(self.request,
                                  ("ok", {"compress": compress}))
                        continue
                    _send_msg(self.request, handle(msg), compress=compress)
            except (ConnectionError, EOFError, OSError):
                pass
            except Exception as e:  # pragma: no cover
                log.exception("%s request failed", log_label)
                try:
                    _send_msg(self.request, ("err", repr(e)))
                except Exception:
                    pass

    return Handler


def _send_msg(sock: socket.socket, obj, compress: bool = False) -> int:
    """Frame and send one message; returns bytes written to the wire."""
    payload = encode(obj)
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame {len(payload)} exceeds cap {MAX_FRAME}")
    word = len(payload)
    if compress and len(payload) >= WIRE_COMPRESS_MIN:
        packed = zlib.compress(payload, WIRE_COMPRESS_LEVEL)
        if len(packed) < len(payload):
            COMPRESS_BYTES_IN.inc(len(payload))
            COMPRESS_BYTES_OUT.inc(len(packed))
            FRAMES_COMPRESSED.inc()
            payload = packed
            word = len(payload) | _FLAG_COMPRESSED
        else:  # incompressible — ship raw rather than grow the frame
            FRAMES_RAW.inc()
    else:
        FRAMES_RAW.inc()
    sock.sendall(struct.pack("<I", word) + payload)
    return 4 + len(payload)


AUTH_FRAME_CAP = 4096  # pre-auth frames must be tiny (auth messages are)


def _recv_frame(sock: socket.socket, cap: int = MAX_FRAME):
    """Receive one frame; returns (decoded message, wire bytes read)."""
    hdr = _recv_exact(sock, 4)
    (word,) = struct.unpack("<I", hdr)
    ln = word & ~_FLAG_COMPRESSED
    if ln > cap:
        raise ConnectionError(f"frame {ln} exceeds cap {cap}")
    payload = _recv_exact(sock, ln)
    if word & _FLAG_COMPRESSED:
        # bounded inflate: cap what a hostile/buggy peer can expand to —
        # the decompressed payload obeys the same cap as a raw frame
        d = zlib.decompressobj()
        try:
            payload = d.decompress(payload, cap + 1)
        except zlib.error as e:
            raise ConnectionError(f"bad compressed frame: {e}") from e
        if len(payload) > cap or d.unconsumed_tail:
            raise ConnectionError(
                f"decompressed frame exceeds cap {cap}")
    return decode(payload), 4 + ln


def _recv_msg(sock: socket.socket, cap: int = MAX_FRAME):
    return _recv_frame(sock, cap)[0]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class PlanExecutorServer:
    """Executes shipped plan subtrees against the local memstore
    (the receive side of ``ActorPlanDispatcher``)."""

    def __init__(self, memstore, host: str = "127.0.0.1", port: int = 0,
                 extra_handlers: dict | None = None,
                 secret: str | None = None):
        self.memstore = memstore
        # control-plane extensions: {kind: fn(*payload) -> response tuple}
        # (join/start_shard/shard_status... registered by the server runtime)
        self.extra_handlers = extra_handlers or {}
        self.secret = secret if secret is not None else cluster_secret()
        Handler = make_authed_handler(lambda: self.secret, self._handle,
                                      "remote exec")

        class Server(socketserver.ThreadingTCPServer):
            # fixed executor ports must rebind across fast restarts
            allow_reuse_address = True

        self.server = Server((host, port), Handler, bind_and_activate=True)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self.address = (host, self.port)
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def _handle(self, msg):
        kind = msg[0]
        if kind == "ping":
            return ("pong",)
        if kind == "execute":
            _, dataset, plan, qcontext = msg
            try:
                # same admission gate as local queries: scatter fan-in from
                # many coordinators can't stampede this peer. A shed is a
                # typed verdict, not an error — the dispatcher re-raises it
                # as QueryRejected without counting a breaker failure.
                from filodb_tpu.coordinator.query_service import plan_tenant
                from filodb_tpu.utils.governor import (
                    EXPENSIVE,
                    QueryRejected,
                    governor,
                )
                tc = getattr(qcontext, "trace", None) \
                    if qcontext is not None else None
                sampled = tc is not None and getattr(tc, "sampled", False)
                try:
                    # tenant extracted from the exec plan's leaf filters so
                    # per-tenant inflight caps hold on remote leaves too
                    t_admit = time.perf_counter()
                    with governor().admit(cost=EXPENSIVE,
                                          tenant=plan_tenant(plan)):
                        wait_s = time.perf_counter() - t_admit
                        ctx = ExecContext(self.memstore, dataset,
                                          qcontext or QueryContext())
                        ctx.stats.admission_wait_s += wait_s
                        if sampled:
                            # sampled query: join the root's distributed
                            # trace — execute under a local trace and ship
                            # the span tree back in the result frame for
                            # the dispatcher to graft, node-tagged
                            with start_trace() as trace:
                                result = plan.execute(ctx)
                                # wire-encode host, not device
                                result.result.materialize()
                            result.spans = trace.as_dicts()
                        else:
                            result = plan.execute(ctx)
                            result.result.materialize()
                        return ("ok", result)
                except QueryRejected as e:
                    return ("rejected", str(e), e.retry_after_s)
            except Exception as e:
                log.exception("plan execution failed")
                return ("err", repr(e))
        handler = self.extra_handlers.get(kind)
        if handler is not None:
            try:
                return ("ok", handler(*msg[1:]))
            except Exception as e:
                log.exception("control message %s failed", kind)
                return ("err", repr(e))
        return ("err", f"unknown message {kind!r}")

    def start(self) -> "PlanExecutorServer":
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


# transport-failure classes that invalidate a pooled socket. Decode
# errors (malformed frame off a half-dead peer) poison the stream the
# same way a reset does: the connection must be dropped and redialed.
# Shared with the remote column-store client so the sets cannot drift.
TRANSPORT_ERRORS = (ConnectionError, OSError, EOFError, ValueError)


class _SocketPool:
    """Process-level pool of authed sockets, keyed by (host, port).

    Checkout/checkin rather than thread-local: scatter-gather runs
    children on short-lived worker threads, so sockets bound to thread
    identity would never be reused (every query would redial and re-auth
    per child, and dead threads would leak sockets to GC). A socket that
    hits a transport error is closed by the caller and never checked
    back in; idle sockets beyond ``idle_cap`` per peer are closed on
    checkin."""

    def __init__(self, idle_cap: int = 8):
        self.idle_cap = idle_cap
        self._lock = threading.Lock()
        self._idle: dict[tuple[str, int], list[socket.socket]] = {}

    def checkout(self, key: tuple[str, int]) -> socket.socket | None:
        with self._lock:
            idle = self._idle.get(key)
            return idle.pop() if idle else None

    def checkin(self, key: tuple[str, int], sock: socket.socket) -> None:
        with self._lock:
            idle = self._idle.setdefault(key, [])
            if len(idle) < self.idle_cap:
                idle.append(sock)
                return
        _close_quietly(sock)

    def drop(self, key: tuple[str, int]) -> None:
        """Close every idle socket for a peer (auth/secret changed,
        tests forcing a fresh dial)."""
        with self._lock:
            idle = self._idle.pop(key, [])
        for s in idle:
            _close_quietly(s)

    def clear(self) -> None:
        with self._lock:
            all_idle = [s for conns in self._idle.values() for s in conns]
            self._idle.clear()
        for s in all_idle:
            _close_quietly(s)


def _close_quietly(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


_pool = _SocketPool()


def reset_pool() -> None:
    """Drop all pooled connections (tests)."""
    _pool.clear()


class RemotePlanDispatcher(PlanDispatcher):
    """Ships a plan subtree to a peer node (the send side of
    ``ActorPlanDispatcher``). Connections are pooled process-wide per
    (host, port) — scatter-gather worker threads check them out and back
    in, so thread churn does not cost redials or re-auth.

    Resilience: the peer's circuit breaker gates every dial (open peer →
    ``CircuitOpenError`` without touching the network, which scatter-gather
    tolerates as a lost child); transport failures retry on a fresh socket
    under the process retry policy (a stale pooled socket — peer restarted —
    must not fail the first request after reconnect); query dispatch
    timeouts derive from the query ``Deadline`` on ``ExecContext``."""

    __wire_fields__ = ("host", "port", "timeout")

    TRANSPORT_ERRORS = TRANSPORT_ERRORS

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    @property
    def peer(self) -> str:
        return f"{self.host}:{self.port}"

    def _dial(self, timeout: float) -> socket.socket:
        FaultInjector.fire("remote.connect", host=self.host,
                           port=self.port)
        sock = socket.create_connection((self.host, self.port),
                                        timeout=timeout)
        # anything that raises between connect and return — setsockopt,
        # the auth/hello exchange, an encode TypeError, even
        # KeyboardInterrupt — must not leak the socket; a narrow
        # TRANSPORT_ERRORS guard here leaked fds for every other
        # exception class
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            secret = cluster_secret()
            if secret is not None:
                _send_msg(sock, ("auth", secret))
                resp = _recv_msg(sock)
                if resp[0] != "ok":
                    raise ConnectionError("cluster auth rejected")
            key = (self.host, self.port)
            if _peer_caps.get(key) is not False:
                # negotiate frame compression; a pre-compression peer
                # answers ("err", "unknown message 'hello'") and the
                # connection stays usable — remember the refusal so
                # later dials skip the exchange
                _send_msg(sock, ("hello", {"compress": True}))
                resp = _recv_msg(sock)
                _peer_caps[key] = (resp[0] == "ok"
                                   and isinstance(resp[1], dict)
                                   and bool(resp[1].get("compress")))
        except BaseException:
            _close_quietly(sock)
            raise
        return sock

    def _drop_conn(self):
        _pool.drop((self.host, self.port))

    def _roundtrip(self, msg: tuple, timeout: float | None = None,
                   nbytes_out: list | None = None):
        """One request/response on a pooled (or fresh) socket; transport
        failure closes the connection so the next attempt redials.
        ``nbytes_out`` collects per-call wire bytes (sent + received) for
        per-query stats attribution."""
        t = timeout if timeout is not None else self.timeout
        key = (self.host, self.port)
        sock = _pool.checkout(key)
        if sock is None:
            sock = self._dial(t)
        try:
            # pooled sockets are shared across calls; apply this call's
            # timeout (a prior short-timeout ping must not poison a later
            # long call)
            sock.settimeout(t)
            nsent = _send_msg(sock, msg,
                              compress=_peer_caps.get(key, False))
            resp, nrecv = _recv_frame(sock)
        except BaseException:
            # broad on purpose: the checked-out socket must reach
            # checkin or close on EVERY exit edge. Transport errors
            # still propagate for the retry loop; a non-transport
            # exception (encode TypeError, KeyboardInterrupt) used to
            # leak the fd out of the pool forever
            _close_quietly(sock)
            raise
        _pool.checkin(key, sock)
        BYTES_SENT.inc(nsent)
        BYTES_RECEIVED.inc(nrecv)
        if nbytes_out is not None:
            nbytes_out.append(nsent + nrecv)
        return resp

    def dispatch(self, plan, ctx):
        breaker = breaker_for(self.peer)
        deadline = getattr(ctx, "deadline", None)
        nbytes: list[int] = []

        def attempt():
            timeout = deadline.timeout(cap=self.timeout,
                                       what=f"dispatch to {self.peer}") \
                if deadline is not None else self.timeout
            FaultInjector.fire("remote.dispatch", host=self.host,
                               port=self.port)
            return self._roundtrip(
                ("execute", ctx.dataset, plan, ctx.qcontext), timeout,
                nbytes_out=nbytes)

        # calling() records a failure only for genuine transport errors —
        # a DeadlineExceeded (raised before even dialing) or an open
        # breaker must not count against a healthy peer — and guarantees
        # a half-open probe reports exactly one outcome
        t0 = time.perf_counter()
        with span("dispatch", peer=self.peer) as dspan, \
                breaker.calling(transport_errors=self.TRANSPORT_ERRORS):
            resp = default_retry_policy().call(
                attempt, retry_on=self.TRANSPORT_ERRORS, deadline=deadline)
        # feed the replica read router's EWMA ordering (successes only —
        # a failed dispatch says "down", which the breaker already records)
        record_peer_latency(self.peer, time.perf_counter() - t0)
        if resp[0] == "ok":
            result = resp[1]
            stats = getattr(result, "stats", None)
            if stats is not None:
                # attributed on the CHILD's stats object (this thread owns
                # it until the gather settles; root ctx.stats is not
                # thread-safe under concurrent workers), folded upward by
                # settle()'s merge
                stats.wire_bytes += sum(nbytes)
            rspans = getattr(result, "spans", None)
            if rspans:
                # graft the peer's span tree under this dispatch span;
                # top-level remote spans get the node tag
                graft_spans(rspans, dspan, node=self.peer)
                result.spans = []
            return result
        if resp[0] == "rejected":
            # the peer's admission gate shed the query: a healthy-peer
            # verdict (breaker already recorded success above). Re-raise
            # typed so the root maps it to 503 + Retry-After; deliberately
            # NOT gather-TOLERABLE — a shed peer is overload, not data loss.
            from filodb_tpu.utils.governor import QueryRejected
            retry_after = resp[2] if len(resp) > 2 else 1.0
            raise QueryRejected(f"peer {self.peer} shed the query: {resp[1]}",
                                retry_after_s=retry_after)
        raise RuntimeError(
            f"remote execution failed on {self.peer}: {resp[1]}")

    def ping(self) -> bool:
        try:
            return self._roundtrip(("ping",))[0] == "pong"
        except self.TRANSPORT_ERRORS:
            return False

    def call(self, kind: str, *payload):
        """Send a control message; returns the handler's response payload.
        A stale pooled socket (peer restarted between calls) retries once
        on a fresh connection before surfacing the error."""
        resp = default_retry_policy().call(
            lambda: self._roundtrip((kind, *payload)),
            retry_on=self.TRANSPORT_ERRORS)
        if resp[0] == "ok":
            return resp[1]
        if resp[0] == "pong":
            return None
        raise RuntimeError(f"control call {kind} failed: {resp[1]}")

