"""Plan shipping over TCP: the distributed dispatch transport.

Counterpart of the reference's Akka-remoting + Kryo plan shipping
(``PlanDispatcher.scala:31`` ``ActorPlanDispatcher``, ``client/Serializer.
scala:23-64``): ExecPlan subtrees are serialized and executed on the node
owning the target shard; results (StepMatrix batches) return on the same
connection. Serialization is the typed wire codec (``coordinator/wire.py``)
— a closed class registry, so a hostile peer cannot execute code — with a
hard frame-size cap and an optional shared-secret handshake
(``FILODB_CLUSTER_SECRET``): connections must authenticate before any other
message when the server has a secret configured.

Control messages (ping/shard-status) share the channel — the cluster's
failure detector rides the same transport.
"""

from __future__ import annotations

import hmac
import logging
import os
import socket
import socketserver
import struct
import threading

from filodb_tpu.coordinator.wire import MAX_FRAME, decode, encode
from filodb_tpu.query.exec.plan import ExecContext, PlanDispatcher
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.resilience import (
    FaultInjector,
    breaker_for,
    default_retry_policy,
)

log = logging.getLogger(__name__)


def cluster_secret() -> str | None:
    return os.environ.get("FILODB_CLUSTER_SECRET") or None


def make_authed_handler(get_secret, handle, log_label: str):
    """Build a socketserver handler enforcing the framed auth protocol:
    pre-auth frames capped at AUTH_FRAME_CAP, ("auth", secret) handshake
    via constant-time compare, connection dropped on failure. ``handle``
    maps a decoded message to a response tuple. Shared by the plan
    executor and the log server so the protocol cannot drift."""

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            secret = get_secret()
            authed = secret is None
            try:
                while True:
                    msg = _recv_msg(self.request,
                                    MAX_FRAME if authed else AUTH_FRAME_CAP)
                    if not authed:
                        if msg[0] == "auth" and len(msg) == 2 \
                                and isinstance(msg[1], str) \
                                and hmac.compare_digest(msg[1], secret):
                            authed = True
                            _send_msg(self.request, ("ok", True))
                            continue
                        _send_msg(self.request, ("err", "auth required"))
                        return  # drop the unauthenticated connection
                    _send_msg(self.request, handle(msg))
            except (ConnectionError, EOFError, OSError):
                pass
            except Exception as e:  # pragma: no cover
                log.exception("%s request failed", log_label)
                try:
                    _send_msg(self.request, ("err", repr(e)))
                except Exception:
                    pass

    return Handler


def _send_msg(sock: socket.socket, obj) -> None:
    payload = encode(obj)
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame {len(payload)} exceeds cap {MAX_FRAME}")
    sock.sendall(struct.pack("<I", len(payload)) + payload)


AUTH_FRAME_CAP = 4096  # pre-auth frames must be tiny (auth messages are)


def _recv_msg(sock: socket.socket, cap: int = MAX_FRAME):
    hdr = _recv_exact(sock, 4)
    (ln,) = struct.unpack("<I", hdr)
    if ln > cap:
        raise ConnectionError(f"frame {ln} exceeds cap {cap}")
    return decode(_recv_exact(sock, ln))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class PlanExecutorServer:
    """Executes shipped plan subtrees against the local memstore
    (the receive side of ``ActorPlanDispatcher``)."""

    def __init__(self, memstore, host: str = "127.0.0.1", port: int = 0,
                 extra_handlers: dict | None = None,
                 secret: str | None = None):
        self.memstore = memstore
        # control-plane extensions: {kind: fn(*payload) -> response tuple}
        # (join/start_shard/shard_status... registered by the server runtime)
        self.extra_handlers = extra_handlers or {}
        self.secret = secret if secret is not None else cluster_secret()
        Handler = make_authed_handler(lambda: self.secret, self._handle,
                                      "remote exec")

        class Server(socketserver.ThreadingTCPServer):
            # fixed executor ports must rebind across fast restarts
            allow_reuse_address = True

        self.server = Server((host, port), Handler, bind_and_activate=True)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self.address = (host, self.port)
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def _handle(self, msg):
        kind = msg[0]
        if kind == "ping":
            return ("pong",)
        if kind == "execute":
            _, dataset, plan, qcontext = msg
            try:
                ctx = ExecContext(self.memstore, dataset,
                                  qcontext or QueryContext())
                result = plan.execute(ctx)
                result.result.materialize()  # wire-encode host, not device
                return ("ok", result)
            except Exception as e:
                log.exception("plan execution failed")
                return ("err", repr(e))
        handler = self.extra_handlers.get(kind)
        if handler is not None:
            try:
                return ("ok", handler(*msg[1:]))
            except Exception as e:
                log.exception("control message %s failed", kind)
                return ("err", repr(e))
        return ("err", f"unknown message {kind!r}")

    def start(self) -> "PlanExecutorServer":
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


class RemotePlanDispatcher(PlanDispatcher):
    """Ships a plan subtree to a peer node (the send side of
    ``ActorPlanDispatcher``). One pooled connection per (host, port) per
    thread.

    Resilience: the peer's circuit breaker gates every dial (open peer →
    ``CircuitOpenError`` without touching the network, which scatter-gather
    tolerates as a lost child); transport failures retry on a fresh socket
    under the process retry policy (a stale pooled socket — peer restarted —
    must not fail the first request after reconnect); query dispatch
    timeouts derive from the query ``Deadline`` on ``ExecContext``."""

    _local = threading.local()

    __wire_fields__ = ("host", "port", "timeout")

    # transport-failure classes that invalidate the pooled socket. Decode
    # errors (malformed frame off a half-dead peer) poison the stream the
    # same way a reset does: the connection must be dropped and redialed.
    TRANSPORT_ERRORS = (ConnectionError, OSError, EOFError, ValueError)

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    @property
    def peer(self) -> str:
        return f"{self.host}:{self.port}"

    def _conn(self, timeout: float | None = None) -> socket.socket:
        pool = getattr(self._local, "pool", None)
        if pool is None:
            pool = self._local.pool = {}
        key = (self.host, self.port)
        sock = pool.get(key)
        if sock is None:
            FaultInjector.fire("remote.connect", host=self.host,
                               port=self.port)
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=timeout if timeout is not None else self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            secret = cluster_secret()
            if secret is not None:
                _send_msg(sock, ("auth", secret))
                resp = _recv_msg(sock)
                if resp[0] != "ok":
                    sock.close()
                    raise ConnectionError("cluster auth rejected")
            pool[key] = sock
        # pooled sockets are shared across dispatcher instances; apply this
        # call's timeout (a prior short-timeout ping must not poison a
        # later long call)
        sock.settimeout(timeout if timeout is not None else self.timeout)
        return sock

    def _drop_conn(self):
        pool = getattr(self._local, "pool", {})
        sock = pool.pop((self.host, self.port), None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _roundtrip(self, msg: tuple, timeout: float | None = None):
        """One request/response on the pooled socket; transport failure
        drops the connection so the next attempt redials."""
        try:
            sock = self._conn(timeout)
            _send_msg(sock, msg)
            return _recv_msg(sock)
        except self.TRANSPORT_ERRORS:
            self._drop_conn()
            raise

    def dispatch(self, plan, ctx):
        breaker = breaker_for(self.peer)
        breaker.guard()
        deadline = getattr(ctx, "deadline", None)

        def attempt():
            timeout = deadline.timeout(cap=self.timeout,
                                       what=f"dispatch to {self.peer}") \
                if deadline is not None else self.timeout
            FaultInjector.fire("remote.dispatch", host=self.host,
                               port=self.port)
            return self._roundtrip(
                ("execute", ctx.dataset, plan, ctx.qcontext), timeout)

        try:
            resp = default_retry_policy().call(
                attempt, retry_on=self.TRANSPORT_ERRORS, deadline=deadline)
        except self.TRANSPORT_ERRORS:
            breaker.record_failure()
            raise
        breaker.record_success()
        if resp[0] == "ok":
            return resp[1]
        raise RuntimeError(
            f"remote execution failed on {self.peer}: {resp[1]}")

    def ping(self) -> bool:
        try:
            return self._roundtrip(("ping",))[0] == "pong"
        except self.TRANSPORT_ERRORS:
            return False

    def call(self, kind: str, *payload):
        """Send a control message; returns the handler's response payload.
        A stale pooled socket (peer restarted between calls) retries once
        on a fresh connection before surfacing the error."""
        resp = default_retry_policy().call(
            lambda: self._roundtrip((kind, *payload)),
            retry_on=self.TRANSPORT_ERRORS)
        if resp[0] == "ok":
            return resp[1]
        if resp[0] == "pong":
            return None
        raise RuntimeError(f"control call {kind} failed: {resp[1]}")

