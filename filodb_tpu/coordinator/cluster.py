"""FilodbCluster: node membership, per-node shard lifecycle, failure
detection and recovery.

Counterpart of the reference's Akka-cluster control plane
(``FilodbCluster.scala:31,40``, ``NodeClusterActor.scala:61,187,368-412``
cluster-singleton + ``ShardManager``, ``IngestionActor.scala:43-57,237,294``,
``NodeCoordinatorActor``): a coordinator (in the real deployment: one
elected node; here a plain object shareable in-process or fronted by RPC)
tracks members, assigns shards, and drives per-node ingestion lifecycles:

  start shard → recover index from column store → read checkpoints →
  replay the shard's log from min(checkpoint) (group watermarks skip
  persisted rows) → continuous ingestion (reference ``doRecovery`` →
  ``normalIngestion``).

Failure detection: heartbeat probes over the plan-shipping channel (or
liveness flags for in-process nodes) stand in for Akka's phi-accrual
detector; on member loss, shards are marked DOWN and reassigned, and the new
owner recovers from the shared column store + log — the reference's
elastic-recovery story (``doc/sharding.md:158``).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from dataclasses import dataclass, field

from filodb_tpu.coordinator.migration import MigrationError, ShardMigration
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.replication import (
    ReplicaCandidate,
    ReplicaDispatcher,
    ReplicaSyncer,
)
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.shard_manager import ShardManager
from filodb_tpu.coordinator.shardmapper import ShardStatus
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import IngestionConfig
from filodb_tpu.kafka.log import ReplayLog
from filodb_tpu.kafka.log_server import LogOpError
from filodb_tpu.query.exec.plan import ExecContext, PlanDispatcher
from filodb_tpu.utils.metrics import GaugeFn, get_counter
from filodb_tpu.utils.resilience import FaultInjector, breaker_for
from filodb_tpu.utils.selfmon import STAMPS

log = logging.getLogger(__name__)


class NodeDispatcher(PlanDispatcher):
    """In-process dispatch to another node's memstore (stands in for the
    remote dispatcher when nodes share a process, e.g. tests)."""

    def __init__(self, node: "Node"):
        self.node = node

    def dispatch(self, plan, ctx):
        FaultInjector.fire("node.dispatch", node=self.node.name)
        if not self.node.alive:
            raise ConnectionError(f"node {self.node.name} is down")
        ctx2 = ExecContext(self.node.memstore, ctx.dataset, ctx.qcontext,
                           deadline=ctx.deadline)
        return plan.execute(ctx2)


@dataclass
class Node:
    """One cluster member: local memstore + ingestion workers.

    Reference: one FiloServer process (NodeCoordinatorActor + per-dataset
    IngestionActor/QueryActor).
    """

    name: str
    memstore: TimeSeriesMemStore
    alive: bool = True
    executor_port: int | None = None  # set when fronted by PlanExecutorServer
    flush_tick_s: float | None = None  # override scheduler cadence (tests)
    _workers: dict = field(default_factory=dict)  # (dataset, shard) -> worker
    _flusher: object = None

    def start_shard(self, dataset: str, shard: int, config: IngestionConfig,
                    shard_log: ReplayLog, on_status=None) -> None:
        """Start ingestion for a shard: recover then tail the log
        (reference ``IngestionActor.start``)."""
        key = (dataset, shard)
        if key in self._workers:
            return
        # a migration destination may hold a stale cached view of this
        # shard's durable state from before the source's upload — re-read
        # the remote manifest before recovering (no-op on other backends)
        refresh = getattr(self.memstore.column_store, "refresh_shard", None)
        if callable(refresh):
            refresh(dataset, shard)
        try:
            self.memstore.setup(dataset, shard, config.store)
        except ValueError:
            pass  # already set up (restart)
        s = self.memstore.get_shard(dataset, shard)
        s.recover_index()
        start_offset = s.setup_watermarks_for_recovery()
        # torn-tail guard: never hand out offsets at or below a checkpoint
        # (a truncated WAL tail may have held checkpointed offsets; reusing
        # them would make the watermark skip-check drop new rows)
        shard_log.align_after(max(s.group_watermarks, default=-1))
        ds_cfg = config.downsample or {}
        if ds_cfg.get("streaming"):
            self._setup_streaming_downsample(dataset, shard, s, ds_cfg)
        if on_status:
            on_status(shard, ShardStatus.RECOVERY, 0)
        worker = _IngestWorker(self, s, shard_log, start_offset, on_status)
        self._workers[key] = worker
        worker.start()
        self._register_lag_gauges(dataset, shard, s, shard_log, worker)
        if self._flusher is None:
            self._flusher = _FlushScheduler(self, self.flush_tick_s)
            self._flusher.start()

    def _register_lag_gauges(self, dataset: str, shard: int, s, shard_log,
                             worker) -> None:
        """Replay-log freshness gauges for one shard, scrape-time computed
        and weakly bound — a stopped/migrated shard drops its series
        instead of freezing at the last value. Registration is idempotent
        (same name+tags overwrites in the registry), so a shard restart
        simply rebinds the callbacks to the live objects."""
        tags = {"dataset": dataset, "shard": str(shard)}
        # pre-register the lazy error counter so the family scrapes at 0
        # from boot (the parity gate checks it is in the expected lists)
        get_counter("filodb_ingest_errors", tags)
        log_ref = weakref.ref(shard_log)
        worker_ref = weakref.ref(worker)
        shard_ref = weakref.ref(s)

        def offset_lag():
            lg, w = log_ref(), worker_ref()
            if lg is None or w is None:
                return None
            return float(lg.offset_lag(w.offset))

        def checkpoint_lag():
            lg, sh = log_ref(), shard_ref()
            if lg is None or sh is None:
                return None
            return float(lg.offset_lag(min(sh.group_watermarks,
                                           default=-1)))

        GaugeFn("filodb_ingest_offset_lag", offset_lag, tags,
                help="log records appended but not yet ingested")
        GaugeFn("filodb_ingest_checkpoint_lag", checkpoint_lag, tags,
                help="log records past the lowest group checkpoint")

    def _setup_streaming_downsample(self, dataset: str, shard: int,
                                    raw_shard, ds_cfg: dict) -> None:
        """Streaming downsampling (reference ShardDownsampler publishing the
        downsample stream): rollup records produced at flush time land in
        co-sharded ds datasets of the same memstore, queryable immediately.
        The scheduled batch job remains the consistency backstop after
        crashes (derived rows between the last ds flush and a crash are
        regenerated by the job, exactly the reference's split)."""
        from filodb_tpu.core.downsample.downsampler import (
            ShardDownsampler,
            ds_dataset_name,
        )
        from filodb_tpu.core.record import SomeData
        from filodb_tpu.core.store.config import StoreConfig

        resolutions = tuple(ds_cfg.get("resolutions_ms",
                                       (300_000, 3_600_000)))
        # downsampled data outlives raw (reference retention tiering:
        # raw → 5m → 1h with growing TTLs)
        ds_retention = ds_cfg.get("ds_retention_ms",
                                  raw_shard.config.retention_ms * 5)
        for res in resolutions:
            name = ds_dataset_name(dataset, res)
            try:
                ds_shard = self.memstore.setup(
                    dataset=name, shard=shard,
                    store_config=StoreConfig(
                        max_chunk_size=raw_shard.config.max_chunk_size,
                        retention_ms=ds_retention))
                ds_shard.recover_index()
            except ValueError:
                pass  # restart: already set up
        ms = self.memstore
        import itertools
        seq = itertools.count(1)

        def publish(res, container, _shard=shard, _ds=dataset):
            # monotonic offsets: the ds shard's own flush watermarks must
            # never skip later rollup batches
            name = ds_dataset_name(_ds, res)
            ms.ingest(name, _shard, SomeData(container, next(seq)))

        raw_shard.downsampler = ShardDownsampler(resolutions, publish)
        for res in resolutions:
            self._ds_shards.append((ds_dataset_name(dataset, res), shard))

    _ds_shards: list = None

    def __post_init__(self):
        self._ds_shards = []

    def stop_shard(self, dataset: str, shard: int) -> None:
        w = self._workers.pop((dataset, shard), None)
        if w:
            w.stop()
        self.memstore.teardown(dataset, shard)

    def promote_shard(self, dataset: str, shard: int,
                      config: IngestionConfig, shard_log: ReplayLog,
                      start_offset: int, on_status=None) -> None:
        """Failover fast path (follower → leader): the replica image is
        already warm — index recovered at follow time, WAL applied through
        ``start_offset`` — so ingestion resumes right there and the shard
        joins the flush schedule. Deliberately NO manifest refresh, index
        recovery, or watermark pass: the flip replays only the un-tailed
        WAL tail and performs zero durable-tier reads (the chaos soak's
        GET-accounting criterion)."""
        key = (dataset, shard)
        if key in self._workers:
            return
        s = self.memstore.get_shard(dataset, shard)
        worker = _IngestWorker(self, s, shard_log, start_offset, on_status)
        self._workers[key] = worker
        worker.start()
        self._register_lag_gauges(dataset, shard, s, shard_log, worker)
        if self._flusher is None:
            self._flusher = _FlushScheduler(self, self.flush_tick_s)
            self._flusher.start()

    # -- live migration (coordinator/migration.py source/destination API) --

    def prepare_handoff(self, dataset: str, shard: int) -> int:
        """Source side of a migration's SYNCING phase: flush every group
        (sealed segments ride the column store's write-behind path), drain
        the upload queue (the durability ack), and snapshot the index so
        the destination cold-recovers warm. Returns the source's latest
        ingested offset."""
        s = self.memstore.get_shard(dataset, shard)
        s.flush_all()
        FaultInjector.fire("migration.sync.upload", node=self.name,
                           dataset=dataset, shard=shard)
        flush = getattr(self.memstore.column_store, "flush", None)
        if callable(flush):
            flush()  # write-behind drain: raises if an upload failed
        FaultInjector.fire("migration.sync.checkpoint.before",
                           node=self.name, dataset=dataset, shard=shard)
        s.snapshot_index()
        FaultInjector.fire("migration.sync.checkpoint.after",
                           node=self.name, dataset=dataset, shard=shard)
        return s.latest_offset

    def shard_offset(self, dataset: str, shard: int) -> int:
        """Latest log offset this shard COVERS (-1 when not resident) —
        the migration's catch-up lag probe. A freshly-recovered shard that
        has replayed nothing still covers everything below its recovered
        group watermarks (every group is flushed through its checkpoint),
        so the covered offset is max(ingested, min over group
        watermarks) — without the watermark term, a destination with no
        reachable ingest tail would report -1 forever despite holding all
        of the source's flushed data."""
        try:
            s = self.memstore.get_shard(dataset, shard)
        except KeyError:
            return -1
        recovered = min(s.group_watermarks) if s.group_watermarks else -1
        return max(s.latest_offset, recovered)

    def kill(self) -> None:
        """Simulate process death (multi-jvm kill tests)."""
        self.alive = False
        for w in list(self._workers.values()):
            w.stop()
        self._workers.clear()
        if self._flusher is not None:
            self._flusher.stop()
            self._flusher = None

    def owned_shards(self, dataset: str) -> list[int]:
        return sorted(s for (d, s) in self._workers if d == dataset)


class _FlushScheduler(threading.Thread):
    """Per-node flush scheduler: walks each owned shard's flush groups
    round-robin, spacing group flushes so one full cycle spans the store's
    flush interval (reference time-staggered ``createFlushTasks``,
    ``TimeSeriesShard.scala:889``); also drives retention purge and
    memory-pressure eviction."""

    def __init__(self, node: "Node", tick_s: float | None = None):
        super().__init__(daemon=True, name=f"flush-{node.name}")
        self.node = node
        self.tick_s = tick_s
        self._stop_ev = threading.Event()
        self._last_snapshot: dict[tuple[str, int], float] = {}

    def run(self):
        while not self._stop_ev.wait(self._next_tick()):
            if not self.node.alive:
                return
            for (dataset, shard_num) in list(self.node._workers):
                try:
                    shard = self.node.memstore.get_shard(dataset, shard_num)
                except KeyError:
                    continue
                try:
                    shard.flush_group(shard.next_flush_group())
                    shard.enforce_memory()
                    shard.purge_expired(int(time.time() * 1000))
                    # WAL retention: everything at/below the min checkpoint
                    # watermark is durably persisted and replay skips it
                    w = self.node._workers.get((dataset, shard_num))
                    wm = min(shard.group_watermarks)
                    if (w is not None and wm >= 0
                            and hasattr(w.log, "truncate_before")):
                        w.log.truncate_before(wm + 1)
                    # periodic index snapshot (reference: the durable
                    # Lucene index dir) for fast restart at cardinality
                    interval = shard.config.index_snapshot_interval_ms
                    if interval:
                        key, now = (dataset, shard_num), time.time()
                        # first interval counts from first sight: no
                        # multi-second snapshot pause right at startup
                        last = self._last_snapshot.setdefault(key, now)
                        if now - last >= interval / 1000.0:
                            shard.snapshot_index()
                            self._last_snapshot[key] = now
                except Exception:
                    get_counter("filodb_flush_errors",
                                {"dataset": dataset,
                                 "shard": str(shard_num)}).inc()
                    log.exception("scheduled flush failed for %s/%d on "
                                  "node %s", dataset, shard_num,
                                  self.node.name)
            # streaming-downsample datasets flush on the same cadence
            for (ds_name, shard_num) in list(self.node._ds_shards or ()):
                try:
                    ds = self.node.memstore.get_shard(ds_name, shard_num)
                    ds.flush_group(ds.next_flush_group())
                except KeyError:
                    continue
                except Exception:
                    get_counter("filodb_flush_errors",
                                {"dataset": ds_name,
                                 "shard": str(shard_num)}).inc()
                    log.exception("ds flush failed for %s/%d on node %s",
                                  ds_name, shard_num, self.node.name)

    def _next_tick(self) -> float:
        if self.tick_s is not None:
            return self.tick_s
        # spacing = flush_interval / groups, bounded for sane defaults
        interval = 3_600.0
        groups = 20
        for (dataset, shard_num) in list(self.node._workers):
            try:
                cfg = self.node.memstore.get_shard(dataset,
                                                   shard_num).config
                interval = cfg.flush_interval_ms / 1000.0
                groups = cfg.groups_per_shard
                break
            except KeyError:
                continue
        return max(min(interval / max(groups, 1), 300.0), 0.5)

    def stop(self):
        self._stop_ev.set()


class _IngestWorker(threading.Thread):
    """Per-shard ingestion thread: replay from the recovery offset, then tail
    (the reference's per-shard single-writer ingest scheduler)."""

    def __init__(self, node: Node, shard, log_: ReplayLog, start_offset: int,
                 on_status=None, poll_interval: float = 0.01):
        super().__init__(daemon=True,
                         name=f"ingest-{shard.dataset}-{shard.shard_num}")
        self.node = node
        self.shard = shard
        self.log = log_
        self.offset = start_offset
        self.on_status = on_status
        self.poll_interval = poll_interval
        self._stop_ev = threading.Event()
        self.caught_up = threading.Event()

    # consecutive server-reported (deterministic) log errors tolerated
    # before the shard surfaces ERROR for reassignment — a corrupt broker
    # log answers identically forever, so spinning on it is pointless
    MAX_SERVER_ERRORS = 5

    def run(self):
        recovered = False
        server_errors = 0
        while not self._stop_ev.is_set() and self.node.alive:
            progressed = False
            it = self.log.read_from(self.offset + 1)
            transport_failed = False
            while True:
                try:
                    sd = next(it)
                except StopIteration:
                    # a successful (possibly empty) server reply: isolated
                    # one-off errors (e.g. a truncate racing a read) must
                    # not accumulate across hours of healthy polling
                    server_errors = 0
                    break
                except LogOpError:
                    # the broker ANSWERED with an error (corrupt log file,
                    # rejected request): deterministic, will not heal with
                    # retries. Tolerate a few (a truncate racing a read can
                    # produce one-offs), then surface ERROR for
                    # reassignment instead of spinning forever.
                    server_errors += 1
                    if server_errors >= self.MAX_SERVER_ERRORS:
                        log.error(
                            "shard %s/%d: %d consecutive server-side log "
                            "errors; surfacing ERROR", self.shard.dataset,
                            self.shard.shard_num, server_errors,
                            exc_info=True)
                        if self.on_status:
                            self.on_status(self.shard.shard_num,
                                           ShardStatus.ERROR, 0)
                        return
                    log.warning("shard %s/%d server-side log error "
                                "(%d/%d); retrying", self.shard.dataset,
                                self.shard.shard_num, server_errors,
                                self.MAX_SERVER_ERRORS, exc_info=True)
                    time.sleep(min(self.poll_interval * 100, 1.0))
                    transport_failed = True
                    break
                except (ConnectionError, OSError, RuntimeError):
                    # transient log TRANSPORT failure (broker restart, TCP
                    # reset): keep the worker alive and retry from the last
                    # ingested offset — dying here would silently halt the
                    # shard forever. Only the read is guarded: a
                    # deterministic ingest error must surface, not spin.
                    log.warning("shard %s/%d log read failed; retrying",
                                self.shard.dataset, self.shard.shard_num,
                                exc_info=True)
                    time.sleep(min(self.poll_interval * 100, 1.0))
                    transport_failed = True
                    break
                if self._stop_ev.is_set() or not self.node.alive:
                    return
                try:
                    self.shard.ingest(sd)
                except Exception:
                    # poison record / deterministic ingest failure: surface
                    # it as shard ERROR so the coordinator can reassign —
                    # dying silently would leave the shard ACTIVE-but-dead
                    get_counter("filodb_ingest_errors",
                                {"dataset": self.shard.dataset,
                                 "shard": str(self.shard.shard_num)}).inc()
                    log.exception("shard %s/%d ingest failed at offset %d; "
                                  "stopping worker", self.shard.dataset,
                                  self.shard.shard_num, sd.offset)
                    if self.on_status:
                        self.on_status(self.shard.shard_num,
                                       ShardStatus.ERROR, 0)
                    return
                self.offset = sd.offset
                progressed = True
                server_errors = 0
                # close the e2e freshness loop: any gateway stamps at or
                # below this offset are now queryable in the shard
                STAMPS.observe(self.shard.dataset, self.shard.shard_num,
                               sd.offset)
            if transport_failed:
                continue
            if not recovered:
                recovered = True
                self.caught_up.set()
                if self.on_status:
                    self.on_status(self.shard.shard_num, ShardStatus.ACTIVE,
                                   100)
            if not progressed:
                time.sleep(self.poll_interval)

    def stop(self):
        self._stop_ev.set()
        self.join(timeout=5)


@dataclass
class FilodbCluster:
    """The cluster singleton: membership + shard managers + dataset setup."""

    nodes: dict[str, Node] = field(default_factory=dict)
    shard_managers: dict[str, ShardManager] = field(default_factory=dict)
    configs: dict[str, IngestionConfig] = field(default_factory=dict)
    logs: dict[tuple[str, int], ReplayLog] = field(default_factory=dict)
    heartbeat_interval_s: float = 0.05
    # consecutive missed heartbeats before a node is declared down (the
    # reference's phi-accrual detector likewise tolerates transient misses)
    failure_threshold: int = 3
    on_heartbeat: list = field(default_factory=list)  # callbacks per tick
    # live migrations in flight, keyed (dataset, shard); auto_rebalance
    # triggers them on node join (config "migration" block)
    migrations: dict = field(default_factory=dict)
    auto_rebalance: bool = False
    migration_lag_threshold: int = 0
    migration_catchup_timeout_s: float = 30.0
    # continuous replication ("replication" config block): maintain this
    # many follower replicas per shard on other in-process members; 0 off
    replication: int = 0
    replica_in_sync_lag: int = 0    # max offset lag still counted in-sync
    replica_hedge_s: float = 0.05   # hedge timer for replica reads
    replica_durable_sync_s: float = 5.0  # follower sealed-segment sync cadence
    # live follower syncers, keyed (dataset, shard, node)
    replica_syncers: dict = field(default_factory=dict)
    _hb_misses: dict = field(default_factory=dict)
    _hb_thread: threading.Thread | None = None
    _stop_hb: threading.Event = field(default_factory=threading.Event)

    # -- membership --

    def join(self, node: Node) -> None:
        self.nodes[node.name] = node
        if getattr(node, "executor_port", None):
            # a (re)joining remote member starts with a clean slate: its
            # breaker closes so query routing dials it again immediately
            host = getattr(node, "host", "127.0.0.1")
            breaker_for(f"{host}:{node.executor_port}").record_success()
        for dataset, sm in self.shard_managers.items():
            for ev in sm.add_member(node.name):
                self._on_event(dataset, ev)
        if self.auto_rebalance and self.shard_managers:
            # level shard counts onto the joiner via live migrations, off
            # the caller's thread (a handoff blocks through catch-up)
            threading.Thread(
                target=lambda: [self.maybe_rebalance(d)
                                for d in list(self.shard_managers)],
                daemon=True, name=f"rebalance-{node.name}").start()

    def leave(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node:
            if getattr(node, "executor_port", None):
                # declared down: open the breaker so in-flight and future
                # queries skip the peer without paying a connect timeout
                host = getattr(node, "host", "127.0.0.1")
                breaker_for(f"{host}:{node.executor_port}").force_open()
            node.kill()
        for dataset, sm in self.shard_managers.items():
            for ev in sm.remove_member(name):
                self._on_event(dataset, ev)

    # -- datasets --

    def setup_dataset(self, config: IngestionConfig,
                      logs: dict[int, ReplayLog]) -> None:
        """Reference ``NodeClusterActor ! SetupDataset``."""
        dataset = config.dataset
        self.configs[dataset] = config
        for shard, log_ in logs.items():
            self.logs[(dataset, shard)] = log_
        sm = ShardManager(dataset, config.num_shards, config.min_num_nodes)
        self.shard_managers[dataset] = sm
        for name in self.nodes:
            for ev in sm.add_member(name):
                self._on_event(dataset, ev)

    def _on_event(self, dataset: str, ev) -> None:
        if getattr(ev, "replica", False):
            # a follower dropping out of a replica set stops its syncer;
            # upserts are the syncer's own reports — nothing to drive
            if ev.node and ev.status in (ShardStatus.STOPPED,
                                         ShardStatus.DOWN,
                                         ShardStatus.UNASSIGNED):
                sy = self.replica_syncers.pop((dataset, ev.shard, ev.node),
                                              None)
                if sy is not None:
                    sy.stop()
            return
        if ev.status == ShardStatus.ACTIVE and ev.node and \
                (dataset, ev.shard, ev.node) in self.replica_syncers:
            # promotion map flip: the ACTIVE event names a node we hold a
            # follower syncer for — hand its warm image to the ingest path
            sy = self.replica_syncers.pop((dataset, ev.shard, ev.node))
            self.nodes[ev.node].promote_shard(
                dataset, ev.shard, self.configs[dataset],
                self.logs[(dataset, ev.shard)], sy.promote(),
                self._status_cb(dataset, ev.node))
            return
        if ev.status == ShardStatus.ASSIGNED and ev.node:
            node = self.nodes[ev.node]
            node.start_shard(dataset, ev.shard, self.configs[dataset],
                             self.logs[(dataset, ev.shard)],
                             self._status_cb(dataset, ev.node))

    def _status_cb(self, dataset: str, node: str):
        sm = self.shard_managers[dataset]

        def on_status(shard, status, progress, _node=node):
            if status == ShardStatus.ACTIVE:
                sm.shard_active(shard, _node)
            elif status == ShardStatus.RECOVERY:
                sm.shard_recovery(shard, _node, progress)

        return on_status

    # -- continuous replication --

    def ensure_replicas(self, dataset: str) -> None:
        """Converge each shard's follower set toward ``replication``
        replicas: prune syncers whose node died or took leadership, then
        start new followers on the least-loaded live in-process members.
        Idempotent; runs every heartbeat tick, so replica placement heals
        after joins, leaves, and promotions without a dedicated planner."""
        if not self.replication:
            return
        sm = self.shard_managers.get(dataset)
        if sm is None:
            return
        for shard in range(sm.num_shards):
            owner = sm.mapper.node_for(shard)
            for name in list(sm.mapper.replicas_of(shard)):
                node = self.nodes.get(name)
                sy = self.replica_syncers.get((dataset, shard, name))
                dead_tail = (sy is not None and sy._tail is not None
                             and not sy._tail.is_alive())
                if node is None or not node.alive or name == owner \
                        or dead_tail:
                    sy = self.replica_syncers.pop((dataset, shard, name),
                                                  None)
                    if sy is not None:
                        sy.stop()
                    sm.drop_replica(shard, name)
            if owner is None:
                continue  # followers of a DOWN shard keep tailing as-is
            # count syncers still bootstrapping (not yet in the mapper)
            # so a slow bootstrap is not doubled up on the next tick
            have = set(sm.mapper.replicas_of(shard))
            have |= {n for (d, s, n) in self.replica_syncers
                     if d == dataset and s == shard}
            need = self.replication - len(have)
            if need <= 0:
                continue
            cands = [n for n, nd in self.nodes.items()
                     if nd.alive and getattr(nd, "memstore", None)
                     is not None and n != owner and n not in have]
            cands.sort(key=lambda n: len(sm.mapper.follower_shards(n)))
            for name in cands[:need]:
                sy = ReplicaSyncer(
                    self.nodes[name], dataset, shard,
                    self.configs[dataset], self.logs[(dataset, shard)],
                    sm, in_sync_lag=self.replica_in_sync_lag,
                    durable_sync_interval_s=self.replica_durable_sync_s)
                self.replica_syncers[(dataset, shard, name)] = sy
                sy.start()

    # -- live migration / rebalancing --

    def _migration_store(self):
        """The shared column store migration manifests persist beside —
        any in-process member's view of it (all members share one durable
        tier)."""
        for node in self.nodes.values():
            ms = getattr(node, "memstore", None)
            if ms is not None:
                return ms.column_store
        raise MigrationError("no in-process column store for the "
                             "migration manifest; pass store= explicitly")

    def migrate_shard(self, dataset: str, shard: int, dest: str,
                      store=None, **kw) -> ShardMigration:
        """Move one shard to ``dest`` through the crash-safe state machine
        (blocks until DONE; run in a thread for live traffic). The source
        is the current owner from the shard map."""
        sm = self.shard_managers[dataset]
        source = sm.mapper.node_for(shard)
        if source is None:
            raise MigrationError(f"shard {shard} has no owner to migrate "
                                 "from")
        kw.setdefault("lag_threshold", self.migration_lag_threshold)
        kw.setdefault("catchup_timeout_s", self.migration_catchup_timeout_s)
        mig = ShardMigration(self, store or self._migration_store(),
                             dataset, shard, source, dest, **kw)
        self.migrations[(dataset, shard)] = mig
        try:
            return mig.run()
        finally:
            if mig.phase in ("done", "aborted"):
                self.migrations.pop((dataset, shard), None)

    def resume_migration(self, dataset: str, shard: int, store=None,
                         **kw) -> ShardMigration | None:
        """Continue a migration whose driver crashed, from its durable
        manifest."""
        return ShardMigration.resume(self, store or self._migration_store(),
                                     dataset, shard, **kw)

    def maybe_rebalance(self, dataset: str, overloaded: str | None = None,
                        min_imbalance: int = 2) -> list[ShardMigration]:
        """Run the planned rebalance moves (node join levels shard counts;
        ``overloaded`` sheds away from a pressured node). One migration at
        a time per dataset — a handoff is heavyweight."""
        sm = self.shard_managers.get(dataset)
        if sm is None:
            return []
        done = []
        for shard, src, dst in sm.plan_rebalance(overloaded, min_imbalance):
            if (dataset, shard) in self.migrations:
                continue
            try:
                done.append(self.migrate_shard(dataset, shard, dst))
            except Exception:
                get_counter("filodb_shard_migration_errors",
                            {"dataset": dataset}).inc()
                log.exception("rebalance migration of %s/%d %s -> %s "
                              "failed", dataset, shard, src, dst)
                break
        return done

    def shed_load(self, node_name: str) -> list[ShardMigration]:
        """MemoryWatchdog overload trigger: move one shard off the
        pressured node per dataset, even when counts are level."""
        out = []
        for dataset in list(self.shard_managers):
            out += self.maybe_rebalance(dataset, overloaded=node_name,
                                        min_imbalance=1)
        return out

    # -- failure detection --

    def start_failure_detector(self) -> None:
        """Heartbeat loop (reference: Akka phi-accrual → MemberRemoved)."""
        if self._hb_thread:
            return
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()

    def _hb_loop(self):
        while not self._stop_hb.wait(self.heartbeat_interval_s):
            for name, node in list(self.nodes.items()):
                if node.alive:
                    self._hb_misses[name] = 0
                    continue
                misses = self._hb_misses.get(name, 0) + 1
                self._hb_misses[name] = misses
                if misses >= self.failure_threshold:
                    log.warning("failure detector: node %s down "
                                "(%d missed heartbeats)", name, misses)
                    self.leave(name)
                    self._hb_misses.pop(name, None)
            # membership check: rate-limit-deferred shards whose interval
            # elapsed get reassigned now, not on the next unrelated event
            for dataset, sm in list(self.shard_managers.items()):
                for ev in sm.check_deferred():
                    try:
                        self._on_event(dataset, ev)
                    except Exception:
                        get_counter("filodb_heartbeat_errors").inc()
                        log.exception("deferred reassignment of %s/%d "
                                      "failed", dataset, ev.shard)
                try:
                    self.ensure_replicas(dataset)
                except Exception:
                    get_counter("filodb_heartbeat_errors").inc()
                    log.exception("replica convergence for %s failed",
                                  dataset)
            for cb in self.on_heartbeat:
                try:
                    cb()
                except Exception:
                    get_counter("filodb_heartbeat_errors").inc()
                    log.exception("heartbeat callback %s failed",
                                  getattr(cb, "__name__", repr(cb)))

    def stop(self):
        self._stop_hb.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=2)
        for sy in list(self.replica_syncers.values()):
            sy.stop()
        self.replica_syncers.clear()
        for node in list(self.nodes.values()):
            node.kill()

    # -- query --

    def query_service(self, dataset: str, spread: int = 0,
                      engine: str = "exec",
                      result_cache=None,
                      federation=None) -> QueryService:
        """Planner whose leaves dispatch to the shard-owning nodes.
        ``engine="mesh"`` additionally lowers supported aggregations onto
        the device mesh when all shards are local (single-node).
        ``result_cache`` is a ``result_cache`` config block (dict) enabling
        the extent result cache; it self-bypasses when shards are remote.
        ``federation`` is a federation config block (dict with at least
        ``mem_retention_ms``): ranges older than memstore retention are
        routed to the shared column store through a cold-tier planner and
        stitched with the hot result (see query/federation.py)."""
        sm = self.shard_managers[dataset]
        cluster = self

        def candidate_for(name: str, follower: bool = False
                          ) -> ReplicaCandidate:
            node = cluster.nodes[name]
            if getattr(node, "memstore", None) is not None:
                # in-process member: breaker-guard under the node name
                return ReplicaCandidate(name, NodeDispatcher(node),
                                        follower=follower, guard=True)
            from filodb_tpu.coordinator.remote import RemotePlanDispatcher
            host = getattr(node, "host", "127.0.0.1")
            # the dispatcher's breaker guard skips open peers at dispatch
            # time (CircuitOpenError → scatter-gather partial result); the
            # failure detector force-opens breakers of departed members so
            # the skip never pays a connect timeout
            d = RemotePlanDispatcher(host, node.executor_port)
            return ReplicaCandidate(d.peer, d, follower=follower,
                                    guard=False)

        def dispatcher_for_shard(shard: int) -> PlanDispatcher:
            # read the follower set BEFORE the owner slot: a concurrent
            # promotion writes the new owner first, then pops it from the
            # replica set — this order never observes "stale dead owner +
            # empty follower set", which would route a read solely at the
            # dead leader mid-flip
            followers = [n for n in sm.mapper.in_sync_followers(shard)
                         if n in cluster.nodes]
            owner = sm.mapper.node_for(shard)
            followers = [n for n in followers if n != owner]
            if not followers:
                if owner is None or owner not in cluster.nodes:
                    raise RuntimeError(f"shard {shard} unassigned")
                return candidate_for(owner).dispatcher
            # replica set: leader first (writes & freshest reads), then
            # in-sync followers; EWMA ordering + hedging inside
            cands = []
            if owner is not None and owner in cluster.nodes:
                cands.append(candidate_for(owner))
            cands += [candidate_for(n, follower=True) for n in followers]
            return ReplicaDispatcher(
                shard, cands, hedge_timeout_s=cluster.replica_hedge_s)

        # the facade's local memstore is only used for metadata fan-out;
        # use the first node's
        any_node = next(iter(self.nodes.values()))
        svc = QueryService(any_node.memstore, dataset,
                           self.configs[dataset].num_shards, spread,
                           engine=engine, result_cache=result_cache)
        svc.planner = SingleClusterPlanner(
            dataset, self.configs[dataset].num_shards, spread,
            dispatcher_for_shard=dispatcher_for_shard)
        if federation and federation.get("enabled", True) \
                and federation.get("mem_retention_ms"):
            from filodb_tpu.coordinator.tiered_planner import (
                build_tiered_planner)
            svc.planner = build_tiered_planner(
                svc.planner, self._migration_store(), dataset,
                self.configs[dataset].num_shards, spread,
                mem_retention_ms=int(federation["mem_retention_ms"]),
                raw_retention_ms=federation.get("raw_retention_ms"),
                odp_max_chunks=int(federation.get("odp_max_chunks",
                                                  10_000)),
                refresh_s=float(federation.get("refresh_s", 60.0)))
        def shard_status_fn():
            out = []
            for s in range(sm.num_shards):
                st = sm.mapper.statuses[s]
                if st in (ShardStatus.RECOVERY, ShardStatus.HANDOFF):
                    out.append((s, st.name.lower()))
                    continue
                if st != ShardStatus.ACTIVE:
                    continue
                owner = sm.mapper.node_for(s)
                node = cluster.nodes.get(owner) if owner else None
                unhealthy = node is None or not getattr(node, "alive", True)
                if not unhealthy and getattr(node, "executor_port", None) \
                        and getattr(node, "memstore", None) is None:
                    host = getattr(node, "host", "127.0.0.1")
                    unhealthy = breaker_for(
                        f"{host}:{node.executor_port}").is_open
                followers = sm.mapper.in_sync_followers(s)
                if unhealthy and followers:
                    # the replica dispatcher will serve this shard from a
                    # follower — surface that as a result warning
                    out.append((s, f"served by follower {followers[0]}"))
            return out

        svc.shard_status_fn = shard_status_fn
        return svc

    def shard_statuses(self, dataset: str) -> list[dict]:
        sm = self.shard_managers.get(dataset)
        return sm.mapper.snapshot() if sm else []

    def wait_active(self, dataset: str, timeout: float = 10.0) -> bool:
        """Wait until every shard reached ACTIVE (recovery complete) —
        RECOVERY shards are queryable but still replaying."""
        sm = self.shard_managers[dataset]
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if all(st == ShardStatus.ACTIVE for st in sm.mapper.statuses):
                return True
            time.sleep(0.01)
        return False
