"""FilodbCluster: node membership, per-node shard lifecycle, failure
detection and recovery.

Counterpart of the reference's Akka-cluster control plane
(``FilodbCluster.scala:31,40``, ``NodeClusterActor.scala:61,187,368-412``
cluster-singleton + ``ShardManager``, ``IngestionActor.scala:43-57,237,294``,
``NodeCoordinatorActor``): a coordinator (in the real deployment: one
elected node; here a plain object shareable in-process or fronted by RPC)
tracks members, assigns shards, and drives per-node ingestion lifecycles:

  start shard → recover index from column store → read checkpoints →
  replay the shard's log from min(checkpoint) (group watermarks skip
  persisted rows) → continuous ingestion (reference ``doRecovery`` →
  ``normalIngestion``).

Failure detection: heartbeat probes over the plan-shipping channel (or
liveness flags for in-process nodes) stand in for Akka's phi-accrual
detector; on member loss, shards are marked DOWN and reassigned, and the new
owner recovers from the shared column store + log — the reference's
elastic-recovery story (``doc/sharding.md:158``).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.coordinator.shard_manager import ShardManager
from filodb_tpu.coordinator.shardmapper import ShardStatus
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import IngestionConfig
from filodb_tpu.kafka.log import ReplayLog
from filodb_tpu.query.exec.plan import ExecContext, PlanDispatcher

log = logging.getLogger(__name__)


class NodeDispatcher(PlanDispatcher):
    """In-process dispatch to another node's memstore (stands in for the
    remote dispatcher when nodes share a process, e.g. tests)."""

    def __init__(self, node: "Node"):
        self.node = node

    def dispatch(self, plan, ctx):
        if not self.node.alive:
            raise ConnectionError(f"node {self.node.name} is down")
        ctx2 = ExecContext(self.node.memstore, ctx.dataset, ctx.qcontext)
        return plan.execute(ctx2)


@dataclass
class Node:
    """One cluster member: local memstore + ingestion workers.

    Reference: one FiloServer process (NodeCoordinatorActor + per-dataset
    IngestionActor/QueryActor).
    """

    name: str
    memstore: TimeSeriesMemStore
    alive: bool = True
    executor_port: int | None = None  # set when fronted by PlanExecutorServer
    flush_tick_s: float | None = None  # override scheduler cadence (tests)
    _workers: dict = field(default_factory=dict)  # (dataset, shard) -> worker
    _flusher: object = None

    def start_shard(self, dataset: str, shard: int, config: IngestionConfig,
                    shard_log: ReplayLog, on_status=None) -> None:
        """Start ingestion for a shard: recover then tail the log
        (reference ``IngestionActor.start``)."""
        key = (dataset, shard)
        if key in self._workers:
            return
        try:
            self.memstore.setup(dataset, shard, config.store)
        except ValueError:
            pass  # already set up (restart)
        s = self.memstore.get_shard(dataset, shard)
        s.recover_index()
        start_offset = s.setup_watermarks_for_recovery()
        if on_status:
            on_status(shard, ShardStatus.RECOVERY, 0)
        worker = _IngestWorker(self, s, shard_log, start_offset, on_status)
        self._workers[key] = worker
        worker.start()
        if self._flusher is None:
            self._flusher = _FlushScheduler(self, self.flush_tick_s)
            self._flusher.start()

    def stop_shard(self, dataset: str, shard: int) -> None:
        w = self._workers.pop((dataset, shard), None)
        if w:
            w.stop()
        self.memstore.teardown(dataset, shard)

    def kill(self) -> None:
        """Simulate process death (multi-jvm kill tests)."""
        self.alive = False
        for w in list(self._workers.values()):
            w.stop()
        self._workers.clear()
        if self._flusher is not None:
            self._flusher.stop()
            self._flusher = None

    def owned_shards(self, dataset: str) -> list[int]:
        return sorted(s for (d, s) in self._workers if d == dataset)


class _FlushScheduler(threading.Thread):
    """Per-node flush scheduler: walks each owned shard's flush groups
    round-robin, spacing group flushes so one full cycle spans the store's
    flush interval (reference time-staggered ``createFlushTasks``,
    ``TimeSeriesShard.scala:889``); also drives retention purge and
    memory-pressure eviction."""

    def __init__(self, node: "Node", tick_s: float | None = None):
        super().__init__(daemon=True, name=f"flush-{node.name}")
        self.node = node
        self.tick_s = tick_s
        self._stop_ev = threading.Event()

    def run(self):
        while not self._stop_ev.wait(self._next_tick()):
            if not self.node.alive:
                return
            for (dataset, shard_num) in list(self.node._workers):
                try:
                    shard = self.node.memstore.get_shard(dataset, shard_num)
                except KeyError:
                    continue
                try:
                    shard.flush_group(shard.next_flush_group())
                    shard.enforce_memory()
                    shard.purge_expired(int(time.time() * 1000))
                    # WAL retention: everything at/below the min checkpoint
                    # watermark is durably persisted and replay skips it
                    w = self.node._workers.get((dataset, shard_num))
                    wm = min(shard.group_watermarks)
                    if (w is not None and wm >= 0
                            and hasattr(w.log, "truncate_before")):
                        w.log.truncate_before(wm + 1)
                except Exception:
                    log.exception("scheduled flush failed for %s/%d",
                                  dataset, shard_num)

    def _next_tick(self) -> float:
        if self.tick_s is not None:
            return self.tick_s
        # spacing = flush_interval / groups, bounded for sane defaults
        interval = 3_600.0
        groups = 20
        for (dataset, shard_num) in list(self.node._workers):
            try:
                cfg = self.node.memstore.get_shard(dataset,
                                                   shard_num).config
                interval = cfg.flush_interval_ms / 1000.0
                groups = cfg.groups_per_shard
                break
            except KeyError:
                continue
        return max(min(interval / max(groups, 1), 300.0), 0.5)

    def stop(self):
        self._stop_ev.set()


class _IngestWorker(threading.Thread):
    """Per-shard ingestion thread: replay from the recovery offset, then tail
    (the reference's per-shard single-writer ingest scheduler)."""

    def __init__(self, node: Node, shard, log_: ReplayLog, start_offset: int,
                 on_status=None, poll_interval: float = 0.01):
        super().__init__(daemon=True,
                         name=f"ingest-{shard.dataset}-{shard.shard_num}")
        self.node = node
        self.shard = shard
        self.log = log_
        self.offset = start_offset
        self.on_status = on_status
        self.poll_interval = poll_interval
        self._stop_ev = threading.Event()
        self.caught_up = threading.Event()

    def run(self):
        recovered = False
        while not self._stop_ev.is_set() and self.node.alive:
            progressed = False
            for sd in self.log.read_from(self.offset + 1):
                if self._stop_ev.is_set() or not self.node.alive:
                    return
                self.shard.ingest(sd)
                self.offset = sd.offset
                progressed = True
            if not recovered:
                recovered = True
                self.caught_up.set()
                if self.on_status:
                    self.on_status(self.shard.shard_num, ShardStatus.ACTIVE,
                                   100)
            if not progressed:
                time.sleep(self.poll_interval)

    def stop(self):
        self._stop_ev.set()
        self.join(timeout=5)


@dataclass
class FilodbCluster:
    """The cluster singleton: membership + shard managers + dataset setup."""

    nodes: dict[str, Node] = field(default_factory=dict)
    shard_managers: dict[str, ShardManager] = field(default_factory=dict)
    configs: dict[str, IngestionConfig] = field(default_factory=dict)
    logs: dict[tuple[str, int], ReplayLog] = field(default_factory=dict)
    heartbeat_interval_s: float = 0.05
    # consecutive missed heartbeats before a node is declared down (the
    # reference's phi-accrual detector likewise tolerates transient misses)
    failure_threshold: int = 3
    on_heartbeat: list = field(default_factory=list)  # callbacks per tick
    _hb_misses: dict = field(default_factory=dict)
    _hb_thread: threading.Thread | None = None
    _stop_hb: threading.Event = field(default_factory=threading.Event)

    # -- membership --

    def join(self, node: Node) -> None:
        self.nodes[node.name] = node
        for dataset, sm in self.shard_managers.items():
            for ev in sm.add_member(node.name):
                self._on_event(dataset, ev)

    def leave(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node:
            node.kill()
        for dataset, sm in self.shard_managers.items():
            for ev in sm.remove_member(name):
                self._on_event(dataset, ev)

    # -- datasets --

    def setup_dataset(self, config: IngestionConfig,
                      logs: dict[int, ReplayLog]) -> None:
        """Reference ``NodeClusterActor ! SetupDataset``."""
        dataset = config.dataset
        self.configs[dataset] = config
        for shard, log_ in logs.items():
            self.logs[(dataset, shard)] = log_
        sm = ShardManager(dataset, config.num_shards, config.min_num_nodes)
        self.shard_managers[dataset] = sm
        for name in self.nodes:
            for ev in sm.add_member(name):
                self._on_event(dataset, ev)

    def _on_event(self, dataset: str, ev) -> None:
        if ev.status == ShardStatus.ASSIGNED and ev.node:
            node = self.nodes[ev.node]
            config = self.configs[dataset]
            sm = self.shard_managers[dataset]

            def on_status(shard, status, progress, _node=ev.node):
                if status == ShardStatus.ACTIVE:
                    sm.shard_active(shard, _node)
                elif status == ShardStatus.RECOVERY:
                    sm.shard_recovery(shard, _node, progress)

            node.start_shard(dataset, ev.shard, config,
                             self.logs[(dataset, ev.shard)], on_status)

    # -- failure detection --

    def start_failure_detector(self) -> None:
        """Heartbeat loop (reference: Akka phi-accrual → MemberRemoved)."""
        if self._hb_thread:
            return
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()

    def _hb_loop(self):
        while not self._stop_hb.wait(self.heartbeat_interval_s):
            for name, node in list(self.nodes.items()):
                if node.alive:
                    self._hb_misses[name] = 0
                    continue
                misses = self._hb_misses.get(name, 0) + 1
                self._hb_misses[name] = misses
                if misses >= self.failure_threshold:
                    log.warning("failure detector: node %s down "
                                "(%d missed heartbeats)", name, misses)
                    self.leave(name)
                    self._hb_misses.pop(name, None)
            for cb in self.on_heartbeat:
                try:
                    cb()
                except Exception:
                    log.exception("heartbeat callback failed")

    def stop(self):
        self._stop_hb.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=2)
        for node in list(self.nodes.values()):
            node.kill()

    # -- query --

    def query_service(self, dataset: str, spread: int = 0) -> QueryService:
        """Planner whose leaves dispatch to the shard-owning nodes."""
        sm = self.shard_managers[dataset]
        cluster = self

        def dispatcher_for_shard(shard: int) -> PlanDispatcher:
            owner = sm.mapper.node_for(shard)
            if owner is None:
                raise RuntimeError(f"shard {shard} unassigned")
            node = cluster.nodes[owner]
            if getattr(node, "memstore", None) is not None:
                return NodeDispatcher(node)  # in-process member
            from filodb_tpu.coordinator.remote import RemotePlanDispatcher
            return RemotePlanDispatcher(getattr(node, "host", "127.0.0.1"),
                                        node.executor_port)

        # the facade's local memstore is only used for metadata fan-out;
        # use the first node's
        any_node = next(iter(self.nodes.values()))
        svc = QueryService(any_node.memstore, dataset,
                           self.configs[dataset].num_shards, spread)
        svc.planner = SingleClusterPlanner(
            dataset, self.configs[dataset].num_shards, spread,
            dispatcher_for_shard=dispatcher_for_shard)
        return svc

    def shard_statuses(self, dataset: str) -> list[dict]:
        sm = self.shard_managers.get(dataset)
        return sm.mapper.snapshot() if sm else []

    def wait_active(self, dataset: str, timeout: float = 10.0) -> bool:
        """Wait until every shard reached ACTIVE (recovery complete) —
        RECOVERY shards are queryable but still replaying."""
        sm = self.shard_managers[dataset]
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if all(st == ShardStatus.ACTIVE for st in sm.mapper.statuses):
                return True
            time.sleep(0.01)
        return False
