"""ShardManager: shard ↔ node assignment on membership change.

Counterpart of reference ``ShardManager.scala:28,40`` +
``ShardAssignmentStrategy.scala:9,36``: assigns shards to nodes on member
add/remove via a pluggable strategy (default: spread evenly, stable for
existing assignments), publishes shard events to subscribers, and
rate-limits auto-reassignment after failures
(``shard-manager.reassignment-min-interval``).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field

from filodb_tpu.coordinator.shardmapper import (
    ShardEvent,
    ShardMapper,
    ShardStatus,
)
from filodb_tpu.utils import racecheck

log = logging.getLogger(__name__)


class ShardAssignmentStrategy:
    def assignments(self, mapper: ShardMapper, nodes: list[str],
                    min_num_nodes: int = 1) -> dict[int, str]:
        """Return {shard: node} for shards that should (re)assign."""
        raise NotImplementedError


class DefaultShardAssignmentStrategy(ShardAssignmentStrategy):
    """Spread unassigned shards across nodes, keeping counts balanced and
    existing assignments stable (reference default strategy): a node takes at
    most ceil(num_shards / max(num_nodes, min_num_nodes)) shards, so early
    joiners leave capacity for the expected cluster size."""

    def assignments(self, mapper, nodes, min_num_nodes: int = 1):
        if not nodes:
            return {}
        per_node = {n: len(mapper.shards_of(n)) for n in nodes}
        max_per_node = -(-mapper.num_shards
                         // max(len(nodes), min_num_nodes))
        out = {}
        for shard in mapper.unassigned_shards():
            # least-loaded node with capacity
            candidates = [n for n in nodes if per_node[n] < max_per_node]
            if not candidates:
                break
            node = min(candidates, key=lambda n: per_node[n])
            out[shard] = node
            per_node[node] += 1
        return out


@dataclass
class ShardManager:
    """Per-dataset shard coordination (held by the cluster singleton)."""

    dataset: str
    num_shards: int
    min_num_nodes: int = 1
    strategy: ShardAssignmentStrategy = field(
        default_factory=DefaultShardAssignmentStrategy)
    reassignment_min_interval_s: float = 0.0
    mapper: ShardMapper = field(init=False)
    subscribers: list = field(default_factory=list)
    _nodes: list[str] = field(default_factory=list)
    _last_reassign: dict[int, float] = field(default_factory=dict)
    # shards whose reassignment was rate-limited: retried by
    # check_deferred() once reassignment_min_interval_s elapses
    _deferred: set[int] = field(default_factory=set)
    # sequenced event log for remote subscribers (reference StatusActor
    # ack/resync, ``StatusActor.scala:41``): followers poll with their last
    # -seen sequence; a gap beyond the retained window forces a resync
    event_log_cap: int = 512
    _seq: int = 0
    _event_log: list = field(default_factory=list)  # [(seq, ShardEvent)]
    # _publish runs on heartbeat/join threads; events_since on executor
    # handler threads — the log and mapper snapshot need a lock
    _ev_lock: object = field(init=False, repr=False)

    def __post_init__(self):
        # created here rather than via default_factory: a class-body
        # default_factory captures threading.Lock at import time, so the
        # lock would dodge lockcheck's wrapping and every _publish write
        # would look guard-free to the race sanitizer
        self._ev_lock = threading.Lock()
        self.mapper = ShardMapper(self.num_shards)
        # feed-generation token: a restarted coordinator resets _seq to 0,
        # and a follower whose ack lands inside the NEW feed's range would
        # otherwise silently skip events (neither behind nor ahead fires).
        # Followers echo the epoch; any change forces a snapshot resync.
        self.epoch = uuid.uuid4().hex[:16]
        # shared across heartbeat/join/migration/executor-handler threads
        racecheck.register(self, f"ShardManager[{self.dataset}]")

    # -- membership --

    def add_member(self, node: str) -> list[ShardEvent]:
        if node in self._nodes:
            return []
        self._nodes.append(node)
        # a join is a membership check: deferred (rate-limited) shards whose
        # interval has elapsed rejoin the assignable pool first
        events = self.check_deferred()
        events += self._assign()
        return events

    def remove_member(self, node: str) -> list[ShardEvent]:
        """Node lost: promote an in-sync follower where one exists (the map
        flip — ONE sequenced ACTIVE event, no DOWN window), otherwise mark
        the shard down and reassign (rate-limited) for cold recovery
        (reference ``removeMember`` → ``MemberRemoved`` handling). A shard
        inside its rate-limit interval is NOT dropped on the floor: it is
        recorded in ``_deferred`` and reassigned by :meth:`check_deferred`
        on the next membership check once the interval elapses."""
        if node not in self._nodes:
            return []
        self._nodes.remove(node)
        events = []
        now = time.monotonic()
        # follower roles held by the dead node die with it
        for shard in self.mapper.follower_shards(node):
            events.append(self._publish(ShardEvent(
                shard, ShardStatus.STOPPED, node, replica=True)))
        down = []
        for shard in self.mapper.shards_of(node):
            best = self._promotion_candidate(shard)
            if best is not None:
                events.append(self.promote(shard, best))
                continue
            down.append(shard)
            events.append(self._publish(ShardEvent(shard, ShardStatus.DOWN,
                                                   None)))
        if len(self._nodes) >= self.min_num_nodes:
            for shard in down:
                last = self._last_reassign.get(shard, 0.0)
                if now - last < self.reassignment_min_interval_s:
                    log.warning("shard %d reassignment rate-limited; "
                                "deferred for retry", shard)
                    self._deferred.add(shard)
                    continue
                self._last_reassign[shard] = now
            events += self._assign()
        return events

    def check_deferred(self) -> list[ShardEvent]:
        """Reassign rate-limited shards whose interval has elapsed. Called
        from every membership change and heartbeat tick, so a deferred
        shard no longer waits for an unrelated membership event. A deferred
        shard that meanwhile gained an owner (a follower promotion handled
        it) is dropped rather than reassigned — retrying it would
        double-assign the shard over its promoted leader; one whose replica
        set caught up since the failure is promoted instead of
        cold-recovered."""
        if not self._deferred:
            return []
        now = time.monotonic()
        ready = [s for s in self._deferred
                 if now - self._last_reassign.get(s, 0.0)
                 >= self.reassignment_min_interval_s]
        if not ready or len(self._nodes) < self.min_num_nodes:
            return []
        events = []
        for s in ready:
            self._deferred.discard(s)
            if self.mapper.node_for(s) is not None:
                continue  # already owned (promotion won the race)
            best = self._promotion_candidate(s)
            if best is not None:
                events.append(self.promote(s, best))
                continue
            self._last_reassign[s] = now
        return events + self._assign()

    @property
    def nodes(self) -> list[str]:
        return list(self._nodes)

    # -- adoption / rebalance (singleton failover) --

    def adopt(self, shard: int, node: str, status: ShardStatus) -> None:
        """Record existing ownership without (re)starting ingestion — used by
        a freshly-promoted coordinator taking over a running cluster."""
        if node not in self._nodes:
            self._nodes.append(node)
        self.mapper.apply(ShardEvent(shard, status, node))

    def rebalance(self) -> list[ShardEvent]:
        """Assign any unassigned shards to current members."""
        return self._assign()

    def plan_rebalance(self, overloaded: str | None = None,
                       min_imbalance: int = 2
                       ) -> list[tuple[int, str, str]]:
        """Propose live migrations ``(shard, from, to)`` that even out
        ACTIVE shard counts. With ``overloaded`` given (MemoryWatchdog
        pressure), moves only flow away from that node;
        ``min_imbalance=1`` forces a shed even when counts are level."""
        if len(self._nodes) < 2:
            return []
        active = {n: [s for s in self.mapper.shards_of(n)
                      if self.mapper.statuses[s] == ShardStatus.ACTIVE]
                  for n in self._nodes}
        counts = {n: len(self.mapper.shards_of(n)) for n in self._nodes}
        moves: list[tuple[int, str, str]] = []
        while True:
            src = overloaded if overloaded in counts else \
                max(counts, key=lambda n: counts[n])
            others = [n for n in counts if n != src]
            if not others or not active[src]:
                break
            dst = min(others, key=lambda n: counts[n])
            # an overloaded source sheds at one lower threshold, so a
            # pressured node gives up a shard even when counts are level
            threshold = min_imbalance - 1 if src == overloaded \
                else min_imbalance
            if counts[src] - counts[dst] < threshold:
                break
            shard = active[src].pop()
            moves.append((shard, src, dst))
            counts[src] -= 1
            counts[dst] += 1
        return moves

    # -- live migration (coordinator/migration.py drives these) --

    def begin_handoff(self, shard: int, source: str) -> ShardEvent:
        """Mark a shard in HANDOFF: the source keeps serving queries while
        the destination catches up (the HANDOFF queryability rule)."""
        return self._publish(ShardEvent(shard, ShardStatus.HANDOFF, source))

    def complete_handoff(self, shard: int, dest: str) -> ShardEvent:
        """Atomic flip: ONE sequenced event moves owner+status to the
        destination, so any mapper observer sees either the old or the new
        owner — never a gap."""
        return self._publish(ShardEvent(shard, ShardStatus.ACTIVE, dest))

    def abort_handoff(self, shard: int, source: str) -> ShardEvent:
        """Roll the shard back to ACTIVE on the source (migration abort)."""
        return self._publish(ShardEvent(shard, ShardStatus.ACTIVE, source))

    # -- replica sets (coordinator/replication.py drives these) --

    def replica_update(self, shard: int, node: str, status: ShardStatus,
                       watermark: int = -1) -> ShardEvent | None:
        """Upsert one follower's replica state. Status CHANGES publish a
        sequenced event (remote mirrors track the lifecycle); watermark-only
        progress mutates in place under the event lock — a follower tails
        continuously, and sequencing every applied offset would churn the
        retained event window out from under slow subscribers."""
        cur = self.mapper.replicas[shard].get(node)
        if cur is not None and cur.status == status:
            with self._ev_lock:
                cur.watermark = watermark
            return None
        return self._publish(ShardEvent(shard, status, node, replica=True,
                                        watermark=watermark))

    def drop_replica(self, shard: int, node: str) -> ShardEvent | None:
        """Remove a follower from the shard's replica set (tail stopped)."""
        if node not in self.mapper.replicas[shard]:
            return None
        return self._publish(ShardEvent(shard, ShardStatus.STOPPED, node,
                                        replica=True))

    def promote(self, shard: int, node: str) -> ShardEvent:
        """Failover map flip: ONE sequenced ACTIVE event moves leadership to
        an in-sync follower (which drops out of the replica set), so mapper
        observers see either the old or the new leader — never a DOWN gap."""
        from filodb_tpu.utils.metrics import get_counter
        get_counter("filodb_replica_promotions",
                    {"dataset": self.dataset}).inc()
        log.warning("promoting in-sync follower %s to leader of %s/%d",
                    node, self.dataset, shard)
        return self._publish(ShardEvent(shard, ShardStatus.ACTIVE, node))

    def _promotion_candidate(self, shard: int) -> str | None:
        """Best in-sync follower still in the membership: highest applied
        watermark wins (shortest WAL tail left to replay)."""
        live = [n for n in self.mapper.in_sync_followers(shard)
                if n in self._nodes]
        if not live:
            return None
        return max(live,
                   key=lambda n: self.mapper.replicas[shard][n].watermark)

    # -- assignment --

    def _assign(self) -> list[ShardEvent]:
        out = []
        for shard, node in sorted(self.strategy.assignments(
                self.mapper, self._nodes, self.min_num_nodes).items()):
            if shard in self._deferred:
                continue  # rate-limited: check_deferred() retries it
            out.append(self._publish(ShardEvent(shard, ShardStatus.ASSIGNED,
                                                node)))
        return out

    def shard_active(self, shard: int, node: str) -> ShardEvent:
        return self._publish(ShardEvent(shard, ShardStatus.ACTIVE, node))

    def shard_recovery(self, shard: int, node: str,
                       progress: int) -> ShardEvent:
        return self._publish(ShardEvent(shard, ShardStatus.RECOVERY, node,
                                        progress))

    def shard_error(self, shard: int, node: str) -> ShardEvent:
        ev = self._publish(ShardEvent(shard, ShardStatus.ERROR, None))
        return ev

    def _publish(self, ev: ShardEvent) -> ShardEvent:
        with self._ev_lock:
            self.mapper.apply(ev)
            self._seq += 1
            self._event_log.append((self._seq, ev))
            if len(self._event_log) > self.event_log_cap:
                del self._event_log[: len(self._event_log)
                                    - self.event_log_cap]
        for sub in self.subscribers:
            try:
                sub(ev)
            except Exception:
                from filodb_tpu.utils.metrics import get_counter
                get_counter("filodb_shard_event_errors",
                            {"dataset": self.dataset}).inc()
                log.exception("shard event subscriber failed for %s "
                              "(shard %d -> %s)", self.dataset, ev.shard,
                              ev.status.name)
        return ev

    def events_since(self, since_seq: int, epoch: str | None = None):
        """(events, current_seq, resynced, epoch): ordered events after
        ``since_seq``. The follower resyncs with a full-state snapshot when
        its ack falls behind the retained window, is AHEAD of the current
        sequence, or carries a different feed epoch (a restarted
        coordinator may have re-emitted >= since_seq events, making the ack
        numerically plausible but meaningless) — the reference's resync
        path."""
        with self._ev_lock:
            oldest = self._event_log[0][0] if self._event_log \
                else self._seq + 1
            behind = since_seq + 1 < oldest and self._seq > since_seq
            ahead = since_seq > self._seq
            stale_epoch = epoch is not None and epoch != self.epoch
            if behind or ahead or stale_epoch:
                snapshot = self._state_events()
                return snapshot, self._seq, True, self.epoch
            events = [ev for seq, ev in self._event_log if seq > since_seq]
            return events, self._seq, False, self.epoch

    def _state_events(self) -> list[ShardEvent]:
        """Full-state snapshot as a replayable event list: leader mappings
        first, then replica-set entries (so a resyncing mirror rebuilds
        both tables)."""
        out = [ShardEvent(s, self.mapper.statuses[s], self.mapper.owners[s])
               for s in range(self.num_shards)]
        for s in range(self.num_shards):
            for node, st in sorted(self.mapper.replicas[s].items()):
                out.append(ShardEvent(s, st.status, node, replica=True,
                                      watermark=st.watermark))
        return out

    def subscribe(self, fn) -> None:
        self.subscribers.append(fn)
        # resync: replay current state (reference SubscribeShardUpdates)
        for ev in self._state_events():
            fn(ev)
