"""High-level query facade: PromQL string → results.

Counterpart of the reference's QueryActor + client ask path
(``coordinator/src/main/scala/filodb.coordinator/QueryActor.scala:43,119,171``):
parse → plan → execute against the memstore, returning StepMatrix results.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

from filodb_tpu.coordinator import mesh_cluster as _mesh_cluster  # noqa: F401
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec.plan import ExecContext
from filodb_tpu.query.model import QueryContext, QueryResult
from filodb_tpu.utils.governor import (
    CHEAP,
    EXPENSIVE,
    RULES,
    default_budget,
    governor,
    tenant_of,
)
from filodb_tpu.utils.metrics import Histogram, get_counter
from filodb_tpu.utils.resilience import Deadline
from filodb_tpu.utils.resilience import config as resilience_config

query_latency = Histogram("query_latency_seconds")
partial_results = get_counter("filodb_partial_results")


class _BudgetCtx:
    """Minimal ctx for boundary budget checks on engines without an
    ExecContext (the mesh path): carries budget + partial/warnings."""

    def __init__(self, budget):
        self.budget = budget
        self.partial = False
        self.warnings: list[str] = []


def _admission_cost(plan) -> str:
    """Admission cost class for a logical plan: instant queries (a single
    evaluation step) are CHEAP — they stay admissible when the governor is
    CRITICAL; range scans are EXPENSIVE and shed first."""
    import dataclasses
    stack, seen = [plan], 0
    while stack and seen < 64:
        p = stack.pop()
        seen += 1
        start, end = getattr(p, "start", None), getattr(p, "end", None)
        if isinstance(start, int) and isinstance(end, int) and end > 0:
            return CHEAP if start == end else EXPENSIVE
        if dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name, None)
                if dataclasses.is_dataclass(v) and not isinstance(v, type):
                    stack.append(v)
    return EXPENSIVE


def plan_tenant(plan) -> str:
    """Tenant id (``ws/ns``) from the first selector's ``_ws_``/``_ns_``
    equality filters — keys the governor's per-tenant inflight gate. Empty
    string (untenanted/unmatchable plan shapes) means no tenant gating."""
    import dataclasses

    from filodb_tpu.core.filters import Equals
    stack, seen = [plan], 0
    while stack and seen < 64:
        p = stack.pop()
        seen += 1
        filters = getattr(p, "filters", None)
        if filters:
            labels = {}
            for cf in filters:
                f = getattr(cf, "filter", None)
                if getattr(cf, "column", None) in ("_ws_", "_ns_") \
                        and isinstance(f, Equals):
                    labels[cf.column] = str(f.value)
            if labels:
                return tenant_of(labels)
        if dataclasses.is_dataclass(p):
            for fld in dataclasses.fields(p):
                v = getattr(p, fld.name, None)
                if dataclasses.is_dataclass(v) and not isinstance(v, type):
                    stack.append(v)
    return ""


@dataclass
class QueryService:
    memstore: TimeSeriesMemStore
    dataset: str
    num_shards: int = 1
    spread: int = 0
    time_split_ms: int = 0
    # instant-selector staleness (reference QueryConfig staleSampleAfterMs)
    lookback_ms: int = 300_000
    # "exec" = scatter-gather exec-plan tree (the reference's distribution);
    # "mesh" = lower supported agg(range_fn(sel[w])) by (...) plans onto the
    # (shard × time) device mesh, falling back to exec for everything else;
    # "adaptive" = mesh plus a host lane, cost-routed per batch size
    # (parallel/adaptive.py) — the default serving posture
    engine: str = "exec"
    mesh: object = None  # jax Mesh override for engine="mesh"
    # per-query deadline; every socket/HTTP timeout on the distributed
    # path derives from it (None = resilience-config default)
    query_timeout_s: float | None = None
    # extent result cache (filodb_tpu.query.result_cache): a config dict /
    # ResultCacheConfig / ResultCache / True enables it; None or False
    # disables. Sits in front of exec, mesh, and adaptive engines alike.
    result_cache: object = None
    # callable () -> [(shard, status_str)] for queryable-but-not-ACTIVE
    # shards (recovery/handoff); results touching them carry a warning so
    # callers know the answer may lag the live shard (never wrong, at most
    # behind the in-flight tail). Wired by cluster/standalone.
    shard_status_fn: object = None
    # multi-process mesh runtime (coordinator/mesh_cluster.py): when set,
    # mesh-shaped plans scatter to worker processes first; ``None`` from
    # the runtime (slice unavailable / shape declined / FILODB_MULTIPROC=0)
    # falls through to the single-process engines inside the same
    # admission scope. Wired by standalone when mesh_workers.enabled.
    mesh_cluster: object = None
    planner: SingleClusterPlanner = field(init=False)

    # monotonic construction serial: response-cache keys must survive a
    # service being torn down and a new one allocated at the same address
    # (id() aliases; a serial never does)
    _serial_counter = itertools.count(1)

    def __post_init__(self):
        self.planner = SingleClusterPlanner(
            self.dataset, self.num_shards, self.spread,
            time_split_ms=self.time_split_ms)
        self._plan_cache: dict = {}
        self.serial = next(QueryService._serial_counter)
        from filodb_tpu.query.result_cache import ResultCache
        self.result_cache = ResultCache.from_config(self.result_cache)
        self.mesh_engine = None
        if self.engine == "mesh":
            from filodb_tpu.parallel.mesh_engine import MeshQueryEngine
            self.mesh_engine = MeshQueryEngine(mesh=self.mesh, sidecars=True)
        elif self.engine == "adaptive":
            from filodb_tpu.parallel.adaptive import AdaptiveQueryEngine
            self.mesh_engine = AdaptiveQueryEngine(mesh=self.mesh,
                                                   sidecars=True)

    # ---- promql entry points --------------------------------------------

    def query_range(self, promql: str, start_sec: int, step_sec: int,
                    end_sec: int, qcontext: QueryContext | None = None
                    ) -> QueryResult:
        from filodb_tpu.utils.tracing import span, traced_query
        qcontext = qcontext or QueryContext()
        params = TimeStepParams(start_sec, step_sec, end_sec)
        # traced_query: joins an active trace (debug endpoint, rules tick)
        # or head-samples a new one; on exit feeds stage histograms and
        # tail-captures slow queries into the flight recorder
        with traced_query(qcontext, query=promql, dataset=self.dataset) as rec:
            with span("parse", promql=promql):
                plan = self._parse_cached(promql, params)
            result = self.execute_logical(plan, qcontext)
            rec.observe(result)
        return result

    def query_range_many(self, queries, workers: int = 8,
                         return_errors: bool = False) -> list:
        """Execute many in-flight range queries and return results in order.
        Counterpart of the reference QueryActor's concurrent dispatch on its
        ForkJoin query scheduler (``QueryActor.scala:233-237``; the JMH
        ``QueryInMemoryBenchmark`` drives 100 concurrent queries per op,
        cycling 4 plan shapes).

        Two-phase: (1) dispatch every query's device program asynchronously
        (results stay lazy on device); (2) fetch ALL result buffers in one
        batched ``jax.device_get``. On an accelerator behind a high-latency
        link a per-query fetch costs a full RTT (~90ms measured through the
        axon tunnel); one coalesced transfer amortizes it across the whole
        batch. Each element of ``queries`` is
        ``(promql, start_sec, step_sec, end_sec)``.

        The extent result cache is consulted per query first; cache-answered
        queries skip the mesh dispatch and the batch fetch entirely (their
        matrices are already host-resident).

        With ``return_errors=True`` a failing query yields its exception at
        its own position instead of poisoning the whole batch — one bad
        query costs only itself, not an O(n) sequential re-run."""
        import numpy as np

        t0 = time.perf_counter()
        n = len(queries)
        if n == 1:
            # a single-member batch has nothing to coalesce; take the
            # fully-traced query_range path so head-sampling and slow-query
            # span capture keep working for the HTTP fronts (which funnel
            # every hot query through here, even singles)
            promql, start_sec, step_sec, end_sec = queries[0]
            try:
                return [self.query_range(promql, start_sec, step_sec,
                                         end_sec)]
            except Exception as e:  # noqa: BLE001
                if not return_errors:
                    raise
                return [e]
        plans: list = [None] * n
        outcomes: list = [None] * n  # QueryResult | Exception per query
        for i, q in enumerate(queries):
            promql, start_sec, step_sec, end_sec = q
            params = TimeStepParams(start_sec, step_sec, end_sec)
            try:
                plans[i] = self._parse_cached(promql, params)
            except Exception as e:  # noqa: BLE001
                if not return_errors:
                    raise
                outcomes[i] = e

        if self.result_cache is not None:
            for i, plan in enumerate(plans):
                if plan is None or outcomes[i] is not None:
                    continue
                try:
                    r = self.result_cache.execute(self, plan, QueryContext())
                except Exception as e:  # noqa: BLE001
                    if not return_errors:
                        raise
                    outcomes[i] = e
                    continue
                if r is not None:
                    outcomes[i] = r
        pending = [i for i in range(n)
                   if outcomes[i] is None and plans[i] is not None]

        from filodb_tpu.query.model import QueryStats
        stats_list = {i: QueryStats() for i in pending}
        mesh_results = {i: None for i in pending}
        # The mesh executes against the raw memstore only; a federated
        # planner may route part of a straddling range to colder tiers, so
        # only plans the planner proves memstore-resident may take the
        # mesh shortcut — the rest fall to the exec path (tier routing).
        meshable = [i for i in pending
                    if self._planner_mem_only(plans[i])]
        if meshable and self.mesh_engine is not None \
                and self._mesh_eligible():
            # one device program per shared plan signature (micro-batched
            # step grids); unsupported plans fall through to the exec path.
            # The whole batch takes ONE admission slot: it runs as one
            # device program, and per-item gating would stall the batcher.
            try:
                with governor().admit(cost=EXPENSIVE):
                    mr = self.mesh_engine.execute_many(
                        [plans[i] for i in meshable], self.memstore,
                        self.dataset, [stats_list[i] for i in meshable])
            except Exception as e:  # noqa: BLE001
                from filodb_tpu.parallel.mesh_engine import _M_FALLBACK
                _M_FALLBACK["error"].inc(len(meshable))
                if not return_errors:
                    raise
                mr = [None] * len(meshable)  # per-item exec fallback below
            for j, i in enumerate(meshable):
                mesh_results[i] = mr[j]

        deferred = set()
        for i in pending:
            if mesh_results[i] is not None:
                outcomes[i] = QueryResult(mesh_results[i], stats_list[i],
                                          None)
                deferred.add(i)
            else:
                try:
                    outcomes[i] = self._execute_uncached(
                        plans[i], materialize=False)
                except Exception as e:  # noqa: BLE001
                    if not return_errors:
                        raise
                    outcomes[i] = e
        # Coalesced device→host fetch: stack same-shaped lazy result buffers
        # into one device array per shape and fetch each stack once. A
        # per-query fetch costs a full RTT through the tunnel; one stacked
        # transfer amortizes it across the whole in-flight batch.
        import jax.numpy as jnp
        by_shape: dict[tuple, list[int]] = {}
        for i in pending:
            r = outcomes[i]
            if isinstance(r, Exception):
                continue
            v = r.result.values
            if not isinstance(v, np.ndarray):
                by_shape.setdefault((v.shape, str(v.dtype)), []).append(i)
        from filodb_tpu.query.exec.plan import ExecPlan
        for idxs in by_shape.values():
            try:
                stacked = np.asarray(jnp.stack([outcomes[i].result.values
                                                for i in idxs]))
            except Exception as e:  # noqa: BLE001
                if not return_errors:
                    raise
                for i in idxs:
                    outcomes[i] = e
                continue
            for j, i in enumerate(idxs):
                outcomes[i].result.values = stacked[j]
                deferred.add(i)
        # limits + stats AFTER materialization, so deferred compaction has
        # dropped empty series first (enforcing on the pre-compaction count
        # rejected queries the sequential path accepted) — uniformly for
        # mesh AND exec-path results whose fetch was deferred to this batch
        wall = time.perf_counter() - t0
        for i in sorted(deferred):
            try:
                data = outcomes[i].result.materialize()
                qcontext = QueryContext()
                ExecPlan._enforce_limits(data, qcontext)
            except Exception as e:  # noqa: BLE001
                if not return_errors:
                    raise
                outcomes[i] = e
                continue
            outcomes[i].stats.result_series = data.num_series
            # batched execution: the whole pass's wall time is every
            # member's latency (they completed together)
            outcomes[i].stats.wall_time_s = wall
            if not outcomes[i].query_id:
                outcomes[i].query_id = qcontext.query_id
        # tail capture for the batched path: members of a slow batch land in
        # the flight recorder with stats (batched queries are not span-traced
        # — the whole batch runs as one device program)
        from filodb_tpu.utils.tracing import config as tracing_config
        thr = tracing_config().slow_query_threshold_ms
        if deferred and thr > 0 and wall * 1000.0 > thr:
            import dataclasses as _dc

            from filodb_tpu.utils.tracing import record_slow
            for i in sorted(deferred):
                r = outcomes[i]
                if isinstance(r, QueryResult):
                    record_slow("query", wall * 1000.0,
                                stats=_dc.asdict(r.stats),
                                query=queries[i][0], dataset=self.dataset,
                                batched=True)
        return outcomes

    def _parse_cached(self, promql: str, params: TimeStepParams):
        """PromQL parse memo — the concurrent workload cycles few distinct
        query shapes, and logical plans are immutable."""
        key = (promql, params.start, params.step, params.end,
               self.lookback_ms)
        cached = self._plan_cache.get(key)
        if cached is not None:
            return cached
        plan = parse_query(promql, params, self.lookback_ms)
        if len(self._plan_cache) >= 256:
            self._plan_cache.pop(next(iter(self._plan_cache)))
        self._plan_cache[key] = plan
        return plan

    def query_instant(self, promql: str, time_sec: int,
                      qcontext: QueryContext | None = None) -> QueryResult:
        from filodb_tpu.utils.tracing import traced_query
        qcontext = qcontext or QueryContext()
        params = TimeStepParams(time_sec, 0, time_sec)
        plan = parse_query(promql, params, self.lookback_ms)
        with traced_query(qcontext, query=promql, dataset=self.dataset) as rec:
            result = self.execute_logical(plan, qcontext)
            rec.observe(result)
        return result

    def execute_logical(self, plan: lp.LogicalPlan,
                        qcontext: QueryContext | None = None,
                        materialize: bool = True) -> QueryResult:
        qcontext = qcontext or QueryContext()
        if self.result_cache is not None and materialize:
            # extent result cache in front of every engine; None = plan
            # shape (or deployment) the splitter won't touch — fall through
            cached = self.result_cache.execute(self, plan, qcontext)
            if cached is not None:
                # partial results only come out of _execute_uncached (the
                # cache's surrender path), which already counts them
                return cached
        return self._execute_uncached(plan, qcontext, materialize)

    def _execute_uncached(self, plan: lp.LogicalPlan,
                          qcontext: QueryContext | None = None,
                          materialize: bool = True) -> QueryResult:
        """Engine execution without the extent cache — the cache itself
        evaluates per-extent sub-queries through here."""
        qcontext = qcontext or QueryContext()
        t0 = time.perf_counter()
        if isinstance(plan, (lp.LabelValues, lp.LabelNames,
                             lp.SeriesKeysByFilters)):
            return self._metadata(plan, qcontext)
        # attach the node's default scan budget (governor config) unless the
        # caller brought one; it rides the QueryContext to remote leaves
        pp = qcontext.planner_params
        if pp.budget is None:
            pp.budget = default_budget()
        timeout_s = self.query_timeout_s if self.query_timeout_s is not None \
            else resilience_config().query_timeout_s
        deadline = Deadline.after(timeout_s)
        # admission gate: single choke point for the mesh and exec engines
        # (and the cache's per-extent sub-queries); over-capacity queries
        # wait bounded by the deadline, then shed with QueryRejected (503).
        # Standing-query evaluations (QueryContext.origin == "rules")
        # admit as their own lowest-priority class.
        # tiered planners (longtime/tiered_planner) can force a cost
        # class: any query touching a cold tier is EXPENSIVE no matter
        # its shape — paging object-store segments sheds before CHEAP
        # memstore traffic when the governor is CRITICAL
        if qcontext.origin == "rules":
            cost = RULES
        else:
            hint = getattr(self.planner, "cost_hint", None)
            forced = hint(plan) if hint is not None else None
            cost = forced or _admission_cost(plan)
            if forced is None:
                # learned classing: predicted wall time for this plan's
                # signature class replaces the start==end shape heuristic
                # once warm (cold model returns the static class)
                from filodb_tpu.coordinator import adaptive_planner
                cost = adaptive_planner.admission_class(
                    self.dataset, plan, qcontext, cost)
        t_admit = time.perf_counter()
        with governor().admit(deadline=deadline, cost=cost,
                              tenant=plan_tenant(plan)):
            admission_wait_s = time.perf_counter() - t_admit
            if self.mesh_cluster is not None and self._mesh_eligible() \
                    and self._planner_mem_only(plan):
                # multi-process mesh first: lowered descriptors scatter to
                # the worker processes and the root runs the window-
                # boundary reduce. None = slice unavailable / shape
                # declined / disabled — fall through to the single-process
                # engines below WITHOUT re-admitting (one admission per
                # query, whatever path serves it). A worker-side shed
                # raises QueryRejected out of the scope (PR 1/4: overload
                # propagates, unavailability degrades).
                from filodb_tpu.query.model import QueryStats
                from filodb_tpu.utils.tracing import span
                stats = QueryStats()
                stats.admission_wait_s += admission_wait_s
                with query_latency.time(), span("mesh-proc-execute"):
                    data = self.mesh_cluster.execute_plan(plan, deadline,
                                                          stats)
                if data is not None:
                    return self._finish_device_result(data, stats,
                                                      qcontext, pp, cost,
                                                      t0)
            if self.mesh_engine is not None and self._mesh_eligible() \
                    and self._planner_mem_only(plan) \
                    and self.mesh_engine.supports(plan):
                from filodb_tpu.query.model import QueryStats
                from filodb_tpu.utils.tracing import span
                stats = QueryStats()
                stats.admission_wait_s += admission_wait_s
                with query_latency.time(), span("mesh-execute"):
                    data = self.mesh_engine.execute(self.memstore,
                                                    self.dataset, plan, stats)
                if data is None:
                    # recognized plan the kernels declined at execution
                    # time (e.g. histogram batch under a non-sum agg)
                    from filodb_tpu.parallel.mesh_engine import _M_FALLBACK
                    _M_FALLBACK["declined"].inc()
                if data is not None:  # None = shape the kernels don't cover
                    return self._finish_device_result(data, stats,
                                                      qcontext, pp, cost,
                                                      t0)
            from filodb_tpu.utils.tracing import span
            with span("plan-materialize"):
                exec_plan = self.planner.materialize(plan, qcontext)
            ctx = ExecContext(self.memstore, self.dataset, qcontext,
                              deadline=deadline)
            ctx.stats.admission_wait_s += admission_wait_s
            with query_latency.time(), span("exec-dispatch"):
                result = exec_plan.dispatcher.dispatch(exec_plan, ctx)
                if materialize:
                    # device → host once, at the boundary; query_range_many
                    # defers this and batch-fetches across in-flight queries
                    result.result.materialize()
                    # device-resident results skipped in-tree enforcement
                    # (compaction was deferred); enforce on the real count
                    from filodb_tpu.query.exec.plan import (
                        ExecPlan,
                        apply_result_budget,
                    )
                    ExecPlan._enforce_limits(result.result, qcontext)
                    # ...and the result-bytes budget likewise: in-tree
                    # checks only see host-resident matrices
                    result.result = apply_result_budget(result.result, ctx)
                    result.partial = ctx.partial
                    result.warnings = list(ctx.warnings)
        result.stats.wall_time_s = time.perf_counter() - t0
        result.stats.result_series = result.result.num_series
        from filodb_tpu.coordinator import adaptive_planner
        adaptive_planner.settle_query(
            self.dataset, qcontext, result.stats.wall_time_s, cost)
        if result.partial:
            partial_results.inc()
        return self._attach_recovery_warnings(result)

    def _finish_device_result(self, data, stats, qcontext, pp, cost,
                              t0) -> QueryResult:
        """Finishing tail shared by the device engines (single-process
        mesh and multi-process mesh): materialize first so deferred
        compaction applies, then the same resource guards as the exec
        path (real counts), then settle the adaptive cost model."""
        data.materialize()
        from filodb_tpu.query.exec.plan import (
            ExecPlan,
            apply_result_budget,
        )
        ExecPlan._enforce_limits(data, qcontext)
        # result-bytes budget on the materialized matrix (the mesh has no
        # incremental scan hooks, so the boundary check is where it
        # degrades gracefully)
        shim = _BudgetCtx(pp.budget)
        data = apply_result_budget(data, shim)
        stats.wall_time_s = time.perf_counter() - t0
        stats.result_series = data.num_series
        from filodb_tpu.coordinator import adaptive_planner
        adaptive_planner.settle_query(
            self.dataset, qcontext, stats.wall_time_s, cost)
        return self._attach_recovery_warnings(
            QueryResult(data, stats, qcontext.query_id,
                        partial=shim.partial, warnings=shim.warnings))

    def _recovery_warnings(self) -> list[str]:
        """One warning per queryable-but-catching-up shard (recovery replay,
        live-migration handoff, or a read served from a follower replica
        while the leader is unreachable) — satellite rule: queries during
        migration/failover are correct or *flagged*, never silently
        stale."""
        fn = self.shard_status_fn
        if fn is None:
            return []
        try:
            out = []
            for shard, status in fn():
                if status.startswith("served by"):
                    out.append(f"shard {shard} {status}: results may "
                               f"lag live ingest")
                else:
                    out.append(f"shard {shard} recovering ({status}): "
                               f"results may lag live ingest")
            return out
        except Exception:
            return []

    def _attach_recovery_warnings(self, result: QueryResult) -> QueryResult:
        for w in self._recovery_warnings():
            if w not in result.warnings:
                result.warnings.append(w)
        return result

    def _planner_mem_only(self, plan) -> bool:
        """True when the planner certifies the plan reads only memstore-
        resident data (incl. lookback). Planners without tiering (plain
        SingleClusterPlanner) have no ``mem_only`` and are all-raw by
        construction."""
        f = getattr(self.planner, "mem_only", None)
        return True if f is None else bool(f(plan))

    def _mesh_eligible(self) -> bool:
        """The mesh fans ALL series into one device program, so every shard
        of the dataset must be resident in this process's memstore; a
        coordinator facade over remote members sees partial data and must
        use the scatter-gather path."""
        ok = len(self.memstore.shards_for(self.dataset)) >= self.num_shards
        if not ok and self.mesh_engine is not None:
            from filodb_tpu.parallel.mesh_engine import _M_FALLBACK
            _M_FALLBACK["shards"].inc()
        return ok

    # ---- metadata -------------------------------------------------------

    def _metadata(self, plan, qcontext) -> QueryResult:
        from filodb_tpu.query.model import StepMatrix
        import numpy as np
        if isinstance(plan, lp.LabelValues):
            vals = self.memstore.label_values(self.dataset, plan.label,
                                              list(plan.filters) or None)
            meta = [("__label_value__", v) for v in vals]
        elif isinstance(plan, lp.LabelNames):
            meta = [("__label_name__", v)
                    for v in self.memstore.label_names(self.dataset)]
        else:  # SeriesKeysByFilters
            meta = []
            for shard in self.memstore.shards_for(self.dataset):
                for pid in shard.lookup_partitions(list(plan.filters),
                                                   plan.start, plan.end):
                    pk = shard.index.part_key(pid)
                    if pk is not None:
                        meta.append(("__series__", str(sorted(pk.labels))))
        result = StepMatrix.empty()
        result.meta = meta  # metadata rides alongside
        qr = QueryResult(result, query_id=qcontext.query_id)
        return qr

    def chunk_infos(self, filters, start_ms: int, end_ms: int,
                    include_buffer: bool = False) -> list[dict]:
        """Chunk metadata for matching partitions (reference
        ``SelectChunkInfosExec`` debug query)."""
        out = []
        for shard in self.memstore.shards_for(self.dataset):
            for pid in shard.lookup_partitions(list(filters), start_ms,
                                               end_ms):
                part = shard.partition(pid)
                if part is None:
                    continue
                for c in part.chunks_in_range(start_ms, end_ms,
                                              include_buffer):
                    out.append({
                        "shard": shard.shard_num, "partId": pid,
                        "partKey": str(part.part_key), "chunkId": c.id,
                        "numRows": c.num_rows, "startTime": c.start_time,
                        "endTime": c.end_time, "numBytes": c.nbytes,
                    })
        return out

    def series(self, filters, start_sec: int, end_sec: int) -> list[dict]:
        out = []
        for shard in self.memstore.shards_for(self.dataset):
            for pid in shard.lookup_partitions(list(filters),
                                               start_sec * 1000,
                                               end_sec * 1000):
                pk = shard.index.part_key(pid)
                if pk is not None:
                    out.append(pk.label_map)
        return out


class QueryBatcher:
    """Coalesces concurrent in-flight queries into ``query_range_many``
    batches — the serving-side analog of inference micro-batching, and the
    TPU-native answer to the reference's per-query actor dispatch
    (``QueryActor.scala:233-237``): under load the mesh engine evaluates a
    whole batch as one device program, and results fetch in one coalesced
    transfer.

    Handler threads submit and wait; one dispatcher thread drains whatever
    is queued (no artificial batching delay — an idle server answers a lone
    query at single-query latency)."""

    def __init__(self, svc: QueryService, max_batch: int = 64):
        import queue
        import threading

        self.svc = svc
        self.max_batch = max_batch
        self._q: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="query-batcher")
        self._thread.start()

    def query_range(self, promql: str, start_sec: int, step_sec: int,
                    end_sec: int):
        import threading

        item = {"params": (promql, start_sec, step_sec, end_sec),
                "event": threading.Event(), "result": None, "error": None}
        self._q.put(item)
        item["event"].wait()
        if item["error"] is not None:
            raise item["error"]
        return item["result"]

    def _loop(self):
        import queue

        while True:
            items = [self._q.get()]
            try:
                while len(items) < self.max_batch:
                    items.append(self._q.get_nowait())
            except queue.Empty:
                pass
            try:
                # per-item error capture: one poison query surfaces at its
                # own position without forcing the old O(n) sequential
                # re-run of the whole batch
                results = self.svc.query_range_many(
                    [it["params"] for it in items], return_errors=True)
                for it, r in zip(items, results):
                    if isinstance(r, Exception):
                        it["error"] = r
                    else:
                        it["result"] = r
            except Exception:  # pragma: no cover - defensive
                # a failure that escaped per-item capture (batch machinery
                # itself); isolate by running each alone
                for it in items:
                    try:
                        it["result"] = self.svc.query_range(*it["params"])
                    except Exception as e:  # noqa: BLE001
                        it["error"] = e
            for it in items:
                it["event"].set()
