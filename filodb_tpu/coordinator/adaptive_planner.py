"""Coordinator-side wiring for the trace-driven cost model.

``query/cost_model.py`` owns the estimator; this module binds it to the
coordinator's admission path and lifecycle:

- **Admission classing** — `admission_class` replaces the static
  ``start == end`` shape heuristic (``_admission_cost``) with the learned
  wall-time prediction for the query's plan-signature class: predicted
  sub-threshold queries admit as CHEAP, everything else EXPENSIVE. A
  planner-forced class (tiered planner's cold-tier EXPENSIVE) and the
  RULES class are never overridden — those are isolation decisions, not
  cost estimates. Cold model ⇒ the static class, bit for bit.
- **Retry-After** — `retry_after_provider` registers with the governor so
  shed responses advise a backoff from the live p90 of the saturating
  class instead of the ``retry_after_s`` constant.
- **Lifecycle** — `install` loads persisted estimates from the metastore
  at server start; `persist` saves them at shutdown (and whenever the
  server wants a checkpoint).
"""

from __future__ import annotations

from filodb_tpu.query import cost_model as cm
from filodb_tpu.utils import governor as gov

# Predicted wall time below which a query classes CHEAP (overridable via
# the "cost_model" config block). Matches the intent of the static
# heuristic: instant-style evaluations are the ones that stay admissible
# under a CRITICAL governor.
_DEFAULTS = {"cheap_threshold_s": 0.05}
_cheap_threshold_s = _DEFAULTS["cheap_threshold_s"]


def configure(dataset: str, cfg: dict | None) -> cm.CostModel:
    """Apply the ``cost_model`` config block to the dataset's model."""
    global _cheap_threshold_s
    model = cm.model_for(dataset)
    cfg = cfg or {}
    model.configure(
        min_samples=cfg.get("min_samples"),
        max_signatures=cfg.get("max_signatures"),
        reservoir=cfg.get("reservoir"),
        ring_capacity=cfg.get("ring_capacity"),
    )
    thr = cfg.get("cheap_threshold_s")
    if thr is not None:
        _cheap_threshold_s = float(thr)
    return model


def plan_signature_class(plan) -> str:
    """Signature class for a logical plan: the result cache's canonical
    retimed signature (extent-independent), hashed to a stable key."""
    from filodb_tpu.query.result_cache import plan_signature

    return cm.signature_key(plan_signature(plan))


def admission_class(dataset: str, plan, qcontext, static_cost: str) -> str:
    """CHEAP/EXPENSIVE from predicted wall time; the decision defers onto
    ``qcontext`` and settles with the query's actual wall time so the
    prediction keeps calibrating. Only the shape-heuristic class is ever
    replaced — RULES and planner-forced classes pass through untouched."""
    if static_cost not in (gov.CHEAP, gov.EXPENSIVE):
        return static_cost
    model = cm.model_for(dataset)
    d = model.classify(
        "admit",
        plan_signature_class(plan),
        _cheap_threshold_s,
        below_arm=gov.CHEAP,
        above_arm=gov.EXPENSIVE,
        static_arm=static_cost,
    )
    model.defer(qcontext, d)
    return d.arm


def settle_query(dataset: str, qcontext, wall_s: float,
                 cost_class: str | None = None) -> None:
    """Settle everything deferred onto the query context (admission
    classing, pushdown decisions) and feed the per-class latency
    reservoir that Retry-After reads."""
    cm.CostModel.settle_deferred(qcontext, wall_s)
    if cost_class:
        cm.model_for(dataset).observe(
            "admit", f"class:{cost_class}", "wall", wall_s)


def retry_after_provider(reason: str):
    """Advisory Retry-After for a shed: the live p90 wall time of the
    class saturating the admission gate — how long until a slot
    plausibly frees. None (cold model everywhere) keeps the static
    constant."""
    cls = gov.RULES if reason == "rules" else gov.EXPENSIVE
    best = None
    for model in cm.models().values():
        p = model.percentile("admit", f"class:{cls}", "wall", 0.9)
        if p is None and cls != gov.CHEAP:
            p = model.percentile("admit", f"class:{gov.CHEAP}", "wall", 0.9)
        if p is not None and (best is None or p > best):
            best = p
    return best


def install(dataset: str, meta_store=None, cfg: dict | None = None) -> cm.CostModel:
    """Server-start hook: configure + load persisted estimates + register
    the live Retry-After source."""
    model = configure(dataset, cfg)
    if meta_store is not None:
        model.load(meta_store)
    gov.set_retry_after_provider(retry_after_provider)
    return model


def persist(dataset: str, meta_store) -> None:
    """Checkpoint learned estimates through the metastore."""
    if meta_store is None:
        return
    cm.model_for(dataset).save(meta_store)
