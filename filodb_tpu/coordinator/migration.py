"""Live shard migration: crash-safe handoff of a shard between nodes.

The reference keeps shards serving through node churn with its
``ShardManager.scala:28`` assignment/recovery protocol, but loses the shard's
warm state on every move — a reassignment is a cold restart on the new
owner. Here a migration is a first-class, resumable state machine built on
the PR 5 durable tier and the memstore's per-group recovery watermarks
(``core/memstore/shard.py``):

    PLANNED → SYNCING → CATCHUP → FLIPPING → DONE        (or ABORTED)

- **PLANNED**: the migration manifest (dataset, shard, source, dest, phase)
  is persisted NEXT TO the shard's data in the column store
  (``migration.json`` under the shard prefix on the object-store tier), so
  either side can crash and a restarted coordinator resumes — or aborts —
  from durable state.
- **SYNCING**: the source flushes every group (sealed segments ride the
  existing ``ObjectStoreColumnStore`` write-behind path), drains the upload
  queue (the durability ack), and snapshots the index. Checkpoints stay
  ordered BEHIND the data they cover, so a kill mid-upload never makes WAL
  replay skip a lost flush.
- **CATCHUP**: the destination cold-recovers from segments + index snapshot
  and replays the ingest tail from its per-group watermarks, tailing the
  same shard log as the source. The shard map shows ``HANDOFF``: the source
  still owns and serves queries (the HANDOFF queryability rule).
- **FLIPPING**: once the destination's replay lag is ≤ the threshold, ONE
  sequenced shard event flips owner+status to the destination — any mapper
  observer sees the old owner or the new one, never a gap. The source
  lingers briefly for in-flight queries, then tears down.

Every transition has a named :class:`FaultInjector` kill-point (see
``KILL_POINTS``); chaos tests kill at each and prove zero acked-data loss
and zero wrong results after resume. Progress is exported as
``filodb_shard_migration_*`` metrics.
"""

from __future__ import annotations

import json
import logging
import time

from filodb_tpu.coordinator.shardmapper import ShardStatus
from filodb_tpu.utils import racecheck
from filodb_tpu.utils.metrics import Counter, Gauge, Histogram
from filodb_tpu.utils.resilience import FaultInjector

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# phases

PLANNED, SYNCING, CATCHUP, FLIPPING, DONE, ABORTED = (
    "planned", "syncing", "catchup", "flipping", "done", "aborted")
PHASES = (PLANNED, SYNCING, CATCHUP, FLIPPING, DONE, ABORTED)
_PHASE_VALUE = {p: i for i, p in enumerate(PHASES)}

# named kill-points, one per state transition; chaos tests arm errors here
# (``FaultInjector.arm(site, RuntimeError)``) to simulate a process kill at
# that exact point, then resume from the persisted manifest
KILL_POINTS = (
    "migration.plan",                    # manifest persisted, nothing moved
    "migration.sync.upload",             # during segment upload (staged,
                                         # write-behind not yet drained)
    "migration.sync.checkpoint.before",  # uploads durable, index snapshot
                                         # (the recovery barrier) not yet
    "migration.sync.checkpoint.after",   # snapshot durable, phase record not
    "migration.catchup",                 # destination replaying the tail
    "migration.flip.before",             # mid-flip: HANDOFF still on source
    "migration.flip.after",              # flipped: source not yet torn down
)

# ---------------------------------------------------------------------------
# metrics — pre-created at import so the scrape families render before any
# migration runs

_started = Counter("filodb_shard_migrations_started")
_completed = Counter("filodb_shard_migrations_completed")
_aborted = Counter("filodb_shard_migrations_aborted")
_resumed = Counter("filodb_shard_migrations_resumed")
_active_gauge = Gauge("filodb_shard_migration_active")
_phase_gauge = Gauge("filodb_shard_migration_phase")
_lag_gauge = Gauge("filodb_shard_migration_lag")
_seconds = Histogram("filodb_shard_migration_seconds")


class MigrationError(RuntimeError):
    """Migration could not make progress (catch-up timeout, lost node)."""


# ---------------------------------------------------------------------------
# manifest


class MigrationManifest:
    """Durable migration record; JSON next to the shard's data. Registered
    on the wire so control-plane callers (``migration_status``) receive it
    typed."""

    __wire_fields__ = ("dataset", "shard", "source", "dest", "phase",
                       "lag_threshold", "started_ms", "updated_ms")

    def __init__(self, dataset: str = "", shard: int = 0, source: str = "",
                 dest: str = "", phase: str = PLANNED,
                 lag_threshold: int = 0, started_ms: int = 0,
                 updated_ms: int = 0):
        self.dataset = dataset
        self.shard = shard
        self.source = source
        self.dest = dest
        self.phase = phase
        self.lag_threshold = lag_threshold
        self.started_ms = started_ms
        self.updated_ms = updated_ms
        # phase transitions are written by the migration driver and read
        # by control-plane status calls on other threads
        racecheck.register(
            self, f"MigrationManifest[{dataset}/{shard}]")

    def to_bytes(self) -> bytes:
        return json.dumps({k: getattr(self, k)
                           for k in self.__wire_fields__}).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "MigrationManifest":
        doc = json.loads(raw.decode())
        return cls(**{k: doc[k] for k in cls.__wire_fields__ if k in doc})

    def __eq__(self, other):
        return isinstance(other, MigrationManifest) and all(
            getattr(self, k) == getattr(other, k)
            for k in self.__wire_fields__)

    def __repr__(self):
        return (f"MigrationManifest({self.dataset}/{self.shard} "
                f"{self.source}->{self.dest} {self.phase})")


# ---------------------------------------------------------------------------
# the state machine


class ShardMigration:
    """One shard's move from ``source`` to ``dest``, driven to completion by
    :meth:`run` (or :meth:`resume` after a crash, or :meth:`abort`).

    ``cluster`` is duck-typed: it provides ``shard_managers``, ``nodes``,
    ``configs`` and ``logs`` (``FilodbCluster`` in-process; the standalone
    coordinator's cluster over control RPC via ``RemoteNodeHandle``).
    ``store`` is the shared :class:`ColumnStore` holding the shard's durable
    data — the manifest lives beside it.
    """

    def __init__(self, cluster, store, dataset: str, shard: int,
                 source: str, dest: str, lag_threshold: int = 0,
                 catchup_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.01,
                 source_linger_s: float = 0.05):
        if source == dest:
            raise ValueError("migration source and destination are the "
                             "same node")
        self.cluster = cluster
        self.store = store
        self.dataset = dataset
        self.shard = shard
        self.source = source
        self.dest = dest
        self.lag_threshold = lag_threshold
        self.catchup_timeout_s = catchup_timeout_s
        self.poll_interval_s = poll_interval_s
        self.source_linger_s = source_linger_s
        self.phase = PLANNED
        self.started_ms = int(time.time() * 1000)
        self.lag = -1
        racecheck.register(self, f"ShardMigration[{dataset}/{shard}]")

    # -- plumbing ---------------------------------------------------------

    @property
    def sm(self):
        return self.cluster.shard_managers[self.dataset]

    def _node(self, name: str):
        node = self.cluster.nodes.get(name)
        if node is None or not getattr(node, "alive", True):
            raise MigrationError(f"node {name} unavailable for migration "
                                 f"of {self.dataset}/{self.shard}")
        return node

    def _ctx(self) -> dict:
        return {"dataset": self.dataset, "shard": self.shard,
                "source": self.source, "dest": self.dest,
                "phase": self.phase}

    def manifest(self) -> MigrationManifest:
        return MigrationManifest(self.dataset, self.shard, self.source,
                                 self.dest, self.phase, self.lag_threshold,
                                 self.started_ms, int(time.time() * 1000))

    def _persist(self, phase: str) -> None:
        """Durably record the phase BEFORE doing its work: a crash inside
        the phase resumes at (and re-runs) it — every phase's work is
        idempotent (chunk writes dedup by id, checkpoints are monotonic,
        the flip event is a plain re-publish)."""
        self.phase = phase
        _phase_gauge.set(_PHASE_VALUE[phase])
        self.store.write_migration_manifest(self.dataset, self.shard,
                                            self.manifest().to_bytes())

    # -- lifecycle --------------------------------------------------------

    def run(self) -> "ShardMigration":
        """Drive the migration to DONE. Raises on an injected kill or a
        lost node, leaving the durable manifest behind for
        :meth:`resume`/:meth:`abort`."""
        t0 = time.monotonic()
        _started.inc()
        _active_gauge.set(_active_gauge.value + 1)
        try:
            from filodb_tpu.utils.tracing import traced_operation
            if self.phase == PLANNED:
                self._persist(PLANNED)
                FaultInjector.fire("migration.plan", **self._ctx())
                self._persist(SYNCING)
            if self.phase == SYNCING:
                with traced_operation("migration", phase="sync",
                                      shard=self.shard, dataset=self.dataset):
                    self._sync()
                self._persist(CATCHUP)
            if self.phase == CATCHUP:
                with traced_operation("migration", phase="catchup",
                                      shard=self.shard, dataset=self.dataset):
                    self._catchup()
                self._persist(FLIPPING)
            if self.phase == FLIPPING:
                with traced_operation("migration", phase="flip",
                                      shard=self.shard, dataset=self.dataset):
                    self._flip()
            _completed.inc()
            _seconds.observe(time.monotonic() - t0)
            log.info("migration %s/%d %s -> %s complete", self.dataset,
                     self.shard, self.source, self.dest)
            return self
        finally:
            _active_gauge.set(max(0.0, _active_gauge.value - 1))

    def _sync(self) -> None:
        """Source: flush + upload sealed segments, snapshot the index."""
        # the HANDOFF queryability rule: the source keeps owning and
        # serving the shard for the whole sync + catch-up window
        self.sm.begin_handoff(self.shard, self.source)
        src = self._node(self.source)
        src.prepare_handoff(self.dataset, self.shard)

    def _catchup(self) -> None:
        """Destination: cold-recover from segments + index snapshot, then
        replay the ingest tail from the per-group watermarks until its lag
        behind the (still-ingesting) source is ≤ the threshold."""
        # resume path: a restarted coordinator adopted the shard as plain
        # ACTIVE-on-source; restore the HANDOFF marker (idempotent)
        if self.sm.mapper.statuses[self.shard] != ShardStatus.HANDOFF:
            self.sm.begin_handoff(self.shard, self.source)
        dest = self._node(self.dest)
        # no on_status: recovery progress must NOT reach the shard manager
        # — the map stays HANDOFF-on-source until the atomic flip
        dest.start_shard(self.dataset, self.shard,
                         self.cluster.configs[self.dataset],
                         self.cluster.logs[(self.dataset, self.shard)],
                         on_status=None)
        deadline = time.monotonic() + self.catchup_timeout_s
        while True:
            FaultInjector.fire("migration.catchup", **self._ctx())
            src_off = self._node(self.source).shard_offset(self.dataset,
                                                           self.shard)
            dst_off = dest.shard_offset(self.dataset, self.shard)
            self.lag = max(0, src_off - dst_off)
            _lag_gauge.set(self.lag)
            if dst_off >= src_off - self.lag_threshold:
                return
            if time.monotonic() > deadline:
                raise MigrationError(
                    f"catch-up timed out for {self.dataset}/{self.shard}: "
                    f"dest offset {dst_off} behind source {src_off} "
                    f"(threshold {self.lag_threshold})")
            time.sleep(self.poll_interval_s)

    def _flip(self) -> None:
        """Atomic shard-map flip, then tear down the source."""
        FaultInjector.fire("migration.flip.before", **self._ctx())
        self.sm.complete_handoff(self.shard, self.dest)
        FaultInjector.fire("migration.flip.after", **self._ctx())
        # in-flight queries may have resolved routing before the flip;
        # linger so they drain against a live source (a late straggler
        # hitting a torn-down shard degrades to a flagged-partial result,
        # never a wrong one)
        if self.source_linger_s:
            time.sleep(self.source_linger_s)
        try:
            self._node(self.source).stop_shard(self.dataset, self.shard)
        except MigrationError:
            pass  # source died after the flip: nothing left to tear down
        self._persist(DONE)
        self.store.delete_migration_manifest(self.dataset, self.shard)

    def abort(self) -> "ShardMigration":
        """Roll back cleanly: the source resumes sole ownership, the
        destination's partial recovery is torn down, the manifest is
        cleared. Safe from any pre-DONE phase."""
        if self.phase == DONE:
            return self
        try:
            dest = self.cluster.nodes.get(self.dest)
            if dest is not None and getattr(dest, "alive", True):
                dest.stop_shard(self.dataset, self.shard)
        except Exception:
            log.exception("migration abort: destination teardown failed")
        if self.phase in (SYNCING, CATCHUP, FLIPPING):
            self.sm.abort_handoff(self.shard, self.source)
        self.phase = ABORTED
        _phase_gauge.set(_PHASE_VALUE[ABORTED])
        _aborted.inc()
        self.store.delete_migration_manifest(self.dataset, self.shard)
        log.warning("migration %s/%d %s -> %s aborted", self.dataset,
                    self.shard, self.source, self.dest)
        return self

    # -- crash recovery ---------------------------------------------------

    @classmethod
    def resume(cls, cluster, store, dataset: str, shard: int,
               **kw) -> "ShardMigration | None":
        """Reload the durable manifest and continue from the recorded
        phase. Returns None when no migration is in flight. The resumed
        run re-executes the interrupted phase from its start — all phase
        work is idempotent."""
        raw = store.read_migration_manifest(dataset, shard)
        if raw is None:
            return None
        m = MigrationManifest.from_bytes(raw)
        if m.phase in (DONE, ABORTED):
            store.delete_migration_manifest(dataset, shard)
            return None
        mig = cls(cluster, store, dataset, shard, m.source, m.dest,
                  lag_threshold=m.lag_threshold, **kw)
        mig.started_ms = m.started_ms
        mig.phase = SYNCING if m.phase == PLANNED else m.phase
        _resumed.inc()
        log.info("resuming migration %s/%d %s -> %s at phase %s", dataset,
                 shard, m.source, m.dest, mig.phase)
        return mig.run()

    def snapshot(self) -> dict:
        return {"dataset": self.dataset, "shard": self.shard,
                "source": self.source, "dest": self.dest,
                "phase": self.phase, "lag": self.lag}
