"""ShardMapper: shard → node routing table with shard statuses.

Counterpart of reference ``coordinator/src/main/scala/filodb.coordinator/
ShardMapper.scala:26-49`` and ``ShardStatus.scala:1-94``: tracks, per shard,
the owning node and its lifecycle status; computes ingestion routing and
query fan-out sets (hash + spread semantics live in ``core.partkey``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from filodb_tpu.core.partkey import ingestion_shard, shards_for_shard_key
from filodb_tpu.utils import racecheck


class ShardStatus(enum.Enum):
    UNASSIGNED = "unassigned"
    ASSIGNED = "assigned"
    ACTIVE = "active"
    RECOVERY = "recovery"
    # live migration in flight (coordinator/migration.py): the SOURCE node
    # still owns and serves the shard while the destination catches up;
    # the owner only changes at the atomic ACTIVE flip event
    HANDOFF = "handoff"
    ERROR = "error"
    STOPPED = "stopped"
    DOWN = "down"
    # follower replica lifecycle (coordinator/replication.py): a follower
    # tails the shard's durable segments + WAL into a warm read-only image.
    # None of these make the LEADER mapping queryable — replica status lives
    # in a side table keyed (shard, node), never in the owner slot.
    FOLLOWING = "following"
    IN_SYNC = "in_sync"
    LAGGING = "lagging"

    @property
    def queryable(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY,
                        ShardStatus.HANDOFF)

    @property
    def is_replica(self) -> bool:
        return self in (ShardStatus.FOLLOWING, ShardStatus.IN_SYNC,
                        ShardStatus.LAGGING)


@dataclass
class ShardEvent:
    """Reference ``ShardEvent`` family (IngestionStarted, ShardDown, ...)."""

    shard: int
    status: ShardStatus
    node: str | None = None
    progress: int = 0  # recovery progress percent
    # replica events target the shard's FOLLOWER set, not the leader slot:
    # status FOLLOWING/IN_SYNC/LAGGING upserts the (shard, node) replica
    # entry, UNASSIGNED/DOWN/STOPPED removes it
    replica: bool = False
    watermark: int = -1  # follower's applied log offset


@dataclass
class ReplicaState:
    """One follower's view of a shard: lifecycle status + the log offset it
    has applied (the in-sync watermark compared against the leader's
    covered offset)."""

    status: ShardStatus
    watermark: int = -1


@dataclass
class ShardMapper:
    num_shards: int
    statuses: list[ShardStatus] = field(default_factory=list)
    owners: list[str | None] = field(default_factory=list)
    # per-shard follower replica sets: node -> ReplicaState. Maintained
    # beside the leader slot so replica churn never perturbs routing.
    replicas: list[dict[str, ReplicaState]] = field(default_factory=list)

    def __post_init__(self):
        assert self.num_shards & (self.num_shards - 1) == 0, \
            "num_shards must be a power of 2"
        if not self.statuses:
            self.statuses = [ShardStatus.UNASSIGNED] * self.num_shards
            self.owners = [None] * self.num_shards
        if not self.replicas:
            self.replicas = [{} for _ in range(self.num_shards)]
        # routing table read by every query/ingest thread, written by
        # membership and migration events
        racecheck.register(self, "ShardMapper")

    def apply(self, ev: ShardEvent) -> None:
        if ev.replica:
            # follower-set mutation only: the leader mapping is untouched
            if ev.status in (ShardStatus.UNASSIGNED, ShardStatus.DOWN,
                             ShardStatus.STOPPED):
                if ev.node is not None:
                    self.replicas[ev.shard].pop(ev.node, None)
            elif ev.node is not None:
                self.replicas[ev.shard][ev.node] = ReplicaState(
                    ev.status, ev.watermark)
            return
        self.statuses[ev.shard] = ev.status
        if ev.node is not None or ev.status in (ShardStatus.UNASSIGNED,
                                                ShardStatus.DOWN):
            self.owners[ev.shard] = ev.node
        if ev.node is not None:
            # a node taking leadership (promotion / handoff flip) leaves
            # the follower set — it is no longer a replica of itself
            self.replicas[ev.shard].pop(ev.node, None)

    def node_for(self, shard: int) -> str | None:
        return self.owners[shard]

    def shards_of(self, node: str) -> list[int]:
        return [s for s, o in enumerate(self.owners) if o == node]

    def active_shards(self) -> list[int]:
        return [s for s, st in enumerate(self.statuses) if st.queryable]

    def unassigned_shards(self) -> list[int]:
        return [s for s, o in enumerate(self.owners) if o is None]

    def ingestion_shard(self, shard_key_h: int, part_h: int,
                        spread: int) -> int:
        return ingestion_shard(shard_key_h, part_h, self.num_shards, spread)

    def query_shards(self, shard_key_h: int, spread: int) -> list[int]:
        return shards_for_shard_key(shard_key_h, self.num_shards, spread)

    def all_queryable(self, shards: list[int]) -> bool:
        return all(self.statuses[s].queryable for s in shards)

    # -- replica sets --

    def replicas_of(self, shard: int) -> dict[str, ReplicaState]:
        return dict(self.replicas[shard])

    def in_sync_followers(self, shard: int) -> list[str]:
        """Followers whose tail has caught up within the in-sync lag bound —
        the promotion candidates and read-serving alternates."""
        return [n for n, st in self.replicas[shard].items()
                if st.status == ShardStatus.IN_SYNC]

    def follower_shards(self, node: str) -> list[int]:
        """Shards for which ``node`` holds a follower replica."""
        return [s for s in range(self.num_shards)
                if node in self.replicas[s]]

    def snapshot(self) -> list[dict]:
        out = []
        for s in range(self.num_shards):
            entry = {"shard": s, "status": self.statuses[s].value,
                     "node": self.owners[s]}
            if self.replicas[s]:
                entry["replicas"] = [
                    {"node": n, "status": st.status.value,
                     "watermark": st.watermark}
                    for n, st in sorted(self.replicas[s].items())]
            out.append(entry)
        return out
