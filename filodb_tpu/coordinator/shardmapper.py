"""ShardMapper: shard → node routing table with shard statuses.

Counterpart of reference ``coordinator/src/main/scala/filodb.coordinator/
ShardMapper.scala:26-49`` and ``ShardStatus.scala:1-94``: tracks, per shard,
the owning node and its lifecycle status; computes ingestion routing and
query fan-out sets (hash + spread semantics live in ``core.partkey``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from filodb_tpu.core.partkey import ingestion_shard, shards_for_shard_key
from filodb_tpu.utils import racecheck


class ShardStatus(enum.Enum):
    UNASSIGNED = "unassigned"
    ASSIGNED = "assigned"
    ACTIVE = "active"
    RECOVERY = "recovery"
    # live migration in flight (coordinator/migration.py): the SOURCE node
    # still owns and serves the shard while the destination catches up;
    # the owner only changes at the atomic ACTIVE flip event
    HANDOFF = "handoff"
    ERROR = "error"
    STOPPED = "stopped"
    DOWN = "down"

    @property
    def queryable(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY,
                        ShardStatus.HANDOFF)


@dataclass
class ShardEvent:
    """Reference ``ShardEvent`` family (IngestionStarted, ShardDown, ...)."""

    shard: int
    status: ShardStatus
    node: str | None = None
    progress: int = 0  # recovery progress percent


@dataclass
class ShardMapper:
    num_shards: int
    statuses: list[ShardStatus] = field(default_factory=list)
    owners: list[str | None] = field(default_factory=list)

    def __post_init__(self):
        assert self.num_shards & (self.num_shards - 1) == 0, \
            "num_shards must be a power of 2"
        if not self.statuses:
            self.statuses = [ShardStatus.UNASSIGNED] * self.num_shards
            self.owners = [None] * self.num_shards
        # routing table read by every query/ingest thread, written by
        # membership and migration events
        racecheck.register(self, "ShardMapper")

    def apply(self, ev: ShardEvent) -> None:
        self.statuses[ev.shard] = ev.status
        if ev.node is not None or ev.status in (ShardStatus.UNASSIGNED,
                                                ShardStatus.DOWN):
            self.owners[ev.shard] = ev.node

    def node_for(self, shard: int) -> str | None:
        return self.owners[shard]

    def shards_of(self, node: str) -> list[int]:
        return [s for s, o in enumerate(self.owners) if o == node]

    def active_shards(self) -> list[int]:
        return [s for s, st in enumerate(self.statuses) if st.queryable]

    def unassigned_shards(self) -> list[int]:
        return [s for s, o in enumerate(self.owners) if o is None]

    def ingestion_shard(self, shard_key_h: int, part_h: int,
                        spread: int) -> int:
        return ingestion_shard(shard_key_h, part_h, self.num_shards, spread)

    def query_shards(self, shard_key_h: int, spread: int) -> list[int]:
        return shards_for_shard_key(shard_key_h, self.num_shards, spread)

    def all_queryable(self, shards: list[int]) -> bool:
        return all(self.statuses[s].queryable for s in shards)

    def snapshot(self) -> list[dict]:
        return [{"shard": s, "status": self.statuses[s].value,
                 "node": self.owners[s]} for s in range(self.num_shards)]
