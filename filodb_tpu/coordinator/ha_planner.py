"""HA and federation planners.

Counterparts of reference ``coordinator/.../queryplanner/``:

- ``HighAvailabilityPlanner`` + ``FailureProvider``
  (``HighAvailabilityPlanner.scala``, ``FailureRoutingStrategy.scala``):
  route around local-cluster failure time ranges by sending those sub-ranges
  to a replica cluster as PromQL over HTTP, stitching results.
- ``MultiPartitionPlanner`` (``MultiPartitionPlanner.scala``): federate
  distinct FiloDB "partitions" (clusters) — a locator maps shard-key values
  to the owning partition; non-local partitions are queried remotely.
- ``SinglePartitionPlanner``: select a planner per query by metric/shard-key.
- ``ShardKeyRegexPlanner`` (``ShardKeyRegexPlanner.scala``): fan out regex
  shard-key filters into concrete shard keys, pushing aggregations down and
  reducing across the fan-out.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex
from filodb_tpu.coordinator.longtime_planner import _plan_times
from filodb_tpu.coordinator.planner import QueryPlanner, _retime
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec.plan import (
    DistConcatExec,
    ExecPlan,
    ReduceAggregateExec,
    StitchRvsExec,
)
from filodb_tpu.query.exec.remote_exec import PromQlRemoteExec
from filodb_tpu.query.logical_parser import to_promql
from filodb_tpu.query.model import QueryContext


@dataclass(frozen=True)
class TimeRange:
    start: int
    end: int


class FailureProvider:
    """Supplies known failure time ranges of a cluster (reference
    ``FailureProvider``)."""

    def failures(self, dataset: str, time_range: TimeRange
                 ) -> list[TimeRange]:
        raise NotImplementedError


@dataclass
class StaticFailureProvider(FailureProvider):
    ranges: list[TimeRange] = field(default_factory=list)

    def failures(self, dataset, time_range):
        return [r for r in self.ranges
                if r.end >= time_range.start and r.start <= time_range.end]


@dataclass
class HighAvailabilityPlanner(QueryPlanner):
    dataset: str
    local_planner: QueryPlanner
    failure_provider: FailureProvider
    remote_endpoint: str  # replica cluster base URL (…/promql/{dataset})

    def materialize(self, plan, qcontext=None) -> ExecPlan:
        qcontext = qcontext or QueryContext()
        times = _plan_times(plan)
        if times is None:
            return self.local_planner.materialize(plan, qcontext)
        start, step, end, lookback = times
        fails = self.failure_provider.failures(
            self.dataset, TimeRange(start - lookback, end))
        if not fails:
            return self.local_planner.materialize(plan, qcontext)
        step = max(step, 1)
        # classify each step: a step is poisoned when its window overlaps a
        # failure; contiguous runs become local or remote sub-plans
        parts: list[ExecPlan] = []
        run_start = start
        run_remote = self._poisoned(start, lookback, fails)
        t = start + step
        while t <= end + step:
            poisoned = (self._poisoned(t, lookback, fails)
                        if t <= end else not run_remote)
            if t > end or poisoned != run_remote:
                sub = _retime(plan, run_start, step, t - step)
                parts.append(self._remote(sub, run_start, step, t - step)
                             if run_remote
                             else self.local_planner.materialize(sub,
                                                                 qcontext))
                run_start = t
                run_remote = poisoned
            t += step
        if len(parts) == 1:
            return parts[0]
        return StitchRvsExec(children_plans=parts)

    @staticmethod
    def _poisoned(step_ms: int, lookback: int, fails) -> bool:
        return any(f.start <= step_ms and step_ms - lookback <= f.end
                   for f in fails)

    def _remote(self, plan, start, step, end) -> PromQlRemoteExec:
        return PromQlRemoteExec(endpoint=self.remote_endpoint,
                                promql=to_promql(plan), start=start,
                                step=step, end=end)


class PartitionLocationProvider:
    """Maps shard-key label values to the owning cluster partition
    (reference ``PartitionLocationProvider``)."""

    def partition_of(self, shard_key: dict[str, str]) -> str:
        raise NotImplementedError

    def endpoint_of(self, partition: str) -> str:
        raise NotImplementedError


@dataclass
class MultiPartitionPlanner(QueryPlanner):
    locator: PartitionLocationProvider
    local_partition: str
    local_planner: QueryPlanner
    shard_key_labels: tuple[str, ...] = ("_ws_", "_ns_")

    def materialize(self, plan, qcontext=None) -> ExecPlan:
        qcontext = qcontext or QueryContext()
        keys = self._shard_keys(plan)
        partitions = {self.locator.partition_of(k) for k in keys} or {
            self.local_partition}
        if partitions == {self.local_partition}:
            return self.local_planner.materialize(plan, qcontext)
        if len(partitions) == 1:
            part = next(iter(partitions))
            times = _plan_times(plan)
            start, step, end, _ = times
            return PromQlRemoteExec(
                endpoint=self.locator.endpoint_of(part),
                promql=to_promql(plan), start=start, step=max(step, 1),
                end=end)
        # spans partitions: evaluate leaves per partition and concat
        # (aggregates above are handled by the exec tree's reduce node)
        raise ValueError(
            "queries spanning multiple partitions must target a single "
            "shard key per selector (reference MultiPartitionPlanner "
            "limitation)")

    def _shard_keys(self, plan) -> list[dict[str, str]]:
        out = []
        for raw in lp.leaf_raw_series(plan):
            eq = {f.column: f.filter.value for f in raw.filters
                  if isinstance(f.filter, Equals)}
            if all(lbl in eq for lbl in self.shard_key_labels):
                out.append({k: eq[k] for k in self.shard_key_labels})
        return out


@dataclass
class SinglePartitionPlanner(QueryPlanner):
    """Pick a planner by a selector function over the plan (reference
    ``SinglePartitionPlanner`` routes per metric)."""

    planners: dict[str, QueryPlanner] = field(default_factory=dict)
    select: "callable" = None  # plan -> planner name
    default: str = ""

    def materialize(self, plan, qcontext=None) -> ExecPlan:
        name = self.select(plan) if self.select else self.default
        return self.planners.get(name, self.planners[self.default]) \
            .materialize(plan, qcontext or QueryContext())


@dataclass
class ShardKeyRegexPlanner(QueryPlanner):
    """Expand regex/multi-valued shard-key filters into concrete shard keys
    and fan out (reference ``ShardKeyRegexPlanner``): aggregations reduce
    across the fan-out; plain selectors concat."""

    inner_planner: QueryPlanner
    shard_key_matcher: "callable"  # filters -> list[dict[label, value]]
    shard_key_labels: tuple[str, ...] = ("_ws_", "_ns_")

    def materialize(self, plan, qcontext=None) -> ExecPlan:
        qcontext = qcontext or QueryContext()
        raws = lp.leaf_raw_series(plan)
        needs_fanout = any(
            isinstance(f.filter, EqualsRegex) and f.column in
            self.shard_key_labels for raw in raws for f in raw.filters)
        if not needs_fanout:
            return self.inner_planner.materialize(plan, qcontext)
        combos = self.shard_key_matcher(raws[0].filters)

        def fan(p):
            return [self.inner_planner.materialize(
                _replace_shard_keys(p, combo, self.shard_key_labels),
                qcontext) for combo in combos]

        if isinstance(plan, lp.Aggregate):
            if plan.op in ("sum", "min", "max", "group"):
                # associative: push down per combo, re-reduce with same op
                return ReduceAggregateExec(children_plans=fan(plan),
                                           op=plan.op, params=plan.params,
                                           by=plan.by, without=plan.without)
            if plan.op == "count":
                # partial counts combine by summing
                return ReduceAggregateExec(children_plans=fan(plan),
                                           op="sum", params=plan.params,
                                           by=plan.by, without=plan.without)
            # non-associative (avg/stddev/topk/quantile...): fan out the
            # unaggregated inner and aggregate once at the root
            return ReduceAggregateExec(children_plans=fan(plan.vector),
                                       op=plan.op, params=plan.params,
                                       by=plan.by, without=plan.without)
        return DistConcatExec(children_plans=fan(plan))


def _replace_shard_keys(plan, combo: dict[str, str], shard_labels):
    """Rewrite shard-key filters to the concrete combo values."""
    if isinstance(plan, lp.RawSeries):
        new_filters = tuple(
            ColumnFilter(f.column, Equals(combo[f.column]))
            if f.column in combo else f for f in plan.filters)
        return dataclasses.replace(plan, filters=new_filters)
    if dataclasses.is_dataclass(plan):
        changes = {}
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, lp.LogicalPlan):
                changes[f.name] = _replace_shard_keys(v, combo, shard_labels)
        if changes:
            return dataclasses.replace(plan, **changes)
    return plan
