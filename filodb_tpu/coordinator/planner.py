"""Query planners: LogicalPlan → ExecPlan materialization.

Counterpart of reference ``coordinator/src/main/scala/filodb.coordinator/
queryplanner/SingleClusterPlanner.scala:41,93,126`` — shard-aware
materialization with shard-key pruning (spread), per-shard leaf plans under
scatter-gather parents — plus the time-split planning axis
(``materializeTimeSplitPlan``) via ``split_time_range``.
"""

from __future__ import annotations

from dataclasses import dataclass

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.partkey import shard_key_hash, shards_for_shard_key
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import transformers as tf
from filodb_tpu.query.exec.binaryjoin import BinaryJoinExec, SetOperatorExec
from filodb_tpu.query.exec.plan import (
    DistConcatExec,
    EmptyResultExec,
    ExecPlan,
    InProcessPlanDispatcher,
    PlanDispatcher,
    ReduceAggregateExec,
    ScalarBinaryOperationExec,
    ScalarFixedDoubleExec,
    ScalarVaryingExec,
    SelectRawPartitionsExec,
    StitchRvsExec,
    TimeScalarGeneratorExec,
    VectorFromScalarExec,
)
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.metrics import get_counter

# two-phase aggregation pushdown decisions: Aggregate materializations that
# pushed a map stage into the children vs kept the full-gather path
PUSHDOWN_APPLIED = get_counter("filodb_agg_pushdown_applied")
PUSHDOWN_BYPASSED = get_counter("filodb_agg_pushdown_bypassed")


class QueryPlanner:
    """Reference ``QueryPlanner`` trait."""

    def materialize(self, plan: lp.LogicalPlan,
                    qcontext: QueryContext) -> ExecPlan:
        raise NotImplementedError


@dataclass
class SingleClusterPlanner(QueryPlanner):
    dataset: str
    num_shards: int = 1
    spread: int = 1
    shard_key_labels: tuple[str, ...] = ("_ws_", "_ns_", "_metric_")
    # optional: ms above which a range query is split into sequential
    # sub-plans + stitch (reference materializeTimeSplitPlan)
    time_split_ms: int = 0
    dispatcher_for_shard: "callable | None" = None
    # leaves read this store instead of the exec context's (downsample plans)
    store: object = None
    dataset_name_override: str | None = None
    # per-shard-key spread overrides (reference application spread config,
    # ``QueryActor.scala:56-70``): maps non-metric shard-key values
    # (e.g. ("demo", "App-big")) to a spread
    spread_overrides: dict = None

    # ---- shard selection ------------------------------------------------

    def shards_for_filters(self, filters, spread: int | None = None
                           ) -> list[int]:
        """Prune fan-out using shard-key equality filters
        (reference ``SingleClusterPlanner.shardsFromFilters``). Spread
        precedence: per-query override > per-shard-key config override >
        planner default (reference QueryActor spread overrides)."""
        eq = {f.column: f.filter.value for f in filters
              if isinstance(f.filter, Equals)}
        if spread is None and self.spread_overrides:
            key = tuple(eq.get(lbl) for lbl in self.shard_key_labels
                        if lbl != "_metric_")
            spread = self.spread_overrides.get(key)
        spread = self.spread if spread is None else spread
        if all(lbl in eq for lbl in self.shard_key_labels):
            skh = shard_key_hash({k: eq[k] for k in self.shard_key_labels})
            return shards_for_shard_key(skh, self.num_shards, spread)
        return list(range(self.num_shards))

    def _dispatcher(self, shard: int) -> PlanDispatcher | None:
        if self.dispatcher_for_shard is not None:
            return self.dispatcher_for_shard(shard)
        return None

    # ---- materialization ------------------------------------------------

    def materialize(self, plan: lp.LogicalPlan,
                    qcontext: QueryContext | None = None) -> ExecPlan:
        qcontext = qcontext or QueryContext()
        return self._walk(plan, qcontext)

    def _walk(self, plan, q) -> ExecPlan:
        m = getattr(self, "_mat_" + type(plan).__name__, None)
        if m is None:
            raise ValueError(f"cannot materialize {type(plan).__name__}")
        return m(plan, q)

    # -- leaves --

    def _leaves(self, raw: lp.RawSeries, q, mapper) -> list[ExecPlan]:
        chunk_start = raw.range_start - raw.lookback - raw.offset
        chunk_end = raw.range_end - raw.offset
        plans: list[ExecPlan] = []
        spread = q.planner_params.spread if q is not None else None
        for shard in self.shards_for_filters(raw.filters, spread):
            leaf = SelectRawPartitionsExec(
                shard=shard, filters=raw.filters, chunk_start=chunk_start,
                chunk_end=chunk_end, value_column=raw.column,
                store=self.store, dataset_name=self.dataset_name_override)
            d = self._dispatcher(shard)
            if d is not None:
                leaf.dispatcher = d
            leaf.add_transformer(mapper)
            plans.append(leaf)
        return plans

    def _concat(self, plans: list[ExecPlan]) -> ExecPlan:
        if len(plans) == 1:
            return plans[0]
        return DistConcatExec(children_plans=plans)

    def _split_ranges(self, start, step, end):
        """Split [start, end] into sequential sub-ranges on step boundaries
        (reference time-split planning)."""
        if (self.time_split_ms <= 0 or step <= 0
                or end - start <= self.time_split_ms):
            return [(start, end)]
        out = []
        cur = start
        steps_per_split = max(self.time_split_ms // step, 1)
        while cur <= end:
            sub_end = min(cur + steps_per_split * step - step, end)
            out.append((cur, sub_end))
            cur = sub_end + step
        return out

    def _mat_PeriodicSeries(self, plan: lp.PeriodicSeries, q) -> ExecPlan:
        parts = []
        for s, e in self._split_ranges(plan.start, plan.step, plan.end):
            mapper = tf.PeriodicSamplesMapper(
                s, plan.step, e, window=0, function=None, offset=plan.offset,
                at_ms=plan.at_ms)
            raw = (plan.raw if plan.at_ms is not None else
                   lp.RawSeries(plan.raw.filters, s, e, plan.raw.lookback,
                                plan.raw.offset, plan.raw.column))
            parts.append(self._concat(self._leaves(raw, q, mapper)))
        if len(parts) == 1:
            return parts[0]
        return StitchRvsExec(children_plans=parts)

    def _mat_PeriodicSeriesWithWindowing(
            self, plan: lp.PeriodicSeriesWithWindowing, q) -> ExecPlan:
        parts = []
        for s, e in self._split_ranges(plan.start, plan.step, plan.end):
            mapper = tf.PeriodicSamplesMapper(
                s, plan.step, e, window=plan.window, function=plan.function,
                params=plan.params, offset=plan.offset, at_ms=plan.at_ms)
            raw = (plan.raw if plan.at_ms is not None else
                   lp.RawSeries(plan.raw.filters, s, e,
                                max(plan.raw.lookback, plan.window),
                                plan.raw.offset, plan.raw.column))
            parts.append(self._concat(self._leaves(raw, q, mapper)))
        if len(parts) == 1:
            return parts[0]
        return StitchRvsExec(children_plans=parts)

    def _mat_RawSeries(self, plan: lp.RawSeries, q) -> ExecPlan:
        # raw export: instant mapper with lookback at chunk granularity
        mapper = tf.PeriodicSamplesMapper(plan.range_start, 0, plan.range_end,
                                          window=0, function=None,
                                          offset=plan.offset)
        return self._concat(self._leaves(plan, q, mapper))

    # -- aggregates / joins --

    # two-phase pushdown policy: "auto" pushes the map stage only when at
    # least one child leaves the process (the win is wire bytes; local
    # multi-shard plans keep the single big device reduce), "always" pushes
    # whenever the shape allows (tests/benchmarks), "off" never pushes
    agg_pushdown: str = "auto"

    def _agg_pushdown_leaves(self, plan: lp.Aggregate, inner: ExecPlan,
                             q=None) -> "list[ExecPlan] | None":
        """Selector leaves to push the map stage into, or None to bypass.

        Shape gate: the map stage rides the leaf transformer chains, so the
        inner plan must be a plain scatter-gather of selector leaves (any
        intermediate transformer or non-leaf child would see
        already-aggregated rows).

        Under "auto" the locality heuristic (push only when a child leaves
        the process) is the *static* arm of a learned decision: once the
        cost model has settled wall times for both arms of this signature
        class, the predicted-cheaper arm wins ("pushdown" decision site,
        settled with the query's wall time via the deferred-settle hook on
        the query context)."""
        if self.agg_pushdown == "off" or plan.op not in tf.AGG_PUSHDOWN_OPS:
            return None
        if isinstance(inner, SelectRawPartitionsExec):
            leaves = [inner]
        elif (isinstance(inner, DistConcatExec) and not inner.transformers
              and all(isinstance(c, SelectRawPartitionsExec)
                      for c in inner.children_plans)):
            leaves = inner.children_plans
        else:
            return None
        if self.agg_pushdown == "always":
            return leaves
        from filodb_tpu.query import cost_model as cm
        all_local = all(isinstance(c.dispatcher, InProcessPlanDispatcher)
                        for c in leaves)
        static_arm = "local" if all_local else "pushdown"
        model = cm.model_for(self.dataset)
        sig = (f"agg:{plan.op}:leaves{cm.bucket(len(leaves))}:"
               f"{'local' if all_local else 'remote'}")
        d = model.decide("pushdown", sig, ("pushdown", "local"), static_arm)
        if q is not None:
            model.defer(q, d)
        if d.arm == "local":
            return None  # keep the single big device reduce
        return leaves

    def _mat_Aggregate(self, plan: lp.Aggregate, q) -> ExecPlan:
        inner = self._walk(plan.vector, q)
        params = tuple(p for p in plan.params)
        leaves = self._agg_pushdown_leaves(plan, inner, q)
        if leaves is not None:
            PUSHDOWN_APPLIED.inc()
            for c in leaves:
                c.add_transformer(tf.AggregatePartialMapper(
                    plan.op, params, plan.by, plan.without))
            return ReduceAggregateExec(children_plans=leaves, op=plan.op,
                                       params=params, by=plan.by,
                                       without=plan.without, pushdown=True)
        PUSHDOWN_BYPASSED.inc()
        return ReduceAggregateExec(children_plans=[inner], op=plan.op,
                                   params=params, by=plan.by,
                                   without=plan.without)

    def _mat_BinaryJoin(self, plan: lp.BinaryJoin, q) -> ExecPlan:
        l = self._walk(plan.lhs, q)
        r = self._walk(plan.rhs, q)
        if plan.op in ("and", "or", "unless"):
            return SetOperatorExec(lhs_plans=[l], rhs_plans=[r], op=plan.op,
                                   on=plan.on, ignoring=plan.ignoring)
        return BinaryJoinExec(lhs_plans=[l], rhs_plans=[r], op=plan.op,
                              cardinality=plan.cardinality, on=plan.on,
                              ignoring=plan.ignoring, include=plan.include,
                              bool_mode=plan.bool_mode)

    def _mat_ScalarVectorBinaryOperation(
            self, plan: lp.ScalarVectorBinaryOperation, q) -> ExecPlan:
        vec = self._walk(plan.vector, q)
        scalar = self._walk(plan.scalar, q)
        vec.add_transformer(_ScalarOpDeferred(plan.op, scalar,
                                              plan.scalar_is_lhs,
                                              plan.bool_mode))
        return vec

    # -- functions --

    def _mat_ApplyInstantFunction(self, plan: lp.ApplyInstantFunction,
                                  q) -> ExecPlan:
        inner = self._walk(plan.vector, q)
        inner.add_transformer(tf.InstantVectorFunctionMapper(plan.function,
                                                             plan.args))
        return inner

    def _mat_ApplyMiscellaneousFunction(self, plan, q) -> ExecPlan:
        inner = self._walk(plan.vector, q)
        inner.add_transformer(tf.MiscellaneousFunctionMapper(plan.function,
                                                             plan.args))
        return inner

    def _mat_ApplySortFunction(self, plan, q) -> ExecPlan:
        inner = self._walk(plan.vector, q)
        inner.add_transformer(tf.SortFunctionMapper(plan.descending))
        return inner

    def _mat_ApplyAbsentFunction(self, plan: lp.ApplyAbsentFunction,
                                 q) -> ExecPlan:
        inner = self._walk(plan.vector, q)
        inner.add_transformer(tf.AbsentFunctionMapper(
            plan.filters, plan.start, plan.step or 1000, plan.end))
        return inner

    def _mat_ApplyLimitFunction(self, plan, q) -> ExecPlan:
        inner = self._walk(plan.vector, q)
        inner.add_transformer(tf.LimitFunctionMapper(plan.limit))
        return inner

    # -- subqueries --

    def _mat_SubqueryWithWindowing(self, plan: lp.SubqueryWithWindowing,
                                   q) -> ExecPlan:
        # evaluate inner over the extended range at the subquery step, then
        # apply the range function over the produced matrix
        inner_start = plan.start - plan.subquery_window - plan.offset
        inner_end = plan.end - plan.offset
        sub_step = plan.subquery_step or 60_000
        # align inner steps to multiples of sub_step (prom semantics)
        inner_start = (inner_start // sub_step) * sub_step
        inner = _retime(plan.inner, inner_start, sub_step, inner_end)
        inner_exec = self._walk(inner, q)
        inner_exec.add_transformer(tf.PeriodicSamplesMapper(
            plan.start, plan.step, plan.end, window=plan.subquery_window,
            function=plan.function, params=plan.params, offset=plan.offset))
        return inner_exec

    def _mat_TopLevelSubquery(self, plan: lp.TopLevelSubquery, q) -> ExecPlan:
        inner = _retime(plan.inner, plan.start, plan.step, plan.end)
        return self._walk(inner, q)

    # -- scalars --

    def _mat_ScalarFixedDoublePlan(self, plan, q) -> ExecPlan:
        return ScalarFixedDoubleExec(value=plan.value, start=plan.start,
                                     step=plan.step or 1000, end=plan.end)

    def _mat_ScalarTimeBasedPlan(self, plan, q) -> ExecPlan:
        return TimeScalarGeneratorExec(function=plan.function,
                                       start=plan.start,
                                       step=plan.step or 1000, end=plan.end)

    def _mat_ScalarVaryingDoublePlan(self, plan, q) -> ExecPlan:
        from filodb_tpu.coordinator.longtime_planner import _plan_times
        times = _plan_times(plan.vector)
        start, step, end = (times[0], max(times[1], 1), times[2]) if times \
            else (0, 1000, 0)
        return ScalarVaryingExec(inner=self._walk(plan.vector, q),
                                 start=start, step=step, end=end)

    def _mat_ScalarBinaryOperation(self, plan, q) -> ExecPlan:
        def conv(x):
            if isinstance(x, (int, float)):
                return float(x)
            return self._walk(x, q)

        return ScalarBinaryOperationExec(op=plan.op, lhs=conv(plan.lhs),
                                         rhs=conv(plan.rhs), start=plan.start,
                                         step=plan.step or 1000, end=plan.end)

    def _mat_VectorPlan(self, plan, q) -> ExecPlan:
        return VectorFromScalarExec(inner=self._walk(plan.scalar, q))


def _retime(plan: lp.LogicalPlan, start: int, step: int, end: int):
    """Rewrite a plan tree's evaluation range (subquery materialization)."""
    import dataclasses
    if isinstance(plan, (lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing)):
        raw = dataclasses.replace(plan.raw, range_start=start, range_end=end)
        return dataclasses.replace(plan, raw=raw, start=start, step=step,
                                   end=end)
    if isinstance(plan, lp.SubqueryWithWindowing):
        return dataclasses.replace(plan, start=start, step=step, end=end)
    if isinstance(plan, (lp.ScalarFixedDoublePlan, lp.ScalarTimeBasedPlan,
                         lp.ScalarBinaryOperation)):
        return dataclasses.replace(plan, start=start, step=step, end=end)
    if dataclasses.is_dataclass(plan):
        changes = {}
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, lp.LogicalPlan):
                changes[f.name] = _retime(v, start, step, end)
        if changes:
            return dataclasses.replace(plan, **changes)
    return plan


class _ScalarOpDeferred(tf.RangeVectorTransformer):
    """ScalarOperationMapper whose scalar side is an exec plan evaluated at
    apply time (needs the ExecContext — captured via a late bind)."""

    def __init__(self, op, scalar_exec, scalar_is_lhs, bool_mode):
        self.op = op
        self.scalar_exec = scalar_exec
        self.scalar_is_lhs = scalar_is_lhs
        self.bool_mode = bool_mode
        self._ctx = None

    def bind(self, ctx):
        self._ctx = ctx

    def apply(self, data):
        from filodb_tpu.query.exec.plan import ExecContext
        ctx = self._ctx
        if ctx is None:
            # scalar plans that don't touch the store can run with a nil ctx
            ctx = ExecContext(memstore=None, dataset="")
        scalar = self.scalar_exec.execute_scalar(ctx)
        return tf.ScalarOperationMapper(self.op, scalar, self.scalar_is_lhs,
                                        self.bool_mode).apply(data)
