"""Control plane: shard mapping, planners, cluster coordination, ingestion
orchestration.

Counterpart of reference ``coordinator/`` module.
"""
