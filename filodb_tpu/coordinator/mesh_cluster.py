"""Multi-process mesh runtime: root side of cluster-scale SPMD queries.

PR 14's mesh engine proves the SPMD formulation on a single-process
``(shard, time)`` mesh; this module is the cluster half the TPU-pod story
needs (ROADMAP item 4). Instead of shipping exec-plan subtrees and
gathering partial aggregates (``coordinator/remote.py``), the root lowers
the plan ONCE and ships a :class:`LoweredDescriptor` — the plan signature,
step grid, window, and mesh-axis assignment — to every mesh worker
process. Each worker owns a contiguous slice of the shard space, runs the
agg-stripped descriptor through its own ``MeshQueryEngine`` over a
1-device-per-process mesh slice (device-resident batch/bounds/eval caches
per process, PR 14 dkey semantics preserved), and returns per-series
``[P_local, K]`` window evaluations. The root's reduce is then the same
``make_mesh_group_reduce`` segment-sum the single-process engine runs —
over the concatenation of worker blocks in shard order — so the grouped
result is byte-identical to the single-process mesh engine (worker rows
arrive in global part order; the baseline's padding rows contribute an
exact ``+0.0`` at the segment tail).

Degradation mirrors PR 1/4 semantics exactly: every worker call runs
under its peer circuit breaker with deadline-derived timeouts; transport
failure, an open breaker, or a stale worker slice makes
:meth:`MeshClusterRuntime.execute_plan` return ``None`` and the caller
(``QueryService``) falls through — inside the same admission scope — to
the existing single-process mesh / partial-aggregation pushdown paths. A
worker-side admission shed, by contrast, propagates as ``QueryRejected``
(503 + Retry-After): overload is a healthy-peer verdict, not data loss.

``FILODB_MULTIPROC=0`` disables routing entirely (cold-model parity: the
single-process engine serves every query bit-for-bit as before).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass

import numpy as np

from filodb_tpu.coordinator.remote import RemotePlanDispatcher
from filodb_tpu.utils.metrics import Gauge, Histogram, get_counter
from filodb_tpu.utils.resilience import (
    CircuitOpenError,
    FaultInjector,
    breaker_for,
    default_retry_policy,
    record_peer_latency,
)
from filodb_tpu.utils.tracing import span

log = logging.getLogger(__name__)

# multi-process dispatch observability (tests/test_metrics_scrape.py pins
# these families; module-level so a scrape sees them before the first
# routed query — this module is imported at server boot via the wire
# registry / query service).
_M_PROC_DISPATCH = {o: get_counter("filodb_mesh_proc_dispatch",
                                   {"outcome": o},
                                   help="multi-process mesh dispatches by "
                                   "outcome")
                    for o in ("ok", "fallback", "rejected")}
_M_PROC_FALLBACK = {r: get_counter("filodb_mesh_proc_fallback",
                                   {"reason": r},
                                   help="multi-process dispatches that fell "
                                   "back to the single-process engines")
                    for r in ("disabled", "unsupported", "histogram",
                              "worker", "stale")}
_M_PROC_WORKERS = Gauge("filodb_mesh_proc_workers",
                        help="mesh worker processes last seen reachable")
_M_PROC_COLLECTIVE = Histogram(
    "filodb_mesh_proc_collective_seconds",
    help="root-side cross-process reduce latency (gather + group reduce)")


@dataclass(frozen=True)
class LoweredDescriptor:
    """A lowered mesh query shipped root → worker over the plan wire.

    Carries everything a worker needs to run its mesh slice without
    re-planning: the recognized plan signature (selector filters, range
    function, window, offset, grouping), the step grid (``start``/
    ``step``/``end``), and the global mesh-axis assignment
    (``shard_axis`` worker slices × ``time_axis`` devices — the CPU
    harness and today's TPU posture both run ``time_axis=1`` per
    process). Wire-registered (``coordinator/wire.py`` explicit tuple),
    so PR201/202 parity covers it.

    Workers execute the AGG-STRIPPED form (``to_lowered(strip_agg=
    True)``): per-series window evaluation is the shard-local half of the
    SPMD program; grouping/reduction and post-transforms stay on the root
    so the cross-process combine remains a single associative reduce.
    """

    dataset: str
    filters: tuple
    start: int
    step: int
    end: int
    window: int
    fn: str
    offset: int
    agg: str | None
    by: tuple
    without: tuple
    keep_metric: bool
    post: tuple = ()
    shard_axis: int = 1
    time_axis: int = 1

    @classmethod
    def from_lowered(cls, low, dataset: str,
                     shard_axis: int = 1) -> "LoweredDescriptor":
        return cls(dataset=dataset, filters=tuple(low.filters),
                   start=low.start, step=low.step, end=low.end,
                   window=low.window, fn=low.fn, offset=low.offset,
                   agg=low.agg, by=tuple(low.by),
                   without=tuple(low.without),
                   keep_metric=low.keep_metric, post=tuple(low.post),
                   shard_axis=shard_axis, time_axis=1)

    def to_lowered(self, strip_agg: bool = False):
        from filodb_tpu.parallel.mesh_engine import _Lowered

        if strip_agg:
            # worker half: raw per-series [P_local, K] rows, full keys
            # (the root re-derives group keys), no post-transforms
            return _Lowered(self.filters, self.start, self.step, self.end,
                            self.window, self.fn, self.offset, None, (),
                            (), True, ())
        return _Lowered(self.filters, self.start, self.step, self.end,
                        self.window, self.fn, self.offset, self.agg,
                        tuple(self.by), tuple(self.without),
                        self.keep_metric, tuple(self.post))

    @property
    def signature(self):
        """Worker-side descriptor-cache key (grid excluded, like
        ``_Lowered.signature``)."""
        return (self.dataset, self.filters, self.window, self.fn,
                self.offset, self.step)


class MeshWorkerClient(RemotePlanDispatcher):
    """Root → mesh-worker transport: descriptor execution and status on
    the pooled, authed plan wire. Subclassing the remote dispatcher keeps
    one framed protocol (auth, hello/compression, socket pool) and makes
    this class wire-registered through the dispatcher subclass walk."""

    def exec_descriptors(self, descs: list, deadline=None):
        """Execute descriptors on the worker's mesh slice. Returns the
        worker's result dict; raises ``QueryRejected`` when the worker's
        admission gate sheds the query (overload propagates, PR 1/4
        semantics) and transport errors / ``CircuitOpenError`` when the
        worker is unavailable (the runtime maps those to fallback)."""
        breaker = breaker_for(self.peer)

        def attempt():
            timeout = deadline.timeout(cap=self.timeout,
                                       what=f"mesh exec on {self.peer}") \
                if deadline is not None else self.timeout
            FaultInjector.fire("meshproc.exec", host=self.host,
                               port=self.port)
            # ship the remaining budget so the worker's admission wait is
            # bounded by the query deadline, not a local default
            return self._roundtrip(("mesh_exec", list(descs), timeout),
                                   timeout)

        t0 = time.perf_counter()
        with span("mesh-proc-exec", peer=self.peer), \
                breaker.calling(transport_errors=self.TRANSPORT_ERRORS):
            resp = default_retry_policy().call(
                attempt, retry_on=self.TRANSPORT_ERRORS, deadline=deadline)
        record_peer_latency(self.peer, time.perf_counter() - t0)
        if resp[0] == "ok":
            return resp[1]
        if resp[0] == "rejected":
            from filodb_tpu.utils.governor import QueryRejected
            retry_after = resp[2] if len(resp) > 2 else 1.0
            raise QueryRejected(
                f"mesh worker {self.peer} shed the query: {resp[1]}",
                retry_after_s=retry_after)
        raise RuntimeError(f"mesh exec failed on {self.peer}: {resp[1]}")

    def status(self, timeout_s: float = 2.0) -> dict:
        """Worker status snapshot on a short timeout (control plane —
        never under the query path's retry/breaker machinery)."""
        resp = self._roundtrip(("mesh_status",), timeout_s)
        if resp[0] == "ok":
            return resp[1]
        raise RuntimeError(f"mesh status failed on {self.peer}: {resp[1]}")


class MeshClusterRuntime:
    """Routes lowered mesh queries across N worker processes and reduces
    their slices — the cluster analog of ``MeshQueryEngine.execute``.

    ``workers`` is a list of ``(host, port, (shard_lo, shard_hi))``
    entries whose half-open shard ranges must tile ``[0, num_shards)`` in
    order: concatenating worker result blocks in worker order then equals
    the single-process engine's global part order, which is what makes
    the root reduce byte-identical to the single-process path.
    """

    def __init__(self, memstore, dataset: str, num_shards: int,
                 workers: list, timeout: float = 30.0):
        lo_seen = 0
        self.workers = []
        for host, port, (lo, hi) in workers:
            if lo != lo_seen:
                raise ValueError(
                    f"worker shard slices must tile [0, {num_shards}) "
                    f"contiguously; got [{lo}, {hi}) after {lo_seen}")
            lo_seen = hi
            self.workers.append((MeshWorkerClient(host, port,
                                                  timeout=timeout),
                                 (lo, hi)))
        if lo_seen != num_shards:
            raise ValueError(f"worker slices cover [0, {lo_seen}), "
                             f"need [0, {num_shards})")
        self.memstore = memstore
        self.dataset = dataset
        self.num_shards = num_shards
        self.timeout = timeout
        self.last_collective_s: float | None = None
        self._lock = threading.Lock()
        self._lowerer = None
        self._root_mesh = None
        self._reduce_fns: dict = {}
        _M_PROC_WORKERS.set(len(self.workers))

    # ---- routing gate ----------------------------------------------------

    def enabled(self) -> bool:
        return bool(self.workers) \
            and os.environ.get("FILODB_MULTIPROC", "1") != "0"

    # ---- lowering (shared with the single-process engine) ----------------

    def _lowering_engine(self):
        """A bare mesh engine used ONLY for plan recognition — never
        touches devices. ``sidecars=True`` mirrors the serving engines'
        decline of tick-shaped grids, so multiproc routing and the
        single-process path agree on which plans are mesh-shaped."""
        if self._lowerer is None:
            from filodb_tpu.parallel.mesh_engine import MeshQueryEngine
            self._lowerer = MeshQueryEngine(sidecars=True)
        return self._lowerer

    # ---- execution -------------------------------------------------------

    def execute_plan(self, plan, deadline=None, stats=None):
        """Run a plan across the worker processes; ``None`` = fall back
        to the single-process engines (callers stay inside their
        admission scope, so the fallback is never a second admit)."""
        if not self.enabled():
            _M_PROC_FALLBACK["disabled"].inc()
            _M_PROC_DISPATCH["fallback"].inc()
            return None
        low = self._lowering_engine()._lower(plan)
        if low is None:
            _M_PROC_FALLBACK["unsupported"].inc()
            _M_PROC_DISPATCH["fallback"].inc()
            return None
        return self.execute_lowered(low, deadline=deadline, stats=stats)

    def execute_lowered(self, low, deadline=None, stats=None):
        """Scatter one lowered query to every worker slice and reduce.
        ``None`` = worker unavailability / shape decline (fallback);
        ``QueryRejected`` propagates (a shed worker is overload)."""
        if not self.enabled():
            _M_PROC_FALLBACK["disabled"].inc()
            _M_PROC_DISPATCH["fallback"].inc()
            return None
        desc = LoweredDescriptor.from_lowered(low, self.dataset,
                                              shard_axis=len(self.workers))
        # snapshot root offsets BEFORE dispatch: a worker that has tailed
        # at least this far saw everything the root would scan now
        root_off = {s.shard_num: s.latest_offset
                    for s in self.memstore.shards_for(self.dataset)} \
            if self.memstore is not None else {}
        from filodb_tpu.utils.governor import QueryRejected
        threads = []
        outs: list = [None] * len(self.workers)
        errs: list = [None] * len(self.workers)

        def run(i, cli):
            try:
                outs[i] = cli.exec_descriptors([desc], deadline)
            except Exception as e:  # classified below, on the caller
                errs[i] = e

        for i, (cli, _) in enumerate(self.workers):
            t = threading.Thread(target=run, args=(i, cli), daemon=True,
                                 name=f"meshproc-{cli.peer}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        for e in errs:
            if isinstance(e, QueryRejected):
                _M_PROC_DISPATCH["rejected"].inc()
                raise e
        bad = [e for e in errs if e is not None]
        if bad:
            for e in bad:
                if not isinstance(e, (CircuitOpenError, RuntimeError,
                                      *MeshWorkerClient.TRANSPORT_ERRORS)):
                    raise e  # deadline exhaustion, programming errors
            log.warning("mesh worker slice unavailable, falling back: %s",
                        bad[0])
            _M_PROC_FALLBACK["worker"].inc()
            _M_PROC_DISPATCH["fallback"].inc()
            return None
        _M_PROC_WORKERS.set(len(self.workers))
        for (_, (lo, hi)), part in zip(self.workers, outs):
            offs = part.get("offsets", {})
            for s in range(lo, hi):
                if offs.get(s, -1) < root_off.get(s, 0):
                    _M_PROC_FALLBACK["stale"].inc()
                    _M_PROC_DISPATCH["fallback"].inc()
                    return None
        mats = [part["results"][0] for part in outs]
        if any(m is None for m in mats):
            _M_PROC_FALLBACK["unsupported"].inc()
            _M_PROC_DISPATCH["fallback"].inc()
            return None
        if any(m.les is not None for m in mats):
            # histogram batches flatten buckets on the single-process
            # engine; the cross-process combine doesn't carry them yet
            _M_PROC_FALLBACK["histogram"].inc()
            _M_PROC_DISPATCH["fallback"].inc()
            return None
        if stats is not None:
            for part in outs:
                stats.series_scanned += int(part.get("series", 0))
                stats.samples_scanned += int(part.get("samples", 0))
        t0 = time.perf_counter()
        result = self._reduce(low, mats)
        dt = time.perf_counter() - t0
        self.last_collective_s = dt
        _M_PROC_COLLECTIVE.observe(dt)
        _M_PROC_DISPATCH["ok"].inc()
        return result

    def _reduce(self, low, mats):
        """Root-side window-boundary reduce over the gathered worker
        blocks: exactly the single-process engine's group segment-sum,
        run on a 1-device root mesh (the 1-wide shard axis imposes no
        padding, so real rows keep their global order and bit patterns).
        """
        from filodb_tpu.parallel.mesh_engine import MeshQueryEngine
        from filodb_tpu.query.exec.transformers import steps_array
        from filodb_tpu.query.model import StepMatrix

        steps_ms = steps_array(low.start, low.step, low.end)
        K = len(steps_ms)
        keys: list = []
        blocks: list = []
        for m in mats:
            keys.extend(m.keys)
            v = np.asarray(m.values, dtype=np.float64)
            blocks.append(v if v.size else v.reshape(0, K))
        if not keys:
            return MeshQueryEngine._apply_post(StepMatrix.empty(steps_ms),
                                               low)
        vals = np.concatenate(blocks, axis=0) if len(blocks) > 1 \
            else blocks[0]
        if low.agg is None:
            rkeys = list(keys) if low.keep_metric \
                else [k.drop_metric() for k in keys]
            m = StepMatrix(rkeys, vals, steps_ms)
            return MeshQueryEngine._apply_post(m, low)
        gkeys = [MeshQueryEngine._group_key(k, low) for k in keys]
        uniq: dict = {}
        gids = np.empty(len(gkeys), np.int32)
        for i, gk in enumerate(gkeys):
            gids[i] = uniq.setdefault(gk, len(uniq))
        G = len(uniq)
        out = np.asarray(self._reduce_fn(low.agg, G)(vals, gids))
        m = StepMatrix(list(uniq.keys()), out[:G], steps_ms)
        return MeshQueryEngine._apply_post(m, low)

    def _reduce_fn(self, agg: str, G: int):
        """Compiled cross-process group reduce, bucketed by group count
        like the engine's program cache."""
        from filodb_tpu.parallel.dist_query import make_mesh_group_reduce
        from filodb_tpu.parallel.mesh_engine import make_query_mesh
        from filodb_tpu.query.engine.device_batch import _pow2

        Gp = _pow2(max(G, 1))
        with self._lock:
            if self._root_mesh is None:
                self._root_mesh = make_query_mesh(n_devices=1)
            fn = self._reduce_fns.get((agg, Gp))
            if fn is None:
                fn = self._reduce_fns[(agg, Gp)] = \
                    make_mesh_group_reduce(self._root_mesh, Gp, agg)
        return fn

    # ---- observability ---------------------------------------------------

    def status(self) -> dict:
        """Per-worker mesh slice, device count, descriptor-cache
        occupancy, and last collective latency (``filo-cli meshstat`` /
        ``/api/v1/status/mesh``)."""
        workers = []
        reachable = 0
        for cli, (lo, hi) in self.workers:
            entry = {"peer": cli.peer, "shards": [lo, hi],
                     "breaker": breaker_for(cli.peer).state}
            try:
                entry.update(cli.status())
                entry["reachable"] = True
                reachable += 1
            except MeshWorkerClient.TRANSPORT_ERRORS as e:
                entry["reachable"] = False
                entry["error"] = str(e)
            workers.append(entry)
        _M_PROC_WORKERS.set(reachable)
        return {"dataset": self.dataset, "num_shards": self.num_shards,
                "enabled": self.enabled(), "workers": workers,
                "last_collective_s": self.last_collective_s}

    def shutdown(self) -> None:
        """Drop pooled worker connections (the supervisor owns process
        lifecycle)."""
        for cli, _ in self.workers:
            cli._drop_conn()
