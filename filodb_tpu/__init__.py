"""filodb_tpu — a TPU-native, Prometheus-compatible, distributed time-series database.

A ground-up rebuild of the capabilities of FiloDB (reference: tuplejump/FiloDB,
Scala/JVM/Akka/Cassandra) designed TPU-first:

- Host-side ingest runtime (C++ codecs + Python orchestration) writing compressed
  columnar chunks (delta-delta timestamps, XOR doubles, NibblePack bit-packing,
  2D-delta histograms — technique parity with reference ``doc/compression.md``).
- Query hot path (chunk windowing, range functions such as ``rate``/``sum_over_time``,
  histogram quantiles, label-grouped aggregation) as jitted JAX kernels over dense
  padded tensors, scaling over a ``jax.sharding.Mesh`` with XLA collectives.
- PromQL front end, scatter-gather exec-plan tree, shard assignment, durable chunk
  store with checkpointed replay recovery, downsampling — capability parity with the
  reference layer map (see SURVEY.md).
"""

__version__ = "0.1.0"
