"""filodb_tpu — a TPU-native, Prometheus-compatible, distributed time-series database.

A ground-up rebuild of the capabilities of FiloDB (reference: tuplejump/FiloDB,
Scala/JVM/Akka/Cassandra) designed TPU-first:

- Host-side ingest runtime (C++ codecs + Python orchestration) writing compressed
  columnar chunks (delta-delta timestamps, XOR doubles, NibblePack bit-packing,
  2D-delta histograms — technique parity with reference ``doc/compression.md``).
- Query hot path (chunk windowing, range functions such as ``rate``/``sum_over_time``,
  histogram quantiles, label-grouped aggregation) as jitted JAX kernels over dense
  padded tensors, scaling over a ``jax.sharding.Mesh`` with XLA collectives.
- PromQL front end, scatter-gather exec-plan tree, shard assignment, durable chunk
  store with checkpointed replay recovery, downsampling — capability parity with the
  reference layer map (see SURVEY.md).
"""

__version__ = "0.1.0"


def _maybe_install_lockcheck():
    # FILODB_LOCKCHECK=1 arms the debug runtime lock-order validator for
    # the whole process. Must run at package import, before any filodb
    # module creates its locks — later-created locks are the only ones
    # the checker can wrap.
    import os
    if os.environ.get("FILODB_LOCKCHECK", "") not in ("", "0", "false"):
        from filodb_tpu.utils import lockcheck
        lockcheck.install(
            strict=os.environ.get("FILODB_LOCKCHECK_STRICT",
                                  "") not in ("", "0", "false"))


def _maybe_install_racecheck():
    # FILODB_RACECHECK=1 arms the shared-state race sanitizer for the
    # whole process. Runs after lockcheck (its guard sets come from the
    # lock checker's held stack) and before any filodb module registers
    # shared objects.
    import os
    if os.environ.get("FILODB_RACECHECK", "") not in ("", "0", "false"):
        from filodb_tpu.utils import racecheck
        racecheck.install(
            strict=os.environ.get("FILODB_RACECHECK_STRICT",
                                  "") not in ("", "0", "false"))


_maybe_install_lockcheck()
_maybe_install_racecheck()


def __getattr__(name):
    # lazy convenience exports (keep bare import light; jax loads on demand)
    if name == "FiloClient":
        from filodb_tpu.client import FiloClient
        return FiloClient
    if name == "FiloServer":
        from filodb_tpu.standalone import FiloServer
        return FiloServer
    if name == "ServerConfig":
        from filodb_tpu.config import ServerConfig
        return ServerConfig
    if name == "QueryService":
        from filodb_tpu.coordinator.query_service import QueryService
        return QueryService
    if name == "TimeSeriesMemStore":
        from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
        return TimeSeriesMemStore
    raise AttributeError(name)
