"""Gateway TCP server: Influx line protocol in, shard-routed log out.

Counterpart of reference ``GatewayServer.scala:58`` (Netty TCP →
BinaryRecords → per-shard containers → Kafka): lines arrive over TCP (one
per line, Influx wire format), are parsed to records, routed to shards by
partition-key hash (identical hash/spread semantics as ingestion — so this
gateway and the shards agree without coordination), batched per shard and
appended to the shard logs.
"""

from __future__ import annotations

import logging
import socketserver
import threading
import time

from filodb_tpu.coordinator.ingestion import route_container
from filodb_tpu.core.record import RecordContainer
from filodb_tpu.gateway.influx import InfluxParseError, parse_influx_line
from filodb_tpu.kafka.log import ReplayLog
from filodb_tpu.utils import governor as governor_mod
from filodb_tpu.utils.metrics import Counter, GaugeFn, Histogram
from filodb_tpu.utils.selfmon import STAMPS
from filodb_tpu.utils.tracing import traced_operation

log = logging.getLogger(__name__)

lines_parsed = Counter("gateway_lines_parsed")
lines_failed = Counter("gateway_lines_failed")
backpressure_waits = Counter("gateway_backpressure_waits")
backpressure_seconds = Histogram("gateway_backpressure_seconds")
# ingest shedding under governor CRITICAL state: records dropped instead of
# blocking a full queue (observable BEFORE it becomes an outage)
records_shed = Counter("gateway_records_shed")


class ContainerSink:
    """Batches records per shard and flushes to the shard logs (reference
    ``KafkaContainerSink``), with EXPLICIT bounded backpressure (the
    reference's reactive-streams demand signalling, SURVEY §2 P7): at most
    one flush is in flight; producers keep batching into the pending
    container while it drains, and once ``max_pending`` records are
    buffered ``add`` BLOCKS the producer thread — TCP then pushes back to
    the client — until the flush completes. Wait counts/durations surface
    as ``gateway_backpressure_*`` metrics."""

    def __init__(self, logs: dict[int, ReplayLog], num_shards: int,
                 spread: int = 1, flush_every: int = 512,
                 max_pending: int = 16384, dataset: str = "prometheus"):
        self.logs = logs
        self.num_shards = num_shards
        self.spread = spread
        self.dataset = dataset  # keys the sampled e2e freshness stamps
        self.flush_every = flush_every
        self.max_pending = max(max_pending, flush_every)
        self._pending = RecordContainer()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._flushing = False
        # live queue depth at scrape time; weakref so a torn-down sink
        # drops its series instead of pinning the object
        import weakref
        ref = weakref.ref(self)
        GaugeFn("gateway_queue_depth",
                lambda: (len(s._pending) if (s := ref()) is not None
                         else None))

    def add(self, records) -> None:
        records = list(records)
        t0 = None
        while True:
            batch = None
            inserted = False
            with self._cond:
                if len(self._pending) < self.max_pending:
                    for r in records:
                        self._pending.add(r)
                    inserted = True
                    if len(self._pending) >= self.flush_every \
                            and not self._flushing:
                        batch = self._pending
                        self._pending = RecordContainer()
                        self._flushing = True
                elif not self._flushing:
                    # buffer full and nobody draining: this producer takes
                    # the drain, then retries its own insert
                    batch = self._pending
                    self._pending = RecordContainer()
                    self._flushing = True
                else:
                    # full AND a drain is in flight. Under governor
                    # CRITICAL (memory pressure) blocking would hold the
                    # buffered records alive while memory is the scarce
                    # resource — shed this batch instead and let the
                    # client retry once pressure clears.
                    if governor_mod.governor().state == governor_mod.CRITICAL:
                        records_shed.inc(len(records))
                        if t0 is not None:
                            backpressure_seconds.observe(
                                time.perf_counter() - t0)
                        return
                    # otherwise BLOCK (TCP pushes the pressure back to
                    # the client)
                    if t0 is None:
                        t0 = time.perf_counter()
                        backpressure_waits.inc()
                    self._cond.wait(timeout=5.0)
            if batch is not None:
                self._drain(batch)
            if inserted:
                if t0 is not None:
                    backpressure_seconds.observe(time.perf_counter() - t0)
                return

    def flush(self) -> None:
        while True:
            with self._cond:
                while self._flushing:
                    self._cond.wait(timeout=5.0)
                if not len(self._pending):
                    return
                batch = self._pending
                self._pending = RecordContainer()
                self._flushing = True
            self._drain(batch)

    def _drain(self, batch: RecordContainer) -> None:
        """Append owned batches to the shard logs, outside the lock —
        parsing threads keep batching while IO is in flight. The
        ``_flushing`` guard keeps appends serialized in batch-swap order,
        so per-shard record order is preserved (a reordered append would
        trip the shards' out-of-order drop). After each drain, a pending
        buffer that crossed ``flush_every`` mid-drain is taken too —
        otherwise it would sit unflushed until the next add() (an idle
        persistent connection could strand records indefinitely)."""
        while batch is not None:
            try:
                # slow drains land in the ingest-side flight recorder;
                # every Nth appended container is stamped so the shard
                # workers can close the e2e freshness histogram
                with traced_operation("gateway", op="drain",
                                      records=len(batch)):
                    for shard, cont in route_container(
                            batch, self.num_shards, self.spread).items():
                        off = self.logs[shard].append(cont)
                        STAMPS.maybe_stamp(self.dataset, shard, off)
            finally:
                with self._cond:
                    self._flushing = False
                    self._cond.notify_all()
            batch = None
            with self._cond:
                if len(self._pending) >= self.flush_every \
                        and not self._flushing:
                    batch = self._pending
                    self._pending = RecordContainer()
                    self._flushing = True


class GatewayServer:
    def __init__(self, sink: ContainerSink,
                 default_labels: dict[str, str] | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.sink = sink
        self.default_labels = default_labels or {"_ws_": "default",
                                                 "_ns_": "default"}
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for raw in self.rfile:
                    try:
                        recs = parse_influx_line(
                            raw.decode("utf-8", "replace"),
                            outer.default_labels,
                            now_ms=int(time.time() * 1000))
                        if recs:
                            outer.sink.add(recs)
                            lines_parsed.inc()
                    except (InfluxParseError, ValueError):
                        lines_failed.inc()
                outer.sink.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # rebind across fast restarts

        self.server = Server((host, port), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def start(self) -> "GatewayServer":
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
