"""Gateway TCP server: Influx line protocol in, shard-routed log out.

Counterpart of reference ``GatewayServer.scala:58`` (Netty TCP →
BinaryRecords → per-shard containers → Kafka): lines arrive over TCP (one
per line, Influx wire format), are parsed to records, routed to shards by
partition-key hash (identical hash/spread semantics as ingestion — so this
gateway and the shards agree without coordination), batched per shard and
appended to the shard logs.
"""

from __future__ import annotations

import logging
import socketserver
import threading
import time

from filodb_tpu.coordinator.ingestion import route_container
from filodb_tpu.core.record import RecordContainer
from filodb_tpu.gateway.influx import InfluxParseError, parse_influx_line
from filodb_tpu.kafka.log import ReplayLog
from filodb_tpu.utils.metrics import Counter

log = logging.getLogger(__name__)

lines_parsed = Counter("gateway_lines_parsed")
lines_failed = Counter("gateway_lines_failed")


class ContainerSink:
    """Batches records per shard and flushes to the shard logs (reference
    ``KafkaContainerSink``)."""

    def __init__(self, logs: dict[int, ReplayLog], num_shards: int,
                 spread: int = 1, flush_every: int = 512):
        self.logs = logs
        self.num_shards = num_shards
        self.spread = spread
        self.flush_every = flush_every
        self._pending = RecordContainer()
        self._lock = threading.Lock()

    def add(self, records) -> None:
        with self._lock:
            for r in records:
                self._pending.add(r)
            if len(self._pending) >= self.flush_every:
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not len(self._pending):
            return
        for shard, cont in route_container(self._pending, self.num_shards,
                                           self.spread).items():
            self.logs[shard].append(cont)
        self._pending = RecordContainer()


class GatewayServer:
    def __init__(self, sink: ContainerSink,
                 default_labels: dict[str, str] | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.sink = sink
        self.default_labels = default_labels or {"_ws_": "default",
                                                 "_ns_": "default"}
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for raw in self.rfile:
                    try:
                        recs = parse_influx_line(
                            raw.decode("utf-8", "replace"),
                            outer.default_labels,
                            now_ms=int(time.time() * 1000))
                        if recs:
                            outer.sink.add(recs)
                            lines_parsed.inc()
                    except (InfluxParseError, ValueError):
                        lines_failed.inc()
                outer.sink.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # rebind across fast restarts

        self.server = Server((host, port), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def start(self) -> "GatewayServer":
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()
