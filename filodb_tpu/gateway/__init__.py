"""Gateway: external wire protocols → ingestion records → shard-routed log.

Counterpart of reference ``gateway/`` module (``GatewayServer.scala:58``,
``InfluxProtocolParser``, ``KafkaContainerSink``).
"""
