"""Influx Line Protocol parsing → ingestion records.

Counterpart of reference ``gateway/src/main/scala/filodb/gateway/conversion/
InfluxProtocolParser.scala:1-238`` + ``InfluxRecord.scala:1-269`` (histogram-
aware conversion) and the ``InputRecord`` SPI (``InputRecord.scala:1-236``):

  measurement[,tag=v,...] field=value[,field2=v2,...] [timestamp_ns]

- single field ``value``        → gauge record, metric = measurement
- single field ``counter``      → prom-counter record
- histogram fields (numeric bucket bounds / ``+Inf`` with ``sum``/``count``)
  → one first-class prom-histogram record (the reference's histogram-aware
  Influx conversion)
- multiple generic fields       → one gauge series per field, metric =
  ``measurement_field``

Tags become labels; ``_ws_``/``_ns_`` default from the gateway config when
absent (reference gateway dataset conventions).
"""

from __future__ import annotations

import numpy as np

from filodb_tpu.core.partkey import METRIC_LABEL, PartKey
from filodb_tpu.core.record import IngestRecord


class InfluxParseError(ValueError):
    pass


def _split_unescaped(s: str, sep: str) -> list[str]:
    out, cur, i = [], [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _split_top(s: str) -> list[str]:
    """Split line into measurement+tags / fields / timestamp on unescaped,
    unquoted spaces."""
    parts, cur = [], []
    in_quote = False
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
            cur.append(c)
        elif c == " " and not in_quote:
            if cur:
                parts.append("".join(cur))
                cur = []
        else:
            cur.append(c)
        i += 1
    if cur:
        parts.append("".join(cur))
    return parts


def _parse_field_value(v: str) -> float:
    if v.endswith(("i", "u")):
        return float(int(v[:-1]))
    if v in ("t", "T", "true", "True"):
        return 1.0
    if v in ("f", "F", "false", "False"):
        return 0.0
    if v.startswith('"'):
        raise InfluxParseError("string field values are not ingestible")
    return float(v)


def parse_influx_line(line: str, default_labels: dict[str, str] | None = None,
                      now_ms: int | None = None) -> list[IngestRecord]:
    """Parse one line; returns the ingestion records it produces."""
    line = line.strip()
    if not line or line.startswith("#"):
        return []
    parts = _split_top(line)
    if len(parts) < 2:
        raise InfluxParseError(f"malformed line: {line!r}")
    meas_and_tags = _split_unescaped(parts[0], ",")
    measurement = meas_and_tags[0]
    labels: dict[str, str] = dict(default_labels or {})
    for tag in meas_and_tags[1:]:
        if "=" not in tag:
            raise InfluxParseError(f"malformed tag {tag!r}")
        k, v = tag.split("=", 1)
        labels[k] = v
    fields: dict[str, float] = {}
    for fkv in _split_unescaped(parts[1], ","):
        if "=" not in fkv:
            raise InfluxParseError(f"malformed field {fkv!r}")
        k, v = fkv.split("=", 1)
        try:
            fields[k] = _parse_field_value(v)
        except InfluxParseError:
            continue  # skip string fields
    if len(parts) >= 3:
        ts_ms = int(int(parts[2]) // 1_000_000)  # ns → ms
    else:
        import time
        ts_ms = now_ms if now_ms is not None else int(time.time() * 1000)

    if not fields:
        return []

    # histogram detection: numeric bucket bounds (or +Inf) plus sum/count
    bucket_keys = []
    for k in fields:
        if k in ("sum", "count"):
            continue
        try:
            float(k.replace("+Inf", "inf"))
            bucket_keys.append(k)
        except ValueError:
            bucket_keys = []
            break
    if bucket_keys and "sum" in fields and "count" in fields:
        les = sorted((float(k.replace("+Inf", "inf")), k)
                     for k in bucket_keys)
        le_arr = np.array([le for le, _ in les])
        buckets = np.array([fields[k] for _, k in les], dtype=np.int64)
        key = PartKey.create("prom-histogram",
                             {**labels, METRIC_LABEL: measurement})
        return [IngestRecord(key, ts_ms,
                             (fields["sum"], fields["count"],
                              (le_arr, buckets)))]

    out = []
    if set(fields) == {"value"}:
        key = PartKey.create("gauge", {**labels, METRIC_LABEL: measurement})
        out.append(IngestRecord(key, ts_ms, (fields["value"],)))
    elif set(fields) == {"counter"}:
        key = PartKey.create("prom-counter",
                             {**labels, METRIC_LABEL: measurement})
        out.append(IngestRecord(key, ts_ms, (fields["counter"],)))
    else:
        for fname, fval in fields.items():
            key = PartKey.create(
                "gauge", {**labels, METRIC_LABEL: f"{measurement}_{fname}"})
            out.append(IngestRecord(key, ts_ms, (fval,)))
    return out
