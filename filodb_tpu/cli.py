"""filo-cli: operator command line.

Counterpart of reference ``cli/src/main/scala/filodb.cli/CliMain.scala:80,
100-115,378`` commands: init / list / status / indexnames / indexvalues /
labelvalues / importcsv / promql execution / partkey+vector decode debug.

Embedded mode: opens the local data dir directly. Remote mode: ``--host``
targets a running server's HTTP API.
"""

from __future__ import annotations

import argparse
import csv
import json
import sys

import numpy as np


def _open_stores(args):
    """Open the configured ColumnStore backend (embedded mode).

    ``--store local`` (default) opens the sqlite tier under
    ``data_dir/columnstore``; ``--store object`` opens the S3-compatible
    segment tier (``--endpoint`` http(s)://… for a real service, else a
    directory-backed fake under ``data_dir/objectstore``)."""
    import os

    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    data_dir = args if isinstance(args, str) else args.data_dir
    backend = "local" if isinstance(args, str) else args.store
    if backend == "object":
        from filodb_tpu.core.store.objectstore import open_object_store
        cs, meta = open_object_store(
            {"endpoint": getattr(args, "endpoint", None),
             "bucket": getattr(args, "bucket", "filodb")}, data_dir)
    else:
        from filodb_tpu.core.store.localstore import (
            LocalDiskColumnStore,
            LocalDiskMetaStore,
        )
        root = os.path.join(data_dir, "columnstore")
        cs = LocalDiskColumnStore(root)
        meta = LocalDiskMetaStore(root)
    return cs, meta, TimeSeriesMemStore(cs, meta)


def cmd_init(args):
    cs, _, _ = _open_stores(args)
    cs.initialize(args.dataset, args.num_shards)
    print(f"initialized dataset {args.dataset} with {args.num_shards} shards")


def cmd_list(args):
    cs, _, _ = _open_stores(args)
    total = 0
    for shard in range(args.num_shards):
        recs = cs.scan_part_keys(args.dataset, shard)
        total += len(recs)
        for r in recs[: args.limit]:
            print(f"shard={shard} {r.part_key} "
                  f"[{r.start_time}, {r.end_time}]")
    print(f"total partitions: {total}")


def cmd_status(args):
    import urllib.error
    import urllib.request
    url = f"http://{args.host}/api/v1/cluster/{args.dataset}/status"
    with urllib.request.urlopen(url) as r:
        print(json.dumps(json.load(r), indent=2))
    # TSDB head/cardinality summary (``/api/v1/status/tsdb``); older
    # servers without the route still answer the cluster status above
    try:
        with urllib.request.urlopen(
                f"http://{args.host}/api/v1/status/tsdb"
                f"?dataset={args.dataset}&topk={args.k}") as r:
            doc = json.load(r)["data"].get(args.dataset)
    except urllib.error.HTTPError:
        return
    if not doc:
        return
    head = doc["headStats"]
    print(f"\nhead: series={head['numSeries']} shards={head['numShards']}")
    print(f"{'SHARD':>5} {'SERIES':>8} {'INDEX_RAM':>10} {'ENC_BYTES':>10} "
          f"{'CHUNKS_FLUSHED':>14}")
    for s in doc["shards"]:
        print(f"{s['shard']:>5} {s['numSeries']:>8} "
              f"{s['indexRamBytes']:>10} {s['encodedBytes']:>10} "
              f"{s['chunksFlushed']:>14}")
    print("\ntop metrics by active series:")
    for m in doc["seriesCountByMetricName"]:
        print(f"  {m['name']:<40} {m['value']:>8}")
    print("top labels by distinct values:")
    for m in doc["labelValueCountByLabelName"]:
        print(f"  {m['name']:<40} {m['value']:>8}")


def cmd_tiers(args):
    """Retention-tier map for a dataset (``/api/v1/status/tiers``): which
    tiers answer queries (memstore / downsample / objectstore), their time
    floors, and per-tier series/bytes."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://{args.host}/api/v1/status/tiers"
            f"?dataset={args.dataset}") as r:
        d = json.load(r)["data"]
    doc = d.get(args.dataset)
    if doc is None:
        print(f"unknown dataset {args.dataset}")
        return 1
    if args.json:
        print(json.dumps(doc, indent=2))
        return 0
    print(f"dataset={args.dataset} federated={doc['federated']}")
    for k in ("memFloorMs", "rawFloorMs"):
        if doc.get(k) is not None:
            print(f"{k}: {doc[k]}")
    print(f"\n{'TIER':<12} {'SERIES':>9} {'BYTES':>12} {'DETAIL'}")
    for t in doc["tiers"]:
        extra = " ".join(
            f"{k}={t[k]}" for k in ("segments", "resolutionMs")
            if t.get(k) is not None)
        print(f"{t['tier']:<12} {str(t.get('series', '-')):>9} "
              f"{str(t.get('bytes', '-')):>12} {extra}")
    return 0


def cmd_meshstat(args):
    """Multi-process mesh runtime one-pager: per-worker mesh slice,
    reachability/breaker state, device count, descriptor-cache occupancy,
    and the last root-side collective latency
    (``/api/v1/status/mesh``)."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://{args.host}/api/v1/status/mesh") as r:
        d = json.load(r)["data"]
    if args.json:
        print(json.dumps(d, indent=2))
        return 0
    for ds, doc in d.items():
        if not doc.get("multiproc"):
            eng = doc.get("engine")
            extra = (f" engine: hits={eng['hits']} misses={eng['misses']} "
                     f"programs={eng['programs']}" if eng else "")
            print(f"dataset={ds} multiproc=off{extra}")
            continue
        coll = doc.get("last_collective_s")
        print(f"dataset={ds} multiproc=on enabled={doc['enabled']} "
              f"shards={doc['num_shards']} "
              f"last_collective_s="
              f"{'-' if coll is None else f'{coll:.4f}'}")
        print(f"{'WORKER':<22} {'SHARDS':>9} {'UP':>3} {'BREAKER':>9} "
              f"{'DEVS':>5} {'DESCCACHE':>9} {'QUERIES':>8} "
              f"{'LAST_EXEC_S':>11}")
        for w in doc.get("workers", []):
            lo, hi = w.get("shards", [0, 0])
            last = w.get("last_exec_s")
            print(f"{w['peer']:<22} {f'{lo}:{hi}':>9} "
                  f"{('y' if w.get('reachable') else 'n'):>3} "
                  f"{w.get('breaker', '?'):>9} "
                  f"{str(w.get('devices', '-')):>5} "
                  f"{str(w.get('descriptor_cache', '-')):>9} "
                  f"{str(w.get('queries', '-')):>8} "
                  f"{('-' if last is None else f'{last:.4f}'):>11}")
    return 0


def cmd_lag(args):
    """Ingest freshness one-pager: per-shard lag vs wall clock, replay-log
    offset/checkpoint lag, write-behind queue state, and rules watermark
    lag (``/api/v1/status/ingest``)."""
    import urllib.request
    with urllib.request.urlopen(
            f"http://{args.host}/api/v1/status/ingest") as r:
        d = json.load(r)["data"]
    if args.json:
        print(json.dumps(d, indent=2))
        return
    print(f"{'DATASET':<14} {'SHARD':>5} {'LAG_S':>8} {'OFFSET':>8} "
          f"{'LOG_LATEST':>10} {'OFF_LAG':>8} {'CKPT_LAG':>8}")
    for ds, doc in d["datasets"].items():
        for s in doc["shards"]:
            lag = s.get("ingestLagSeconds")
            print(f"{ds:<14} {s['shard']:>5} "
                  f"{('-' if lag is None else f'{lag:.1f}'):>8} "
                  f"{s['ingestedOffset']:>8} "
                  f"{str(s.get('logLatestOffset', '-')):>10} "
                  f"{str(s.get('offsetLag', '-')):>8} "
                  f"{str(s.get('checkpointLag', '-')):>8}")
    ob = d.get("objectstore", {})
    print(f"\nobjectstore: queue_depth={ob.get('queueDepth')} "
          f"oldest_task_age_s={ob.get('oldestTaskAgeSeconds', 0):.1f}")
    if "gatewayQueueDepth" in d:
        print(f"gateway: queue_depth={d['gatewayQueueDepth']}")
    for group, lag in sorted(d.get("rulesWatermarkLagSeconds",
                                   {}).items()):
        print(f"rules[{group}]: watermark_lag_s={lag:.1f}")
    slow = d.get("slowIngest", [])
    if slow:
        print(f"\nslow ingest operations (newest {len(slow)}):")
        for e in slow:
            print(f"  {e.get('kind', '?'):<12} "
                  f"{e.get('duration_ms', 0):>9.1f}ms "
                  + " ".join(f"{k}={e[k]}"
                             for k in ("dataset", "shard", "group", "op")
                             if e.get(k) is not None))


def cmd_shardmap(args):
    """Shard map with migration phases + per-tenant quota usage: one table
    answering "where is every shard, is anything moving, and which tenants
    are near their limits" (``/api/v1/cluster/{dataset}/shardmap``)."""
    import urllib.request
    url = f"http://{args.host}/api/v1/cluster/{args.dataset}/shardmap"
    with urllib.request.urlopen(url) as r:
        doc = json.load(r)["data"]
    print(f"{'SHARD':>5}  {'NODE':<16} {'STATUS':<10} {'WM':>8} "
          f"{'MIGRATION':<24} REPLICAS")
    for entry in doc.get("shards", []):
        mig = entry.get("migration")
        migs = (f"{mig['phase']} {mig['source']}->{mig['dest']} "
                f"lag={mig['lag']}" if mig else "-")
        reps = " ".join(
            f"{r['node']}:{r['status']}@{r.get('watermark', -1)}"
            for r in entry.get("replicas", [])) or "-"
        print(f"{entry['shard']:>5}  {str(entry.get('node')):<16} "
              f"{entry.get('status', '?'):<10} "
              f"{str(entry.get('watermark', '-')):>8} {migs:<24} {reps}")
    tenants = doc.get("tenants", [])
    if tenants:
        print(f"\n{'TENANT':<24} {'SERIES':>10} {'QUOTA':>10} "
              f"{'MAX_INFLIGHT':>12}")
        for t in tenants:
            quota = t["max_series"] or "-"
            infl = t["max_inflight"] or "-"
            print(f"{t['tenant']:<24} {t['active_series']:>10} "
                  f"{str(quota):>10} {str(infl):>12}")


def cmd_replicacheck(args):
    """Replica-divergence detector: compare each shard's leader watermark
    against its followers' applied offsets over the shardmap API; a
    follower trailing by more than ``--max-lag`` (or an IN_SYNC follower
    with no watermark at all) is a divergence and the command exits 1 —
    the filolint-style zero-divergence gate, runnable against a live
    cluster."""
    import urllib.request
    url = f"http://{args.host}/api/v1/cluster/{args.dataset}/shardmap"
    with urllib.request.urlopen(url) as r:
        doc = json.load(r)["data"]
    divergent = 0
    checked = 0
    print(f"{'SHARD':>5}  {'LEADER':<16} {'WM':>8}  "
          f"{'FOLLOWER':<16} {'STATUS':<10} {'WM':>8}  VERDICT")
    for entry in doc.get("shards", []):
        leader_wm = entry.get("watermark")
        for rep in entry.get("replicas", []):
            checked += 1
            rep_wm = rep.get("watermark", -1)
            if rep["status"] != "in_sync":
                verdict = f"skip ({rep['status']})"
            elif leader_wm is None:
                verdict = "skip (no leader watermark)"
            elif leader_wm - rep_wm > args.max_lag:
                verdict = f"DIVERGED (lag {leader_wm - rep_wm})"
                divergent += 1
            else:
                verdict = "ok"
            print(f"{entry['shard']:>5}  {str(entry.get('node')):<16} "
                  f"{str(leader_wm):>8}  {rep['node']:<16} "
                  f"{rep['status']:<10} {rep_wm:>8}  {verdict}")
    print(f"\n{checked} replica(s) checked, {divergent} divergent")
    return 1 if divergent else 0


def cmd_rules(args):
    """Standing-query status: every rule group's watermark plus per-rule
    health, and all active alerts with their state/activation time
    (``/api/v1/rules`` + ``/api/v1/alerts``)."""
    import urllib.request
    with urllib.request.urlopen(f"http://{args.host}/api/v1/rules") as r:
        groups = json.load(r)["data"]["groups"]
    if not groups:
        print("no rule groups configured")
        return
    for g in groups:
        wm = g.get("watermark")
        print(f"group {g['name']} dataset={g['dataset']} "
              f"interval={g['interval']}s watermark={wm if wm else '-'}")
        for rule in g.get("rules", []):
            print(f"  {rule['type']:<9} {rule['name']:<28} "
                  f"health={rule['health']:<8} {rule['query']}")
            if rule.get("lastError"):
                print(f"            lastError: {rule['lastError']}")
    with urllib.request.urlopen(f"http://{args.host}/api/v1/alerts") as r:
        alerts = json.load(r)["data"]["alerts"]
    print(f"\n{'ALERT':<28} {'STATE':<8} {'ACTIVE_AT':<26} LABELS")
    for a in alerts:
        labels = ",".join(f"{k}={v}" for k, v in sorted(a["labels"].items())
                          if k != "alertname")
        print(f"{a['labels'].get('alertname', '?'):<28} {a['state']:<8} "
              f"{a['activeAt']:<26} {labels}")
    if not alerts:
        print("(no active alerts)")


def cmd_slowlog(args):
    """Slow-query flight recorder dump: every query (or traced operation)
    that exceeded ``slow_query_threshold_ms``, newest first, with merged
    stats and — when sampled — the full distributed span tree
    (``/promql/{dataset}/api/v1/debug/slow_queries``)."""
    import urllib.request
    qs = f"?limit={args.limit}" if args.limit else ""
    url = (f"http://{args.host}/promql/{args.dataset}"
           f"/api/v1/debug/slow_queries{qs}")
    with urllib.request.urlopen(url) as r:
        entries = json.load(r)["data"]["slow_queries"]
    if not entries:
        print("(flight recorder empty)")
        return
    if args.json:
        print(json.dumps(entries, indent=2))
        return
    for e in entries:
        import datetime as dt
        when = dt.datetime.fromtimestamp(e.get("when", 0)) \
            .strftime("%Y-%m-%d %H:%M:%S")
        head = (f"{when}  {e.get('kind', 'query'):<10} "
                f"{e.get('duration_ms', 0):>9.1f}ms "
                f"sampled={str(e.get('sampled', False)).lower()}")
        if e.get("query"):
            head += f"  {e['query']}"
        print(head)
        for k in ("dataset", "group", "phase", "op"):
            if e.get(k):
                print(f"    {k}={e[k]}")
        stats = e.get("stats") or {}
        if stats:
            print("    stats: " + " ".join(
                f"{k}={v}" for k, v in sorted(stats.items()) if v))
        for s in e.get("spans", []):
            tags = " ".join(f"{k}={v}"
                            for k, v in sorted((s.get("tags") or {}).items()))
            print(f"    {'  ' * s.get('depth', 0)}"
                  f"{s['name']} {s.get('duration_ms', 0):.3f}ms"
                  + (f" [{tags}]" if tags else ""))


def cmd_coststats(args):
    """Adaptive-planner cost model dump: per-(site, signature, arm) online
    estimates with warm state, per-site calibration error, and recent
    predicted-vs-actual pairs
    (``/promql/{dataset}/api/v1/debug/costmodel``)."""
    import urllib.request
    qs = f"?limit={args.limit}" if args.limit else ""
    url = (f"http://{args.host}/promql/{args.dataset}"
           f"/api/v1/debug/costmodel{qs}")
    with urllib.request.urlopen(url) as r:
        snap = json.load(r)["data"]
    if args.json:
        print(json.dumps(snap, indent=2))
        return
    print(f"dataset={snap['dataset']} adaptive="
          f"{'on' if snap['enabled'] else 'off'} "
          f"signatures={snap['signatures']}/{snap['max_signatures']} "
          f"min_samples={snap['min_samples']}")
    calib = snap.get("calibration_error") or {}
    if calib:
        print("calibration error (EWMA |pred-actual|/actual):")
        for site, err in sorted(calib.items()):
            print(f"    {site:<10} {err:.3f}")
    rows = snap.get("estimates") or []
    if not rows:
        print("(no observations yet)")
        return
    print(f"{'site':<10} {'signature':<32} {'arm':<10} {'n':>5} "
          f"{'est_s':>10} {'p50_s':>10} {'p90_s':>10} warm")
    for row in rows:
        p50 = row["p50_s"]
        p90 = row["p90_s"]
        print(f"{row['site']:<10} {row['signature']:<32.32} "
              f"{row['arm']:<10} {row['n']:>5} {row['estimate_s']:>10.6f} "
              f"{p50 if p50 is None else format(p50, '10.6f')} "
              f"{p90 if p90 is None else format(p90, '10.6f')} "
              f"{'yes' if row['warm'] else 'no'}")


def cmd_indexnames(args):
    cs, meta, ms = _open_stores(args)
    from filodb_tpu.core.store.config import StoreConfig
    names = set()
    for shard in range(args.num_shards):
        s = ms.setup(args.dataset, shard, StoreConfig())
        s.recover_index()
        names.update(s.label_names())
    print("\n".join(sorted(names)))


def cmd_labelvalues(args):
    cs, meta, ms = _open_stores(args)
    from filodb_tpu.core.store.config import StoreConfig
    vals = set()
    for shard in range(args.num_shards):
        s = ms.setup(args.dataset, shard, StoreConfig())
        s.recover_index()
        vals.update(s.label_values(args.label))
    print("\n".join(sorted(vals)))


def cmd_importcsv(args):
    """CSV: timestamp_ms,value,label1=value1,label2=value2,..."""
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.core.partkey import METRIC_LABEL, PartKey
    from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
    from filodb_tpu.core.store.config import StoreConfig

    cs, meta, ms = _open_stores(args)
    for shard in range(args.num_shards):
        s = ms.setup(args.dataset, shard, StoreConfig())
        s.recover_index()
        s.setup_watermarks_for_recovery()
    container = RecordContainer()
    n = 0
    with open(args.file) as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#"):
                continue
            ts, value = int(row[0]), float(row[1])
            labels = {METRIC_LABEL: args.metric}
            for pair in row[2:]:
                k, v = pair.split("=", 1)
                labels[k] = v
            container.add(IngestRecord(PartKey.create("gauge", labels), ts,
                                       (value,)))
            n += 1
            if len(container) >= 1000:
                ingest_routed(ms, args.dataset, [SomeData(container, n)],
                              args.num_shards, args.spread)
                container = RecordContainer()
    if len(container):
        ingest_routed(ms, args.dataset, [SomeData(container, n)],
                      args.num_shards, args.spread)
    for s in ms.shards_for(args.dataset):
        s.flush_all()
    # drain write-behind uploads (object store) before the process exits
    cs.close()
    meta.close()
    print(f"imported {n} samples")


def cmd_promql(args):
    if args.host:
        import urllib.parse
        import urllib.request
        qs = urllib.parse.urlencode({
            "query": args.promql, "start": args.start, "end": args.end,
            "step": args.step})
        url = (f"http://{args.host}/promql/{args.dataset}/api/v1/"
               f"query_range?{qs}")
        with urllib.request.urlopen(url) as r:
            print(json.dumps(json.load(r), indent=2))
        return
    from filodb_tpu.coordinator.query_service import QueryService
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.http.promjson import matrix_json

    cs, meta, ms = _open_stores(args)
    for shard in range(args.num_shards):
        s = ms.setup(args.dataset, shard, StoreConfig())
        s.recover_index()
    svc = QueryService(ms, args.dataset, args.num_shards, args.spread)
    r = svc.query_range(args.promql, args.start, args.step, args.end)
    print(json.dumps(matrix_json(r), indent=2))


def cmd_validate(args):
    """Validate schema definitions (reference ``validateSchemas`` command)."""
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS

    for s in DEFAULT_SCHEMAS.all:
        cols = ", ".join(f"{c.name}:{c.ctype.value}"
                         + ("(counter)" if c.is_counter else "")
                         for c in s.data.columns)
        ds = f" -> {s.data.downsample_schema}" if s.data.downsample_schema \
            else ""
        print(f"{s.name} (id={s.schema_id}): {cols}{ds}")
        if s.data.downsamplers:
            print(f"  downsamplers: {', '.join(s.data.downsamplers)}")
    print(f"{len(DEFAULT_SCHEMAS.all)} schemas OK (no id clashes)")


def cmd_topkcard(args):
    """Top-k cardinality under a shard-key prefix (reference ``topkcard``):
    counts persisted part keys grouped by the next shard-key level."""
    from collections import Counter

    cs, _, _ = _open_stores(args)
    prefix = [p for p in (args.prefix or "").split("/") if p]
    labels = ("_ws_", "_ns_", "_metric_")
    counts = Counter()
    for shard in range(args.num_shards):
        for rec in cs.scan_part_keys(args.dataset, shard):
            lm = rec.part_key.label_map
            path = [lm.get(k, "") for k in labels]
            if path[: len(prefix)] == prefix:
                child = (path[len(prefix)] if len(prefix) < len(path)
                         else path[-1])
                counts[child] += 1
    for name, n in counts.most_common(args.k):
        print(f"{name}\tseries={n}")


def cmd_decode_chunk(args):
    """Debug: decode and dump a partition's chunk info + samples (reference
    ``decodeChunkInfo`` / ``decodeVector`` commands)."""
    cs, meta, ms = _open_stores(args)
    from filodb_tpu.memory.codecs import HistogramColumn
    for shard in range(args.num_shards):
        for rec in cs.scan_part_keys(args.dataset, shard):
            if args.filter and args.filter not in str(rec.part_key):
                continue
            chunks = cs.read_chunks(args.dataset, shard, rec.part_key,
                                    0, 2**62)
            print(f"partition {rec.part_key} shard={shard}: "
                  f"{len(chunks)} chunks")
            for c in chunks[: args.limit]:
                print(f"  chunk id={c.id} rows={c.num_rows} "
                      f"[{c.start_time}..{c.end_time}] bytes={c.nbytes}")
                if args.verbose:
                    ts = c.decode_column(0)
                    print(f"    ts[:5]={ts[:5]}")
                    for ci in range(1, len(c.vectors)):
                        vals = c.decode_column(ci)
                        codec_id = c.vectors[ci][0]
                        if isinstance(vals, HistogramColumn):
                            print(f"    col{ci} codec={codec_id} hist "
                                  f"les={vals.les} rows[:2]={vals.rows[:2]}")
                        elif isinstance(vals, list):  # strings or maps
                            print(f"    col{ci} codec={codec_id} "
                                  f"vals[:5]={vals[:5]}")
                        else:
                            print(f"    col{ci} codec={codec_id} "
                                  f"vals[:5]={np.asarray(vals)[:5]}")


def cmd_promfilter_to_partkey(args):
    """Forensics: turn a PromQL series selector into the part-key bytes the
    ingestion path would produce (reference ``CliMain.scala:100-108``
    ``promFilterToPartKeyBR``), plus its hashes and owning shard.  With
    ``--lookup``, scans the opened ColumnStore (any backend, including the
    object store) for persisted part keys matching the filter."""
    from filodb_tpu.core.partkey import METRIC_LABEL, PartKey, ingestion_shard
    from filodb_tpu.promql.parser import TimeStepParams, parse_query

    plan = parse_query(args.promfilter, TimeStepParams(0, 60, 0))
    raw = plan
    while not hasattr(raw, "filters"):
        raw = raw.raw
    labels = {}
    for f in raw.filters:
        cond = f.filter
        if type(cond).__name__ != "Equals":
            print(f"error: only equality filters map to a part key "
                  f"(got {type(cond).__name__} on {f.column})",
                  file=sys.stderr)
            return 1
        labels[f.column] = cond.value
    if METRIC_LABEL not in labels:
        print("error: selector needs a metric name", file=sys.stderr)
        return 1
    pk = PartKey.create(args.schema, labels)
    skh = pk.shard_key_hash(("_ws_", "_ns_", METRIC_LABEL))
    shard = ingestion_shard(skh, pk.part_hash, args.num_shards, args.spread)
    print(f"partKey      {pk}")
    print(f"schema       {pk.schema}")
    print(f"bytes (hex)  {pk.serialized.hex()}")
    print(f"partHash     {pk.part_hash:#010x}")
    print(f"shardKeyHash {skh:#010x}")
    print(f"shard        {shard}  (numShards={args.num_shards} "
          f"spread={args.spread})")
    if args.lookup:
        cs, _, _ = _open_stores(args)
        want = set(labels.items())
        hits = 0
        for sh in range(args.num_shards):
            for rec in cs.scan_part_keys(args.dataset, sh):
                if want <= set(rec.part_key.labels):
                    hits += 1
                    print(f"  persisted shard={sh} {rec.part_key} "
                          f"[{rec.start_time}, {rec.end_time}]")
        print(f"  {hits} persisted partition(s) match")
    return 0


def cmd_partkey_as_string(args):
    """Forensics: decode serialized part-key bytes (hex) back to a readable
    key (reference ``CliMain.scala:110-115`` ``partKeyBrAsString``)."""
    from filodb_tpu.core.partkey import METRIC_LABEL, ingestion_shard
    from filodb_tpu.core.store.localstore import _pk_from_blob

    try:
        blob = bytes.fromhex(args.hexkey.strip().removeprefix("0x"))
        pk = _pk_from_blob(blob)
    except ValueError as e:
        print(f"error: not a valid part-key blob: {e}", file=sys.stderr)
        return 1
    skh = pk.shard_key_hash(("_ws_", "_ns_", METRIC_LABEL))
    print(f"partKey      {pk}")
    print(f"schema       {pk.schema}")
    for k, v in pk.labels:
        print(f"  {k} = {v}")
    print(f"partHash     {pk.part_hash:#010x}")
    print(f"shardKeyHash {skh:#010x}")
    print(f"shard        "
          f"{ingestion_shard(skh, pk.part_hash, args.num_shards, args.spread)}"
          f"  (numShards={args.num_shards} spread={args.spread})")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="filo-cli")
    ap.add_argument("--data-dir", default="./filodb-data")
    ap.add_argument("--dataset", default="timeseries")
    ap.add_argument("--num-shards", type=int, default=4)
    ap.add_argument("--spread", type=int, default=1)
    ap.add_argument("--host", default=None,
                    help="host:port of a running server (remote mode)")
    ap.add_argument("--store", choices=("local", "object"), default="local",
                    help="ColumnStore backend to open in embedded mode")
    ap.add_argument("--endpoint", default=None,
                    help="object-store endpoint (http(s)://… for S3, "
                         "else a local directory)")
    ap.add_argument("--bucket", default="filodb")
    sub = ap.add_subparsers(dest="command", required=True)

    sub.add_parser("init")
    p = sub.add_parser("list")
    p.add_argument("--limit", type=int, default=20)
    p = sub.add_parser("status")
    p.add_argument("-k", type=int, default=10,
                   help="top-k cardinality entries in the TSDB summary")
    p = sub.add_parser("lag")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the formatted table")
    p = sub.add_parser("tiers")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the formatted table")
    p = sub.add_parser("meshstat")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the formatted table")
    sub.add_parser("shardmap")
    p = sub.add_parser("replicacheck")
    p.add_argument("--max-lag", type=int, default=0,
                   help="offsets a follower may trail the leader by")
    sub.add_parser("rules")
    p = sub.add_parser("slowlog")
    p.add_argument("--limit", type=int, default=0,
                   help="newest N entries (0 = everything retained)")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the formatted table")
    p = sub.add_parser("coststats")
    p.add_argument("--limit", type=int, default=0,
                   help="top N estimate rows (0 = everything retained)")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the formatted table")
    sub.add_parser("indexnames")
    p = sub.add_parser("labelvalues")
    p.add_argument("label")
    p = sub.add_parser("importcsv")
    p.add_argument("file")
    p.add_argument("--metric", required=True)
    p = sub.add_parser("promql")
    p.add_argument("promql")
    p.add_argument("--start", type=int, required=True)
    p.add_argument("--end", type=int, required=True)
    p.add_argument("--step", type=int, default=60)
    p = sub.add_parser("decodechunks")
    p.add_argument("--filter", default=None)
    p.add_argument("--limit", type=int, default=5)
    p.add_argument("--verbose", action="store_true")
    p = sub.add_parser("topkcard")
    p.add_argument("--prefix", default="", help="ws or ws/ns")
    p.add_argument("-k", type=int, default=10)
    sub.add_parser("validate")
    p = sub.add_parser("promfilter-to-partkey")
    p.add_argument("promfilter", help='e.g. \'heap_usage{_ws_="demo"}\'')
    p.add_argument("--schema", default="gauge")
    p.add_argument("--lookup", action="store_true",
                   help="scan the store for matching persisted part keys")
    p = sub.add_parser("partkey-as-string")
    p.add_argument("hexkey", help="serialized part-key bytes, hex")

    args = ap.parse_args(argv)
    return {"init": cmd_init, "list": cmd_list, "status": cmd_status,
            "lag": cmd_lag, "tiers": cmd_tiers, "meshstat": cmd_meshstat,
            "shardmap": cmd_shardmap, "replicacheck": cmd_replicacheck,
            "rules": cmd_rules,
            "slowlog": cmd_slowlog,
            "coststats": cmd_coststats,
            "indexnames": cmd_indexnames, "labelvalues": cmd_labelvalues,
            "importcsv": cmd_importcsv, "promql": cmd_promql,
            "decodechunks": cmd_decode_chunk, "topkcard": cmd_topkcard,
            "validate": cmd_validate,
            "promfilter-to-partkey": cmd_promfilter_to_partkey,
            "partkey-as-string": cmd_partkey_as_string,
            }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
