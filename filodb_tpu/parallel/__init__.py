"""Distributed execution over a jax.sharding.Mesh.

The TPU-native replacement for the reference's distributed communication
backend (SURVEY.md §2.14: Akka Cluster + remoting + Kryo-serialized ExecPlan
shipping): intra-query distribution is expressed as SPMD programs over a
``Mesh`` with XLA collectives —

- axis ``"shard"``: data parallelism over series (the reference's shard
  partitioning P1) — cross-shard aggregation via ``psum`` riding ICI;
- axis ``"time"``: sequence parallelism over the sample/time dimension (the
  reference's temporal-splitting axis P5) — windows crossing block boundaries
  are reconciled by exchanging tiny per-step partial summaries
  (``all_gather`` over the time axis), the TSDB analog of ring-attention's
  halo exchange.
"""
