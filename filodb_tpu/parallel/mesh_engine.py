"""Device-mesh query engine: PromQL plans lowered onto SPMD mesh kernels.

The reference distributes queries by shipping exec-plan subtrees to
shard-owning nodes and gathering partial aggregates over the network
(``query/src/main/scala/filodb/query/exec/ExecPlan.scala:41``,
``PlanDispatcher.scala:31``). On a TPU pod the same computation is ONE SPMD
program over a ``(shard, time)`` ``jax.sharding.Mesh``: series are
data-parallel over the ``shard`` axis, samples sequence-parallel over the
``time`` axis, label-group reduction is a ``segment_sum`` + ``psum`` over
ICI (see ``parallel/dist_query.py`` for the kernels).

This module is the bridge from the query engine. ``MeshQueryEngine`` lowers
the plan family

    [instant-fn | scalar-op | topk]* agg?(range_fn(selector[w] offset o))
                                      by/without (labels)

— range functions with associative time combines, all aggregate ops with
associative series combines, raw/un-aggregated selectors (per-series [P, K]
output sharded over the mesh), instant-selector staleness semantics, offsets,
and instant-function / scalar-op post-transforms applied to the (tiny) mesh
output. ``execute_many`` additionally batches several lowered queries that
share a plan signature into ONE device program by concatenating their step
grids — the serving-side analog of inference micro-batching (the reference's
``QueryInMemoryBenchmark`` drives 100 concurrent queries of 4 shapes).

``QueryService(engine="mesh")`` tries this engine first and falls back to the
scatter-gather exec tree for every other plan shape.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.parallel.dist_query import (
    MESH_AGG_OPS,
    SPLIT_FNS,
    make_mesh_bounds,
    make_mesh_eval_delta,
    make_mesh_eval_simple,
    make_mesh_group_reduce,
    make_mesh_prepare,
)
from filodb_tpu.query import logical as lp
from filodb_tpu.query.model import QueryStats, RangeVectorKey, StepMatrix
from filodb_tpu.utils.metrics import GaugeFn, get_counter

log = logging.getLogger(__name__)

# mesh-engine observability: plan recognition, dispatch form, cache
# behavior, and adaptive lane routing (tests/test_metrics_scrape.py pins
# these families). Registered eagerly so a scrape sees the families even
# before the first mesh query.
_M_SUPPORTED = get_counter(
    "filodb_mesh_supported", help="plans recognized for mesh execution")
_M_UNSUPPORTED = get_counter(
    "filodb_mesh_unsupported", help="plans that fell back to the exec path "
    "at recognition time")
_M_DISPATCH = {f: get_counter("filodb_mesh_dispatch", {"form": f},
                              help="mesh batch dispatches by kernel form "
                              "(split pipeline vs fused one-shot)")
               for f in ("split", "fused")}
_M_COMPILE = {e: get_counter("filodb_mesh_compile_cache", {"event": e},
                             help="compiled mesh program cache hits/misses")
              for e in ("hit", "miss")}
_M_BATCH = {e: get_counter("filodb_mesh_batch_cache", {"event": e},
                           help="decoded+placed batch cache hits/misses")
            for e in ("hit", "miss")}
_M_BOUNDS = {e: get_counter("filodb_mesh_bounds_cache", {"event": e},
                            help="cached window-bounds (searchsorted) "
                            "hits/misses on the split pipeline")
             for e in ("hit", "miss")}
_M_EVAL = {e: get_counter("filodb_mesh_eval_cache", {"event": e},
                          help="cached per-series window evaluation "
                          "hits/misses on the split pipeline")
           for e in ("hit", "miss")}
_M_FALLBACK = {r: get_counter("filodb_mesh_fallback", {"reason": r},
                              help="mesh dispatches that fell back to the "
                              "exec path after recognition")
               for r in ("declined", "error", "shards")}
_M_ROUTED = {la: get_counter("filodb_mesh_routed", {"lane": la},
                             help="adaptive engine lane routing decisions")
             for la in ("device", "single", "host")}
GaugeFn("filodb_mesh_hit_rate",
        lambda: _M_SUPPORTED.value / t
        if (t := _M_SUPPORTED.value + _M_UNSUPPORTED.value) else 0.0,
        help="fraction of inspected plans the mesh engine recognized")

# f32 device arithmetic keeps ≥4 fractional bits of absolute precision for
# values below 2^20 (ulp ≤ 2^-4 = 0.0625); above that, counter deltas and
# gauge cancellation degrade and the f64 host pre-correction lane
# (SeriesBatch.delta_host) takes over. Well under the 2^24 integer-exact
# limit, so integral counters are bit-exact either way.
F32_SAFE_MAX = float(1 << 20)


def _device_correction_ok(vals: np.ndarray) -> bool:
    """May counter-reset correction / delta cancellation run directly on
    the device value dtype? Always under x64; under f32, only when every
    finite value is small enough that window-scale differences keep
    absolute precision (see ``F32_SAFE_MAX``). One host pass per decoded
    batch — amortized across every query the cached batch serves."""
    import jax.numpy as jnp

    from filodb_tpu.query.engine.kernels import fdtype

    if fdtype() == jnp.float64:
        return True
    finite = vals[np.isfinite(vals)]
    return finite.size == 0 or float(np.abs(finite).max()) < F32_SAFE_MAX

# range functions with associative mesh combines (dist_query kernels)
MESH_FNS = ("rate", "increase", "delta", "sum_over_time", "count_over_time",
            "avg_over_time", "min_over_time", "max_over_time",
            "last_over_time", "present_over_time", "stddev_over_time",
            "stdvar_over_time")
MESH_AGGS = MESH_AGG_OPS

# value-wise instant functions safe to post-apply on the [G, K] mesh output
_POST_INSTANT_FNS = (
    "abs", "ceil", "floor", "exp", "ln", "log2", "log10", "sqrt", "round",
    "clamp", "clamp_min", "clamp_max", "sgn", "deg", "rad", "acos", "asin",
    "atan", "cos", "cosh", "sin", "sinh", "tan", "tanh",
)


def _replace(low: _Lowered, **kw) -> _Lowered:
    import dataclasses
    return dataclasses.replace(low, **kw)


def _replace_post(low: _Lowered, op: tuple) -> _Lowered:
    return _replace(low, post=low.post + (op,))


def make_query_mesh(n_devices: int | None = None, time_axis: int | None = None):
    """Build the default (shard × time) mesh over available devices.

    ``time_axis``: devices on the sample axis (sequence parallelism); default
    2 when the device count allows, else 1 — series parallelism usually
    dominates for TSDB workloads (P >> S blocks).
    """
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if time_axis is None:
        time_axis = 2 if n % 2 == 0 and n >= 2 else 1
    shard_axis = n // time_axis
    return Mesh(np.array(devs[: shard_axis * time_axis]).reshape(
        shard_axis, time_axis), ("shard", "time"))


@dataclass(frozen=True)
class _Lowered:
    """A plan recognized for mesh execution."""

    filters: tuple
    start: int
    step: int
    end: int
    window: int
    fn: str
    offset: int
    agg: str | None
    by: tuple
    without: tuple
    keep_metric: bool
    # post-transforms applied to the mesh output StepMatrix, innermost first:
    # ("instant", fn, args) | ("scalarop", op, scalar, lhs, bool)
    # | ("kagg", op, params, by, without)
    post: tuple = ()

    @property
    def signature(self):
        """Batching key: everything except the step grid and post ops."""
        return (self.filters, self.window, self.fn, self.offset, self.agg,
                self.by, self.without, self.keep_metric, self.step)


@dataclass
class MeshQueryEngine:
    """Compiles + caches distributed query steps per (fn, agg, G-bucket).

    Shapes bucket to powers of two (series count, sample count, step count,
    group count) so repeated queries reuse compiled programs — the mesh
    analog of the exec path's batch-shape bucketing.
    """

    mesh: object = None
    variant: str = "gather"  # or "ring" (ppermute time combine)
    # prepare-stage sidecar delegation (engine/sidecar_lane.py): tick-shaped
    # grids (K ≤ 2 steps — rule ticks and alert probes evaluate at a single
    # instant) over eligible range functions are declined here so the exec
    # leaf folds them from chunk aggregate sidecars in O(chunks) —
    # per-evaluation device prep (decode + upload) never amortizes at K≈1.
    # Wider grids keep the device pipeline and its warm split caches. Off by
    # default so direct-constructed engines keep the pure device path;
    # QueryService turns it on for production-facing engines.
    sidecars: bool = False

    _fns: dict = field(default_factory=dict)
    # decoded global batches are reused across queries over unchanged data
    # (the mesh analog of the exec path's per-shard batch cache)
    _batch_cache: dict = field(default_factory=dict)
    _batch_cache_cap: int = 16
    # step-grid device arrays keyed by their bytes: repeated queries
    # re-upload identical grids every batch otherwise (a host→device
    # transfer per chunk — ~one tunnel RTT each on the axon backend)
    _grid_cache: dict = field(default_factory=dict)
    _grid_cache_cap: int = 64
    # split-pipeline device caches: prepared per-batch-version arrays
    # (counter correction / prefix sums), window bounds per (batch
    # version, grid, window), and per-series evaluated windows per (batch
    # version, grid, window, fn) — the passes that otherwise dominate a
    # warm query's device time (see dist_query "split pipeline" section).
    # Caps are deliberately small: entries scale with the batch (bounds
    # ~2·P·K int32, eval ~P·K float), so a handful of distinct dashboards
    # already costs hundreds of MB at big-scan sizes.
    _prep_cache: dict = field(default_factory=dict)
    _prep_cache_cap: int = 4
    _bounds_cache: dict = field(default_factory=dict)
    _bounds_cache_cap: int = 16
    _eval_cache: dict = field(default_factory=dict)
    _eval_cache_cap: int = 32
    # mesh-hit accounting (VERDICT r2 #4: logged mesh-hit rate)
    hits: int = 0
    misses: int = 0

    def _ensure_mesh(self):
        """Build the default mesh lazily on first use: ``jax.devices()``
        can hang or fail while an accelerator tunnel is down, and a server
        that is never mesh-eligible must not pay (or crash on) device
        init at startup."""
        if self.mesh is None:
            self.mesh = make_query_mesh()
        return self.mesh

    # ---- plan recognition ------------------------------------------------

    def supports(self, plan) -> bool:
        ok = self._lower(plan) is not None
        self._note(ok)
        return ok

    def _note(self, ok: bool) -> None:
        if ok:
            self.hits += 1
            _M_SUPPORTED.inc()
        else:
            self.misses += 1
            _M_UNSUPPORTED.inc()

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def _lower(self, plan) -> _Lowered | None:
        low = self._lower_plan(plan)
        if low is not None and self.sidecars \
                and (low.end - low.start) // max(low.step, 1) + 1 <= 2:
            from filodb_tpu.query.engine import sidecar_lane
            if sidecar_lane.covers_fn(low.fn):
                return None  # sidecar delegation (see ``sidecars`` field)
        return low

    def _lower_plan(self, plan) -> _Lowered | None:
        """Recognize a plan for mesh execution (None = exec-path fallback)."""
        # wrappers peel off into post-transforms (applied to the small
        # [G|P, K] mesh output, so any value-wise op is safe)
        if isinstance(plan, lp.ApplyInstantFunction) \
                and plan.function in _POST_INSTANT_FNS \
                and all(isinstance(a, (int, float)) for a in plan.args):
            inner = self._lower(plan.vector)
            if inner is None:
                return None
            return _replace_post(inner, ("instant", plan.function,
                                         tuple(plan.args)))
        if isinstance(plan, lp.ApplyInstantFunction) \
                and plan.function == "histogram_quantile":
            # histogram_quantile(φ, sum(rate(hist[5m])) by (...)) runs
            # fully on the mesh: bucket-rate partials are associative, so
            # buckets flatten into the series axis (see
            # execute_lowered_many) and the quantile is a tiny [G, K, B]
            # post-transform (reference first-class-histogram query path,
            # ``HistogramQuantileMapper`` + README.md:437 claim)
            args = [a.value if isinstance(a, lp.ScalarFixedDoublePlan)
                    else a for a in plan.args]
            if len(args) != 1 or not isinstance(args[0], (int, float)):
                return None
            inner = self._lower(plan.vector)
            if inner is None:
                return None
            return _replace_post(inner, ("instant", "histogram_quantile",
                                         (float(args[0]),)))
        if isinstance(plan, lp.ScalarVectorBinaryOperation):
            sc = plan.scalar
            if isinstance(sc, lp.ScalarFixedDoublePlan):
                sc = sc.value
            if isinstance(sc, (int, float)):
                inner = self._lower(plan.vector)
                if inner is None:
                    return None
                return _replace_post(inner, ("scalarop", plan.op, float(sc),
                                             plan.scalar_is_lhs,
                                             plan.bool_mode))
            return None
        if isinstance(plan, lp.Aggregate) and plan.op in ("topk", "bottomk") \
                and len(plan.params) == 1:
            inner = self._lower(plan.vector)
            if inner is None or inner.post:
                return None
            return _replace_post(inner, ("kagg", plan.op, plan.params,
                                         plan.by, plan.without))
        if isinstance(plan, lp.Aggregate):
            if plan.op not in MESH_AGGS or plan.params:
                return None
            core = self._lower_periodic(plan.vector)
            if core is None or core.agg is not None:
                return None
            return _replace(core, agg=plan.op, by=tuple(plan.by),
                            without=tuple(plan.without))
        return self._lower_periodic(plan)

    def _lower_periodic(self, plan) -> _Lowered | None:
        if isinstance(plan, lp.PeriodicSeriesWithWindowing):
            if plan.function not in MESH_FNS or plan.params \
                    or plan.at_ms is not None:
                return None
            raw = plan.raw
            if not isinstance(raw, lp.RawSeries) or raw.column is not None:
                return None
            # the parser records the selector offset on BOTH the periodic
            # node and the raw selector — one value, not additive
            return _Lowered(tuple(raw.filters), plan.start, plan.step,
                            plan.end, plan.window, plan.function,
                            plan.offset or raw.offset, None, (), (), False)
        if isinstance(plan, lp.PeriodicSeries):
            if plan.at_ms is not None:
                return None
            raw = plan.raw
            if not isinstance(raw, lp.RawSeries) or raw.column is not None:
                return None
            lookback = raw.lookback or 300_000
            return _Lowered(tuple(raw.filters), plan.start, plan.step,
                            plan.end, lookback, "last_sample",
                            plan.offset or raw.offset, None, (), (), True)
        return None

    # ---- execution -------------------------------------------------------

    def execute(self, memstore, dataset: str, plan,
                stats: QueryStats | None = None) -> StepMatrix | None:
        """Run a supported plan on the mesh; ``None`` = fall back to the
        exec path (histogram data or other shapes the kernels don't cover).
        """
        low = self._lower(plan)
        if low is None:
            return None
        out = self.execute_lowered_many([low], memstore, dataset, stats)
        return out[0]

    def execute_many(self, plans: list, memstore, dataset: str,
                     stats_list: list | None = None) -> list:
        """Evaluate many plans, batching those that share a signature into
        one device program (concatenated step grids). Returns a StepMatrix
        (or None = unsupported) per plan, in order."""
        lows = [self._lower(p) for p in plans]
        results: list = [None] * len(plans)
        groups: dict[tuple, list[int]] = {}
        for i, low in enumerate(lows):
            self._note(low is not None)
            if low is not None:
                groups.setdefault(low.signature, []).append(i)
        for idxs in groups.values():
            outs = self.execute_lowered_many(
                [lows[i] for i in idxs], memstore, dataset,
                [stats_list[i] for i in idxs] if stats_list else None)
            for i, out in zip(idxs, outs):
                results[i] = out
        return results

    def execute_lowered_many(self, lows: list[_Lowered], memstore,
                             dataset: str,
                             stats: "QueryStats | list | None" = None
                             ) -> list:
        """Evaluate lowered plans sharing a signature (same selector/fn/agg;
        step grids may differ) in ONE mesh program. Returns one StepMatrix
        (or None) per entry. ``stats`` is one QueryStats (single query) or a
        list aligned with ``lows`` — every query in the group scanned the
        whole shared batch, so each gets the full scan counts."""
        stats_objs = stats if isinstance(stats, list) \
            else ([stats] if stats is not None else [])
        from filodb_tpu.parallel.dist_query import (
            make_distributed_range_agg,
            make_distributed_sum_rate_ring,
            pad_for_mesh,
            shard_batch_arrays,
        )
        from filodb_tpu.query.engine.batch import build_batch
        from filodb_tpu.query.engine.device_batch import _pow2
        from filodb_tpu.query.exec.transformers import steps_array

        low0 = lows[0]
        mesh = self._ensure_mesh()
        fn = "last_over_time" if low0.fn == "last_sample" else low0.fn
        # union data range across the batch (offset shifts evaluation back)
        chunk_start = min(lo.start for lo in lows) - low0.window - low0.offset
        chunk_end = max(lo.end for lo in lows) - low0.offset

        shards = memstore.shards_for(dataset)
        version = sum(s.data_version for s in shards)
        # split pipeline (prepare/bounds/step, dist_query.py): correction
        # and window bounds are cached on device across queries instead of
        # recomputed per call. Window min/max have no prefix form and the
        # ring variant is a fused-only memory optimization — both keep the
        # fused kernels. FILODB_MESH_SPLIT=0 is the safety valve (also how
        # benchmarks measure the pre-split baseline).
        use_split = (self.variant != "ring" and fn in SPLIT_FNS
                     and os.environ.get("FILODB_MESH_SPLIT", "1") != "0")
        # delta-family fns place the pre-corrected/rebased f64→f32 value
        # lane (SeriesBatch.delta_host) instead of raw values, so the lane
        # kind is part of the cache key ("corrected" also implies counter
        # reset correction; "rebased" is shift-only, for delta on gauges —
        # delta on a COUNTER schema is reset-corrected too, mirroring the
        # exec transformers, decided once the matched schema is known).
        # On the split pipeline ("split" lane) the correction instead runs
        # ON DEVICE over the raw placed values whenever the batch's
        # magnitudes make that safe (_device_correction_ok) — the host
        # pre-pass survives only as the big-magnitude fallback.
        lane = ("split" if use_split and fn in ("rate", "increase", "delta")
                else "corrected" if fn in ("rate", "increase")
                else "rebased" if fn == "delta" else "raw")
        # the agg NAME is part of the key (not just agg-vs-none): a
        # histogram batch cached under sum must not satisfy a later
        # min/max/avg over the same selector — those fall back to the
        # exec path, and the cache-hit branch must re-make that decision
        ckey = (dataset, str(low0.filters), chunk_start, chunk_end,
                low0.by, low0.without, low0.agg, lane)
        # split-pipeline device caches (prepare/bounds/eval) consume only
        # the data tensors, never the grouping — keyed WITHOUT agg/by so
        # e.g. sum() and avg() over the same rate() share one evaluation
        dkey = (dataset, str(low0.filters), chunk_start, chunk_end, lane)
        cached = self._batch_cache.get(ckey)
        _M_BATCH["hit" if cached is not None and cached[0] == version
                 else "miss"].inc()
        if cached is not None and cached[0] == version:
            _, batch, keys, gids, out_keys, placed, is_counter = cached
            if batch is None:
                return [StepMatrix.empty(steps_array(lo.start, lo.step,
                                                     lo.end))
                        for lo in lows]
            if batch.is_histogram and low0.agg not in (None, "sum"):
                return [None] * len(lows)
            for st in stats_objs:
                st.series_scanned += len(keys)
                st.samples_scanned += int(batch.counts.sum())
        else:
            placed = None
            parts = []
            extra_by_obj: dict[int, list] = {}
            for shard in shards:
                sparts = []
                for pid in shard.lookup_partitions(list(low0.filters),
                                                   chunk_start, chunk_end):
                    p = shard.partition(pid)
                    if p is not None:
                        sparts.append(p)
                # on-demand paging: cold chunks (flushed + evicted-from-RAM,
                # or pre-restart data recovered only to the column store) are
                # merged exactly like the exec path does (plan.py) — keyed by
                # object identity because part_ids repeat across shards
                if sparts and shard.config.demand_paging_enabled:
                    from filodb_tpu.core.memstore.odp import page_partitions
                    extra = page_partitions(shard, sparts, chunk_start,
                                            chunk_end, shard.odp_cache)
                    if extra:
                        for p in sparts:
                            ec = extra.get(p.part_id)
                            if ec:
                                extra_by_obj[id(p)] = ec
                parts.extend(sparts)
            if not parts:
                self._cache_put(ckey, (version, None, [], None, [], None,
                                       False))
                return [StepMatrix.empty(steps_array(lo.start, lo.step,
                                                     lo.end))
                        for lo in lows]
            batch = build_batch(parts, chunk_start, chunk_end,
                                extra_by_obj=extra_by_obj or None)
            # counter-ness of the scanned value column (same source the
            # exec path reads): decides delta's reset-correction semantics
            sdata = parts[0].schema.data
            is_counter = bool(sdata.columns[sdata.value_column].is_counter)
            if batch.is_histogram and low0.agg not in (None, "sum"):
                # bucket-wise semantics only defined for sum (and raw)
                return [None] * len(lows)
            for st in stats_objs:
                st.series_scanned += len(parts)
                st.samples_scanned += int(batch.counts.sum())
            # label grouping (first-occurrence order, like
            # AggregateMapReduce). The metric label is dropped first — the
            # exec path drops it in range-function output keys before
            # grouping, so `by (_metric_)` must group on nothing there too.
            keys = [p.part_key.range_vector_key for p in parts]
            if low0.agg is None:
                gids = np.zeros(len(keys), np.int32)
                out_keys = []
            else:
                gkeys = [self._group_key(k, low0) for k in keys]
                uniq: dict[RangeVectorKey, int] = {}
                gids = np.empty(len(gkeys), np.int32)
                for i, gk in enumerate(gkeys):
                    gids[i] = uniq.setdefault(gk, len(uniq))
                out_keys = list(uniq.keys())
        # histogram batches flatten buckets into the series axis: every
        # (series, bucket) pair becomes one scalar row, group ids become
        # g*B + b, and the same associative kernels/combines apply. The
        # output un-flattens to [rows, K, B].
        B = batch.vals.shape[2] \
            if (batch is not None and batch.is_histogram) else 1
        # delta mirrors the exec kernels: reset-corrected on counter
        # schemas, raw differences on gauges (rate/increase always correct)
        delta_counter = fn == "delta" and is_counter
        G = len(out_keys)
        Gp = _pow2(max(G * B, 1))

        # per-plan step grids, each padded to a power of two for compile
        # reuse (window evaluations are independent per step — batching
        # queries = concatenating steps)
        all_steps = []
        spans = []
        for lo in lows:
            steps_ms = steps_array(lo.start, lo.step, lo.end)
            K = len(steps_ms)
            Kp = _pow2(K)
            rel = np.empty(Kp, np.int32)
            rel[:K] = (steps_ms - lo.offset - batch.base_ts).astype(np.int32)
            rel[K:] = rel[K - 1]
            spans.append((Kp, K, steps_ms))
            all_steps.append(rel)

        if placed is None:
            gids_full = np.zeros(batch.ts.shape[0], np.int32)
            gids_full[: len(gids)] = gids
            raw_vals = None
            if lane == "raw":
                mesh_vals = batch.vals
            elif lane == "split" and _device_correction_ok(batch.vals):
                # raw values go straight to the device; the counter
                # correction is fused into the cached prepare program
                # (make_mesh_prepare), so no host pre-pass runs at all
                mesh_vals = batch.vals
            else:
                counter = fn in ("rate", "increase") or delta_counter
                mesh_vals = batch.delta_host(counter=counter)
                if fn in ("rate", "increase"):
                    # rate/increase also need the raw values for the
                    # extrapolate-to-zero clamp (heuristic-only reference;
                    # delta never clamps, even when reset-corrected)
                    raw_vals = batch.vals
            bt_ts, bt_counts = batch.ts, batch.counts
            if B > 1:
                Pp_, S_ = bt_ts.shape
                mesh_vals = np.ascontiguousarray(
                    mesh_vals.transpose(0, 2, 1)).reshape(Pp_ * B, S_)
                if raw_vals is not None:
                    raw_vals = np.ascontiguousarray(
                        raw_vals.transpose(0, 2, 1)).reshape(Pp_ * B, S_)
                bt_ts = np.repeat(bt_ts, B, axis=0)
                bt_counts = np.repeat(bt_counts, B)
                gids_full = (gids_full[:, None] * B + np.arange(
                    B, dtype=np.int32)[None, :]).reshape(-1)
            ts_p, vals_p, valid, gid_p = pad_for_mesh(
                bt_ts, mesh_vals, bt_counts, gids_full, mesh)
            raw_p = None
            if raw_vals is not None:
                raw_p = np.zeros(vals_p.shape, vals_p.dtype)
                raw_p[: raw_vals.shape[0], : raw_vals.shape[1]] = \
                    np.nan_to_num(raw_vals, nan=0.0)
            placed = shard_batch_arrays(mesh, ts_p, vals_p, valid, gid_p,
                                        raw_p)
            self._cache_put(ckey, (version, batch, keys, gids, out_keys,
                                   placed, is_counter))

        agg = low0.agg
        if use_split:
            # per-query work is ONLY the group reduce; window evaluation
            # is served from the eval cache (see the chunk loop below)
            step_fn = None if agg is None else self._get_fn(
                ("split-reduce", agg, Gp),
                lambda: make_mesh_group_reduce(mesh, Gp, agg))
        elif self.variant == "ring" and fn == "rate" and agg == "sum":
            step_fn = self._get_fn(
                (fn, agg, Gp if agg else None, self.variant),
                lambda: make_distributed_sum_rate_ring(mesh, Gp))
        else:
            step_fn = self._get_fn(
                (fn, agg, Gp if agg else None, self.variant),
                lambda: make_distributed_range_agg(mesh, fn, Gp, agg))
        _M_DISPATCH["split" if use_split else "fused"].inc()

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        # replicated small operands are PINNED to the mesh's devices: the
        # default backend may be a different platform (e.g. a host-lane CPU
        # mesh inside a TPU process), and a default-placed operand would
        # drag cross-backend transfers into every call
        repl = NamedSharding(mesh, PartitionSpec())
        win_d = jax.device_put(np.int32(low0.window), repl)
        ts_d, vals_d, valid_d, gid_d = placed[:4]
        raw_d = placed[4] if len(placed) > 4 else None

        # split pipeline: prepared per-version device arrays (correction /
        # prefixes), reused by every query over this batch version
        split_cv = None
        split_prefix = None
        if use_split:
            if fn in ("rate", "increase") or delta_counter:
                split_cv = self._prepared(dkey, version, "counter", mesh,
                                          vals_d, valid_d)
            elif fn != "delta":
                split_prefix = self._prepared(dkey, version, "prefix", mesh,
                                              vals_d, valid_d)

        # Fixed call shapes: compile storms would otherwise follow the batch
        # size (every distinct ΣKp is a fresh program). Queries grouped by
        # Kp run in chunks of exactly 1 or GROUP (grids repeated to fill),
        # so each (signature, Kp) compiles at most twice ever. Measured
        # alternatives through the axon tunnel: intermediate power-of-two
        # sizes (late-compile p99 spikes), a 32-wide tier with mixed shapes
        # (second fetch shape = second RTT, 548→385 q/s), and a uniform
        # 32-wide tier (wider program ran slower than 4× 8-wide, ~470 q/s)
        # all lost to plain 1-or-8 chunking with one stacked fetch.
        GROUP = 8
        by_kp: dict[int, list[int]] = {}
        for i, (Kp, _, _) in enumerate(spans):
            by_kp.setdefault(Kp, []).append(i)
        results: list = [None] * len(lows)
        nrows = (G if agg else len(keys)) * B
        # phase 1: dispatch every chunk's device program (async — results
        # stay lazy on device so compute overlaps across chunks)
        calls: list[tuple] = []
        for Kp, idxs in by_kp.items():
            pos = 0
            while pos < len(idxs):
                chunk = idxs[pos : pos + GROUP]
                pos += GROUP
                size = 1 if len(chunk) == 1 else GROUP
                grids = [all_steps[i] for i in chunk]
                grids += [grids[-1]] * (size - len(chunk))
                blob = np.concatenate(grids)
                gkey = blob.tobytes()
                grid_d = self._grid_cache.get(gkey)
                if grid_d is None:
                    if len(self._grid_cache) >= self._grid_cache_cap:
                        self._grid_cache.pop(next(iter(self._grid_cache)))
                    grid_d = self._grid_cache[gkey] = jax.device_put(
                        blob, repl)
                if use_split:
                    ev_d = self._series_eval_cached(
                        dkey, version, low0.window, gkey, fn, mesh, ts_d,
                        vals_d, valid_d, grid_d, win_d, split_cv,
                        split_prefix, raw_d, delta_counter)
                    out = ev_d if step_fn is None else step_fn(ev_d, gid_d)
                elif raw_d is not None:
                    out = step_fn(ts_d, vals_d, valid_d, gid_d, grid_d,
                                  win_d, raw_d)
                else:
                    out = step_fn(ts_d, vals_d, valid_d, gid_d, grid_d,
                                  win_d)
                calls.append((out, chunk, Kp))
        # phase 2: coalesced device→host fetch — one transfer per distinct
        # output shape (per-query slicing on device would cost a dispatch +
        # RTT each; through the axon tunnel that capped the batch path at
        # ~100 q/s while the sequential path ran 338)
        by_shape: dict[tuple, list[int]] = {}
        for ci, (out, _, _) in enumerate(calls):
            by_shape.setdefault(out.shape, []).append(ci)
        fetched: dict[int, np.ndarray] = {}
        for cis in by_shape.values():
            if len(cis) == 1:
                fetched[cis[0]] = np.asarray(calls[cis[0]][0])
            else:
                stacked = np.asarray(jnp.stack(
                    [calls[ci][0] for ci in cis]))
                for j, ci in enumerate(cis):
                    fetched[ci] = stacked[j]
        for ci, (_, chunk, Kp) in enumerate(calls):
            out_np = fetched[ci]
            for j, i in enumerate(chunk):
                lo = lows[i]
                _, K, steps_ms = spans[i]
                vals = out_np[:nrows, j * Kp : j * Kp + K]
                if B > 1:  # un-flatten buckets: [n*B, K] -> [n, K, B]
                    vals = np.ascontiguousarray(
                        vals.reshape(-1, B, K).transpose(0, 2, 1))
                if agg is None:
                    rkeys = keys if lo.keep_metric \
                        else [k.drop_metric() for k in keys]
                else:
                    rkeys = out_keys
                m = StepMatrix(list(rkeys), vals, steps_ms,
                               batch.les if B > 1 else None)
                results[i] = self._apply_post(m, lo)
        return results

    def _cache_put(self, ckey, entry):
        if len(self._batch_cache) >= self._batch_cache_cap:
            self._batch_cache.pop(next(iter(self._batch_cache)))
        self._batch_cache[ckey] = entry

    def _get_fn(self, key, builder):
        """Compiled-program cache with hit/miss accounting."""
        fn = self._fns.get(key)
        if fn is None:
            _M_COMPILE["miss"].inc()
            fn = self._fns[key] = builder()
        else:
            _M_COMPILE["hit"].inc()
        return fn

    def _prepared(self, dkey, version, kind, mesh, vals_d, valid_d):
        """Device-resident prepared arrays for the split pipeline, one
        entry per (batch cache key, kind), invalidated by data version.
        ``kind="counter"``: corrected values; ``"prefix"``: (csum, cnt,
        csum2) exclusive prefixes."""
        key = (dkey, kind)
        hit = self._prep_cache.get(key)
        if hit is not None and hit[0] == version:
            return hit[1]
        prep_fn = self._get_fn(("prep", kind),
                               lambda: make_mesh_prepare(mesh, kind))
        out = prep_fn(vals_d, valid_d)
        if len(self._prep_cache) >= self._prep_cache_cap:
            self._prep_cache.pop(next(iter(self._prep_cache)))
        self._prep_cache[key] = (version, out)
        return out

    def _window_bounds_cached(self, dkey, version, window, grid_bytes,
                              mesh, ts_d, grid_d, win_d):
        """Cached (lo, hi) window bounds per (batch version, step grid,
        window) — the vmapped double searchsorted is the dominant per-query
        cost of the fused path, and its inputs change only when data or
        the query grid do."""
        bkey = (dkey, version, window, grid_bytes)
        hit = self._bounds_cache.get(bkey)
        if hit is not None:
            _M_BOUNDS["hit"].inc()
            return hit
        _M_BOUNDS["miss"].inc()
        bounds_fn = self._get_fn(("bounds",), lambda: make_mesh_bounds(mesh))
        out = bounds_fn(ts_d, grid_d, win_d)
        if len(self._bounds_cache) >= self._bounds_cache_cap:
            self._bounds_cache.pop(next(iter(self._bounds_cache)))
        self._bounds_cache[bkey] = out
        return out

    def _series_eval_cached(self, dkey, version, window, grid_bytes, fn,
                            mesh, ts_d, vals_d, valid_d, grid_d, win_d,
                            split_cv, split_prefix, raw_d,
                            delta_counter=False):
        """Cached per-series evaluated windows [P, K] per (batch version,
        step grid, window, fn) — the boundary gathers + time combine that
        remain the dominant per-query device cost once bounds are cached.
        Nothing here depends on the query's grouping, so every agg over
        the same inner range function shares one entry and a warm query
        runs only the group reduce."""
        ekey = (dkey, version, window, grid_bytes, fn)
        hit = self._eval_cache.get(ekey)
        if hit is not None:
            _M_EVAL["hit"].inc()
            return hit
        _M_EVAL["miss"].inc()
        lo_d, hi_d = self._window_bounds_cached(dkey, version, window,
                                                grid_bytes, mesh, ts_d,
                                                grid_d, win_d)
        if fn in ("rate", "increase", "delta"):
            # delta-on-counter compiles its own corrected variant; the
            # dkey's filters pin the schema, so the eval cache key needs
            # no extra discriminator
            counter = fn in ("rate", "increase") or delta_counter
            ev_fn = self._get_fn(
                ("eval", fn, counter),
                lambda: make_mesh_eval_delta(mesh, fn, counter=counter))
            out = ev_fn(ts_d, vals_d, valid_d, lo_d, hi_d, grid_d, win_d,
                        cv=split_cv, raw=raw_d)
        else:
            ev_fn = self._get_fn(("eval", fn),
                                 lambda: make_mesh_eval_simple(mesh, fn))
            cs_d, cn_d, cs2_d = split_prefix
            out = ev_fn(ts_d, vals_d, valid_d, cs_d, cn_d, cs2_d, lo_d,
                        hi_d, grid_d, win_d)
        if len(self._eval_cache) >= self._eval_cache_cap:
            self._eval_cache.pop(next(iter(self._eval_cache)))
        self._eval_cache[ekey] = out
        return out

    @staticmethod
    def _group_key(k: RangeVectorKey, low: _Lowered) -> RangeVectorKey:
        base = k.drop_metric()
        if low.without:
            return base.without(low.without)
        return base.only(low.by)

    @staticmethod
    def _apply_post(m: StepMatrix, low: _Lowered) -> StepMatrix:
        if not low.post:
            return m.compact() if low.agg is not None else m
        from filodb_tpu.query.exec.transformers import (
            AggregateMapReduce,
            InstantVectorFunctionMapper,
            ScalarOperationMapper,
        )

        for op in low.post:
            if op[0] == "instant":
                m = InstantVectorFunctionMapper(op[1], op[2]).apply(m)
            elif op[0] == "scalarop":
                m = ScalarOperationMapper(op=op[1], scalar=op[2],
                                          scalar_is_lhs=op[3],
                                          bool_mode=op[4]).apply(m)
            elif op[0] == "kagg":
                m = AggregateMapReduce(op=op[1], params=op[2], by=op[3],
                                       without=op[4]).apply(m)
        return m
