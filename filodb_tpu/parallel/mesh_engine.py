"""Device-mesh query engine: PromQL plans lowered onto SPMD mesh kernels.

The reference distributes queries by shipping exec-plan subtrees to
shard-owning nodes and gathering partial aggregates over the network
(``query/src/main/scala/filodb/query/exec/ExecPlan.scala:41``,
``PlanDispatcher.scala:31``). On a TPU pod the same computation is ONE SPMD
program over a ``(shard, time)`` ``jax.sharding.Mesh``: series are
data-parallel over the ``shard`` axis, samples sequence-parallel over the
``time`` axis, label-group reduction is a ``segment_sum`` + ``psum`` over
ICI (see ``parallel/dist_query.py`` for the kernels).

This module is the bridge from the query engine: ``MeshQueryEngine``
recognizes ``agg(range_fn(selector[w])) by (labels)`` logical plans — the
shape of the north-star query and of the reference's
``QueryInMemoryBenchmark``/``QueryHiCardInMemoryBenchmark`` workloads — and
executes them on the mesh, returning the same ``StepMatrix`` the exec path
produces. ``QueryService(engine="mesh")`` tries this engine first and falls
back to the scatter-gather exec tree for every other plan shape.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.query import logical as lp
from filodb_tpu.query.model import QueryStats, RangeVectorKey, StepMatrix

log = logging.getLogger(__name__)

# range functions with associative mesh combines (dist_query kernels)
MESH_FNS = ("rate", "sum_over_time", "count_over_time", "avg_over_time",
            "min_over_time", "max_over_time", "last_over_time")
MESH_AGGS = ("sum", "avg", "count", "min", "max")




def make_query_mesh(n_devices: int | None = None, time_axis: int | None = None):
    """Build the default (shard × time) mesh over available devices.

    ``time_axis``: devices on the sample axis (sequence parallelism); default
    2 when the device count allows, else 1 — series parallelism usually
    dominates for TSDB workloads (P >> S blocks).
    """
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if time_axis is None:
        time_axis = 2 if n % 2 == 0 and n >= 2 else 1
    shard_axis = n // time_axis
    return Mesh(np.array(devs[: shard_axis * time_axis]).reshape(
        shard_axis, time_axis), ("shard", "time"))


@dataclass
class MeshQueryEngine:
    """Compiles + caches distributed query steps per (fn, agg, G-bucket).

    Shapes bucket to powers of two (series count, sample count, step count,
    group count) so repeated queries reuse compiled programs — the mesh
    analog of the exec path's batch-shape bucketing.
    """

    mesh: object = None
    variant: str = "gather"  # or "ring" (ppermute time combine)

    _fns: dict = field(default_factory=dict)
    # decoded global batches are reused across queries over unchanged data
    # (the mesh analog of the exec path's per-shard batch cache)
    _batch_cache: dict = field(default_factory=dict)
    _batch_cache_cap: int = 16

    def _ensure_mesh(self):
        """Build the default mesh lazily on first use: ``jax.devices()``
        can hang or fail while an accelerator tunnel is down, and a server
        that is never mesh-eligible must not pay (or crash on) device
        init at startup."""
        if self.mesh is None:
            self.mesh = make_query_mesh()
        return self.mesh

    # ---- plan recognition ------------------------------------------------

    def supports(self, plan) -> bool:
        """agg(range_fn(raw[w])) by (labels) — optionally wrapped in
        topk/bottomk (reduced host-side over the mesh's [G,K] output)."""
        if isinstance(plan, lp.Aggregate) and plan.op in ("topk", "bottomk") \
                and len(plan.params) == 1:
            return self._supports_core(plan.vector)
        return self._supports_core(plan)

    @staticmethod
    def _supports_core(plan) -> bool:
        if not isinstance(plan, lp.Aggregate):
            return False
        if plan.op not in MESH_AGGS or plan.without or plan.params:
            return False
        psw = plan.vector
        if not isinstance(psw, lp.PeriodicSeriesWithWindowing):
            return False
        if psw.function not in MESH_FNS or psw.params or psw.offset \
                or psw.at_ms is not None:
            return False
        raw = psw.raw
        return isinstance(raw, lp.RawSeries) and raw.column is None \
            and raw.offset == 0

    # ---- execution -------------------------------------------------------

    def execute(self, memstore, dataset: str, plan: lp.Aggregate,
                stats: QueryStats | None = None) -> StepMatrix | None:
        """Run a supported plan on the mesh; ``None`` = fall back to the
        exec path (histogram data or other shapes the kernels don't cover).
        """
        from filodb_tpu.parallel.dist_query import (
            make_distributed_range_agg,
            make_distributed_sum_rate_ring,
            pad_for_mesh,
            shard_batch_arrays,
        )
        from filodb_tpu.query.engine.batch import build_batch
        from filodb_tpu.query.engine.device_batch import _pow2
        from filodb_tpu.query.exec.transformers import steps_array

        if plan.op in ("topk", "bottomk"):
            # mesh computes the inner grouped aggregation; the k-selection
            # over the tiny [G, K] result runs host-side
            from filodb_tpu.query.exec.transformers import AggregateMapReduce
            inner = self.execute(memstore, dataset, plan.vector, stats)
            if inner is None:
                return None
            return AggregateMapReduce(op=plan.op, params=plan.params,
                                      by=plan.by,
                                      without=plan.without).apply(inner)

        mesh = self._ensure_mesh()

        psw: lp.PeriodicSeriesWithWindowing = plan.vector
        raw: lp.RawSeries = psw.raw
        chunk_start = psw.start - psw.window
        chunk_end = psw.end
        steps_ms = steps_array(psw.start, psw.step, psw.end)

        # gather matching partitions across every local shard (the mesh is
        # the "cluster": all series fan into one device program); decoded
        # batches + groupings are cached across queries over unchanged data
        shards = memstore.shards_for(dataset)
        version = sum(s.data_version for s in shards)
        ckey = (dataset, str(raw.filters), chunk_start, chunk_end, plan.by)
        cached = self._batch_cache.get(ckey)
        if cached is not None and cached[0] == version:
            _, batch, keys, gids, out_keys, placed = cached
            if stats is not None:
                stats.series_scanned += len(keys)
                stats.samples_scanned += int(batch.counts.sum())
        else:
            placed = None
            parts = []
            for shard in shards:
                for pid in shard.lookup_partitions(list(raw.filters),
                                                   chunk_start, chunk_end):
                    p = shard.partition(pid)
                    if p is not None:
                        parts.append(p)
            if not parts:
                return StepMatrix.empty(steps_ms)
            batch = build_batch(parts, chunk_start, chunk_end)
            if batch.is_histogram:
                return None  # hist quantile pipeline stays on the exec path
            if stats is not None:
                stats.series_scanned += len(parts)
                stats.samples_scanned += int(batch.counts.sum())
            # label grouping (first-occurrence order, like
            # AggregateMapReduce). The metric label is dropped first — the
            # exec path drops it in range-function output keys before
            # grouping, so `by (_metric_)` must group on nothing there too.
            keys = [RangeVectorKey.of(p.part_key.label_map) for p in parts]
            gkeys = [k.drop_metric().only(plan.by) for k in keys]
            uniq: dict[RangeVectorKey, int] = {}
            gids = np.empty(len(gkeys), np.int32)
            for i, gk in enumerate(gkeys):
                gids[i] = uniq.setdefault(gk, len(uniq))
            out_keys = list(uniq.keys())
        G = len(out_keys)
        Gp = _pow2(G)

        # pad steps to a power of two for compile reuse; extra steps repeat
        # the last step (their results are sliced away)
        K = len(steps_ms)
        Kp = _pow2(K)
        steps_rel = np.empty(Kp, np.int32)
        steps_rel[:K] = (steps_ms - batch.base_ts).astype(np.int32)
        steps_rel[K:] = steps_rel[K - 1]

        if placed is None:
            # build_batch pads P to a power of two; padding series have
            # zero valid samples so their group assignment is inert (NaN
            # results are masked out of every group reduction). The padded
            # + device-placed arrays are the expensive part — cache them.
            gids_full = np.zeros(batch.ts.shape[0], np.int32)
            gids_full[: len(gids)] = gids
            ts_p, vals_p, valid, gid_p = pad_for_mesh(
                batch.ts, batch.vals, batch.counts, gids_full, mesh)
            placed = shard_batch_arrays(mesh, ts_p, vals_p, valid, gid_p)
            if len(self._batch_cache) >= self._batch_cache_cap:
                self._batch_cache.pop(next(iter(self._batch_cache)))
            self._batch_cache[ckey] = (version, batch, keys, gids, out_keys,
                                       placed)

        key = (psw.function, plan.op, Gp, self.variant)
        fn = self._fns.get(key)
        if fn is None:
            if self.variant == "ring" and psw.function == "rate" \
                    and plan.op == "sum":
                fn = make_distributed_sum_rate_ring(mesh, Gp)
            else:
                fn = make_distributed_range_agg(mesh, psw.function, Gp,
                                                plan.op)
            self._fns[key] = fn

        import jax.numpy as jnp
        ts_d, vals_d, valid_d, gid_d = placed
        out = fn(ts_d, vals_d, valid_d, gid_d, jnp.asarray(steps_rel),
                 jnp.asarray(np.int32(psw.window)))
        values = np.asarray(out)[:G, :K]
        return StepMatrix(out_keys, values, steps_ms).compact()
