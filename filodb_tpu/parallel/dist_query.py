"""Sharded query execution: sum(rate(...)) by (...) over a (shard, time) mesh.

The flagship distributed kernel: evaluates a counter-corrected, extrapolated
Prometheus ``rate`` over series sharded across the ``shard`` mesh axis AND
samples sharded across the ``time`` mesh axis, then reduces label groups with
``segment_sum`` + ``psum``.

Why this shape: the reference scales queries by (a) scattering per-shard
subtrees to nodes and gathering partial aggregates (``ExecPlan``/
``ActorPlanDispatcher``) and (b) splitting long time ranges into sequential
sub-plans (``SingleClusterPlanner.materializeTimeSplitPlan``,
``StitchRvsExec``). On a TPU mesh both axes become dimensions of one SPMD
program: shard-axis reduction is a ``psum`` over ICI, and the time axis is
handled like sequence parallelism — each device computes window partials for
its time block, then per-step summaries (count, first/last sample, internal
counter-corrected increase) are all-gathered over the time axis (tiny
[dt, P, K, 6] tensors) and combined associatively, including counter resets
that straddle block boundaries.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # newer jax exports shard_map at top level (check_vma kwarg)
    _shard_map = jax.shard_map
except AttributeError:  # older jax: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from filodb_tpu.query.engine.kernels import fdtype


def _window_bounds(ts, steps, window):
    def bounds(tsp):
        hi = jnp.searchsorted(tsp, steps, side="right")
        lo = jnp.searchsorted(tsp, steps - window, side="right")
        return lo, hi

    return jax.vmap(bounds)(ts)


def _counter_correct(v, valid):
    """Block-local counter-reset correction (monotonized values): the
    cumulative sum of every dropped previous value is added back, exactly
    like ``kernels.range_eval`` / ``SeriesBatch.delta_host``. ``v`` must
    already be masked (invalid positions zeroed)."""
    prev = jnp.concatenate([v[:, :1], v[:, :-1]], axis=1)
    both = valid & jnp.concatenate(
        [jnp.zeros_like(valid[:, :1]), valid[:, :-1]], axis=1)
    dropped = (v < prev) & both
    corr = jnp.cumsum(jnp.where(dropped, prev, 0.0), axis=1)
    return v + corr


def _rate_partials_from_bounds(ts, vals, counts_mask, lo, hi, cv=None,
                               raw=None):
    """[P_l, K, 7] rate partials given precomputed window bounds.

    ``cv`` is the (optionally counter-corrected) value tensor; when None
    the masked values are used directly (delta / non-counter semantics).
    Shared by the fused kernels (bounds computed in-kernel) and the split
    prepare/bounds/step pipeline (bounds and correction cached across
    queries) so both forms run the identical float ops.
    """
    dt = fdtype()
    valid = counts_mask
    v = jnp.where(valid, vals, 0.0).astype(dt)
    if cv is None:
        cv = v
    n = (hi - lo).astype(jnp.int32)
    has = hi > lo

    def g(x, idx):
        return jnp.take_along_axis(x, idx, axis=1)

    i_first = jnp.minimum(lo, ts.shape[1] - 1)
    i_last = jnp.maximum(hi - 1, 0)
    t_first = jnp.where(has, g(ts, i_first), jnp.int32(2**31 - 1)).astype(dt)
    t_last = jnp.where(has, g(ts, i_last), jnp.int32(-(2**31 - 1))).astype(dt)
    v_first = jnp.where(has, g(v, i_first), 0.0)
    v_last = jnp.where(has, g(v, i_last), 0.0)
    inc = jnp.where(has, g(cv, i_last) - g(cv, i_first), 0.0)
    if raw is None:
        v_first_raw = v_first
    else:
        rawm = jnp.where(valid, raw, 0.0).astype(dt)
        v_first_raw = jnp.where(has, g(rawm, i_first), 0.0)
    return jnp.stack([n.astype(dt), t_first, v_first, t_last, v_last, inc,
                      v_first_raw], axis=-1)


def _local_rate_partials(ts, vals, counts_mask, steps, window,
                         counter: bool = True, raw=None):
    """Per-device window partials for the local (P_l, S_l) time block.

    Returns [P_l, K, 7]: n, t_first, v_first, t_last, v_last, internal
    (counter-corrected when ``counter``) increase, v_first_raw. Missing
    => n=0 and sentinels.

    ``raw`` [P_l, S_l] is the uncorrected value tensor when ``vals`` ride
    the pre-corrected/rebased f32-precision lane (``SeriesBatch
    .delta_host``); it feeds ONLY the ``v_first_raw`` field, whose sole
    consumer is Prometheus' extrapolate-to-zero heuristic. The boundary
    combine keeps using the rebased first/last (a large base would not
    cancel exactly in f32 there).
    """
    dt = fdtype()
    valid = counts_mask
    v = jnp.where(valid, vals, 0.0).astype(dt)
    lo, hi = _window_bounds(ts, steps, window)
    cv = _counter_correct(v, valid) if counter else None
    return _rate_partials_from_bounds(ts, vals, counts_mask, lo, hi, cv=cv,
                                      raw=raw)


def _combine_time_partials(parts, steps, window, mode: str = "rate",
                           counter: bool = True):
    """Combine all-gathered time-block partials [dt, P, K, 7] → [P, K].

    Sequential associative combine over the (static, small) time axis,
    handling counter resets across block boundaries, then Prometheus
    extrapolation using the global first/last samples. ``mode``: "rate",
    "increase" (extrapolated, not divided by window) or "delta"
    (non-counter increase, extrapolated).
    """
    dtt = fdtype()
    dt_blocks = parts.shape[0]
    n_tot = jnp.sum(parts[..., 0], axis=0)
    t_first_g = jnp.min(parts[..., 1], axis=0)
    t_last_g = jnp.max(parts[..., 3], axis=0)

    total_inc = jnp.zeros_like(parts[0, ..., 5])
    has_prev = jnp.zeros(parts.shape[1:3], bool)
    v_prev = jnp.zeros_like(total_inc)
    v_first_g = jnp.zeros_like(total_inc)
    for d in range(dt_blocks):  # static unroll; dt is the mesh time size
        nd = parts[d, ..., 0] > 0
        vf, vl, inc = parts[d, ..., 2], parts[d, ..., 4], parts[d, ..., 5]
        if counter:
            boundary = jnp.where(
                nd & has_prev,
                jnp.where(vf < v_prev, vf, vf - v_prev), 0.0)
        else:
            boundary = jnp.where(nd & has_prev, vf - v_prev, 0.0)
        total_inc = total_inc + inc + boundary
        # the global first's RAW value (field 6), for extrapolate-to-zero
        v_first_g = jnp.where(nd & ~has_prev, parts[d, ..., 6], v_first_g)
        v_prev = jnp.where(nd, vl, v_prev)
        has_prev = has_prev | nd

    # Prometheus extrapolatedRate (see kernels.range_eval)
    t_first_s = t_first_g / 1000.0
    t_last_s = t_last_g / 1000.0
    range_start = (steps[None, :] - window).astype(dtt) / 1000.0
    range_end = steps[None, :].astype(dtt) / 1000.0
    sampled = t_last_s - t_first_s
    avg_dur = sampled / jnp.maximum(n_tot - 1.0, 1.0)
    dur_start = t_first_s - range_start
    dur_end = range_end - t_last_s
    if counter and mode != "delta":
        # Prometheus applies the extrapolate-to-zero heuristic only to
        # rate/increase — delta on a counter schema gets the reset
        # correction but never the clamp (kernels.range_eval agrees)
        dur_to_zero = jnp.where(
            total_inc > 0,
            sampled * v_first_g / jnp.maximum(total_inc, 1e-30), jnp.inf)
        dur_start = jnp.minimum(dur_start, dur_to_zero)
    threshold = avg_dur * 1.1
    extend = sampled
    extend = extend + jnp.where(dur_start < threshold, dur_start, avg_dur / 2)
    extend = extend + jnp.where(dur_end < threshold, dur_end, avg_dur / 2)
    ext = total_inc * extend / jnp.maximum(sampled, 1e-10)
    if mode == "rate":
        out = ext / (window.astype(dtt) / 1000.0)
    else:  # increase / delta
        out = ext
    return jnp.where(n_tot >= 2, out, jnp.nan)


def _simple_prefixes(vals, counts_mask):
    """Exclusive prefix sums (value, count, value²) [P_l, S_l+1] — the
    per-batch state that makes every window sum an O(1) pair of gathers."""
    dt = fdtype()
    valid = counts_mask
    v = jnp.where(valid, vals, 0.0).astype(dt)

    def eprefix(x):
        return jnp.concatenate(
            [jnp.zeros(x.shape[:-1] + (1,), x.dtype), jnp.cumsum(x, -1)], -1)

    return eprefix(v), eprefix(valid.astype(dt)), eprefix(v * v)


def _simple_partials_from_bounds(ts, vals, counts_mask, csum, cnt, csum2,
                                 lo, hi, with_minmax: bool = True):
    """[P_l, K, 7] simple-fn partials given precomputed prefixes + bounds:
    sum, count, min, max, last, t_last, sumsq. ``with_minmax=False`` fills
    the min/max fields with sentinels — window min/max have no prefix form
    (the split pipeline excludes those fns and keeps the fused kernel)."""
    dt = fdtype()
    valid = counts_mask
    v = jnp.where(valid, vals, 0.0).astype(dt)

    def g(x, idx):
        return jnp.take_along_axis(x, idx, axis=1)

    s = g(csum, hi) - g(csum, lo)
    s2 = g(csum2, hi) - g(csum2, lo)
    n = g(cnt, hi) - g(cnt, lo)
    if with_minmax:
        # blocked masked min/max (local S is small per device)
        S = ts.shape[1]
        sidx = jnp.arange(S)[None, None, :]
        in_win = (sidx >= lo[:, :, None]) & (sidx < hi[:, :, None]) \
            & valid[:, None, :]
        mn = jnp.min(jnp.where(in_win, vals[:, None, :], jnp.inf), axis=2)
        mx = jnp.max(jnp.where(in_win, vals[:, None, :], -jnp.inf), axis=2)
    else:
        mn = jnp.full_like(s, jnp.inf)
        mx = jnp.full_like(s, -jnp.inf)
    has = n > 0
    last = jnp.where(has, g(v, jnp.maximum(hi - 1, 0)), 0.0)
    t_last = jnp.where(has, g(ts, jnp.maximum(hi - 1, 0)),
                       jnp.int32(-(2**31 - 1))).astype(dt)
    return jnp.stack([s, n, mn, mx, last, t_last, s2], axis=-1)


def _local_simple_partials(ts, vals, counts_mask, steps, window):
    """Per-device partials for associative over-time functions:
    [P_l, K, 7] = sum, count, min, max, last, t_last, sumsq
    (+inf/-inf/0 sentinels)."""
    lo, hi = _window_bounds(ts, steps, window)
    csum, cnt, csum2 = _simple_prefixes(vals, counts_mask)
    return _simple_partials_from_bounds(ts, vals, counts_mask, csum, cnt,
                                        csum2, lo, hi)


def _sc_var(p):
    n = p[..., 1].sum(0)
    s = p[..., 0].sum(0)
    s2 = p[..., 6].sum(0)
    mean = s / jnp.maximum(n, 1.0)
    return n, jnp.maximum(s2 / jnp.maximum(n, 1.0) - mean * mean, 0.0)


_SIMPLE_COMBINE = {
    "sum_over_time": lambda p: jnp.where(p[..., 1].sum(0) > 0,
                                         p[..., 0].sum(0), jnp.nan),
    "count_over_time": lambda p: jnp.where(p[..., 1].sum(0) > 0,
                                           p[..., 1].sum(0), jnp.nan),
    "avg_over_time": lambda p: jnp.where(
        p[..., 1].sum(0) > 0,
        p[..., 0].sum(0) / jnp.maximum(p[..., 1].sum(0), 1.0), jnp.nan),
    "min_over_time": lambda p: jnp.where(p[..., 1].sum(0) > 0,
                                         p[..., 2].min(0), jnp.nan),
    "max_over_time": lambda p: jnp.where(p[..., 1].sum(0) > 0,
                                         p[..., 3].max(0), jnp.nan),
    "last_over_time": lambda p: jnp.where(
        p[..., 1].sum(0) > 0,
        jnp.take_along_axis(p[..., 4], jnp.argmax(p[..., 5], axis=0)[None],
                            axis=0)[0], jnp.nan),
    "last_sample": lambda p: jnp.where(
        p[..., 1].sum(0) > 0,
        jnp.take_along_axis(p[..., 4], jnp.argmax(p[..., 5], axis=0)[None],
                            axis=0)[0], jnp.nan),
    "present_over_time": lambda p: jnp.where(p[..., 1].sum(0) > 0, 1.0,
                                             jnp.nan),
    "stdvar_over_time": lambda p: jnp.where(
        _sc_var(p)[0] > 0, _sc_var(p)[1], jnp.nan),
    "stddev_over_time": lambda p: jnp.where(
        _sc_var(p)[0] > 0, jnp.sqrt(_sc_var(p)[1]), jnp.nan),
}


def _group_reduce(res, gid_l, num_groups, agg):
    """[P_l, K] per-series results → [G, K] grouped aggregate (psum/pmin/
    pmax over the shard axis). NaN = series absent at that step."""
    present = ~jnp.isnan(res)
    contrib = jnp.where(present, res, 0.0)
    if agg in ("min", "max"):
        sentinel = jnp.inf if agg == "min" else -jnp.inf
        marked = jnp.where(present, res, sentinel)
        seg = (jax.ops.segment_min if agg == "min"
               else jax.ops.segment_max)(marked, gid_l, num_groups)
        seg = (lax.pmin if agg == "min" else lax.pmax)(seg, "shard")
        gcnt = lax.psum(jax.ops.segment_sum(
            present.astype(contrib.dtype), gid_l, num_groups), "shard")
        return jnp.where(gcnt > 0, seg, jnp.nan)
    gsum = lax.psum(jax.ops.segment_sum(contrib, gid_l, num_groups), "shard")
    gcnt = lax.psum(jax.ops.segment_sum(
        present.astype(contrib.dtype), gid_l, num_groups), "shard")
    if agg in ("stddev", "stdvar"):
        gsum2 = lax.psum(jax.ops.segment_sum(contrib * contrib, gid_l,
                                             num_groups), "shard")
        mean = gsum / jnp.maximum(gcnt, 1.0)
        var = jnp.maximum(gsum2 / jnp.maximum(gcnt, 1.0) - mean * mean, 0.0)
        out = var if agg == "stdvar" else jnp.sqrt(var)
        return jnp.where(gcnt > 0, out, jnp.nan)
    if agg == "avg":
        return jnp.where(gcnt > 0, gsum / jnp.maximum(gcnt, 1.0), jnp.nan)
    if agg == "count":
        return jnp.where(gcnt > 0, gcnt, jnp.nan)
    if agg == "group":
        return jnp.where(gcnt > 0, 1.0, jnp.nan)
    return jnp.where(gcnt > 0, gsum, jnp.nan)


COUNTER_FNS = {"rate": ("rate", True), "increase": ("increase", True),
               "delta": ("delta", False)}


def _mesh_call(ts, vals, valid, group_ids, steps, window, raw=None):
    """(in_specs, args) for the distributed step functions' shard_map —
    appending the optional raw-value operand when the pre-corrected lane
    supplies it."""
    in_specs = (P("shard", "time"), P("shard", "time"),
                P("shard", "time"), P("shard"), P(None), P())
    args = (ts, vals, valid, group_ids, steps, window)
    if raw is not None:
        in_specs += (P("shard", "time"),)
        args += (raw,)
    return in_specs, args

# aggs with associative mesh reductions
MESH_AGG_OPS = ("sum", "avg", "count", "min", "max", "stddev", "stdvar",
                "group")


def make_distributed_range_agg(mesh: Mesh, fn: str, num_groups: int,
                               agg: str | None = "sum"):
    """Distributed ``agg(fn(x[w])) by (g)`` over the (shard, time) mesh —
    time-block partials all-gathered over ``time``, label groups reduced via
    segment ops + collectives over ``shard``. ``agg=None`` returns the
    per-series [P, K] matrix (raw selectors / un-aggregated range functions),
    sharded over the shard axis."""

    def per_series(ts_l, vals_l, valid_l, steps_r, window_r, raw_l=None):
        if fn in COUNTER_FNS:
            mode, counter = COUNTER_FNS[fn]
            parts = _local_rate_partials(ts_l, vals_l, valid_l, steps_r,
                                         window_r, counter=counter,
                                         raw=raw_l)
            gathered = lax.all_gather(parts, "time")  # [dt, P_l, K, 7]
            return _combine_time_partials(gathered, steps_r, window_r,
                                          mode=mode, counter=counter)
        combine = _SIMPLE_COMBINE[fn]
        parts = _local_simple_partials(ts_l, vals_l, valid_l, steps_r,
                                       window_r)
        gathered = lax.all_gather(parts, "time")  # [dt, P_l, K, 7]
        return combine(gathered)

    def step(ts, vals, valid, group_ids, steps, window, raw=None):
        # ``raw`` [P, S]: uncorrected values, present when ``vals`` ride
        # the pre-corrected/rebased f32-precision lane
        def kernel(ts_l, vals_l, valid_l, gid_l, steps_r, window_r,
                   *rest):
            res = per_series(ts_l, vals_l, valid_l, steps_r, window_r,
                             rest[0] if rest else None)
            if agg is None:
                return res
            return _group_reduce(res, gid_l, num_groups, agg)

        in_specs, args = _mesh_call(ts, vals, valid, group_ids, steps,
                                    window, raw)
        return _shard_map(
            kernel, mesh=mesh, in_specs=in_specs,
            out_specs=P("shard", None) if agg is None else P(None, None),
            check_vma=False,
        )(*args)

    return jax.jit(step)


# ---- split pipeline: prepare / bounds / step --------------------------------
#
# The fused kernels above recompute two batch-level passes on EVERY query:
# the counter-correction cumsum over [P, S] and the vmapped searchsorted
# window bounds — together ~90% of a warm big-scan query's device time,
# even though neither depends on anything but (batch version, step grid,
# window). The split pipeline hoists both into separately-jitted sharded
# programs whose outputs stay resident on device and are cached by the
# mesh engine, so a warm query runs only the tiny step program (a handful
# of gathers, the time-axis all_gather of [dt, P_l, K, 7] partials, and
# the segment_sum + psum group reduce). All three programs are
# shard_map-wrapped over the same (shard, time) mesh and reuse the exact
# helper functions of the fused path, so results are identical.
#
# Window min/max are excluded: they have no prefix-summable form (the
# fused kernel's blocked masked scan stays the per-query cost there).
SPLIT_FNS = ("rate", "increase", "delta", "sum_over_time",
             "count_over_time", "avg_over_time", "last_over_time",
             "present_over_time", "stddev_over_time", "stdvar_over_time")
_SIMPLE_SPLIT_FNS = tuple(f for f in SPLIT_FNS if f not in COUNTER_FNS)


def make_mesh_prepare(mesh: Mesh, kind: str):
    """Per-batch-version prepare program, sharded like the batch itself.

    ``kind="counter"``: (vals, valid) → counter-corrected values [P, S]
    (block-local cumsum, identical to the fused kernels' in-kernel
    correction — cross-block resets are still handled by the combine's
    boundary terms). This is the device-side replacement for the host
    ``SeriesBatch.delta_host`` pre-pass when the value magnitudes make
    direct f32 arithmetic safe (see mesh_engine._device_correction_ok).

    ``kind="prefix"``: (vals, valid) → (csum, cnt, csum2) exclusive
    prefixes, globally [P, S + dt] sharded (shard, time) — each time block
    holds its local [P_l, S_l+1] prefix.
    """

    def prep(vals, valid):
        def kernel(vals_l, valid_l):
            dt = fdtype()
            if kind == "counter":
                v = jnp.where(valid_l, vals_l, 0.0).astype(dt)
                return _counter_correct(v, valid_l)
            return _simple_prefixes(vals_l, valid_l)

        out_specs = P("shard", "time") if kind == "counter" \
            else (P("shard", "time"),) * 3
        return _shard_map(
            kernel, mesh=mesh,
            in_specs=(P("shard", "time"), P("shard", "time")),
            out_specs=out_specs, check_vma=False,
        )(vals, valid)

    return jax.jit(prep)


def make_mesh_bounds(mesh: Mesh):
    """Window-bounds program: (ts, steps, window) → (lo, hi) int32, each
    time block's bounds local to its own [P_l, S_l] slice. Globally
    [P, dt·K] sharded (shard, time); only ever consumed by step programs
    with the same sharding, so the global layout is never materialized.
    The vmapped double searchsorted here is the single most expensive op
    of the whole query (~200 ms at P=8192, S=2048, K=256 on one CPU
    device) — caching its output per (batch version, grid, window) is
    what the split pipeline exists for."""

    def bounds(ts, steps, window):
        def kernel(ts_l, steps_r, window_r):
            lo, hi = _window_bounds(ts_l, steps_r, window_r)
            return lo.astype(jnp.int32), hi.astype(jnp.int32)

        return _shard_map(
            kernel, mesh=mesh,
            in_specs=(P("shard", "time"), P(None), P()),
            out_specs=(P("shard", "time"), P("shard", "time")),
            check_vma=False,
        )(ts, steps, window)

    return jax.jit(bounds)


def make_mesh_eval_delta(mesh: Mesh, fn: str, counter: bool | None = None):
    """Per-(batch version, grid, window) series evaluation for rate/
    increase/delta given cached correction + bounds: gathers →
    [P_l, K, 7] partials → all_gather over ``time`` → associative combine
    with Prometheus extrapolation. Output [P, K] per-series values,
    sharded on ``shard`` and replicated over ``time``.

    The boundary gathers are the dominant remaining cost once bounds are
    cached (XLA's gather is per-element on CPU: ~280 ms for the 7 gathers
    at P=8192, K=256) and depend only on (data version, step grid,
    window) — never on the query's grouping — so the engine caches THIS
    stage's output and re-runs only the group reduce per query. ``cv``
    (counter-corrected values) rides along for counter fns; ``raw``
    accompanies the host-corrected lane exactly as in the fused kernels.

    ``counter`` overrides the per-fn default: delta on a COUNTER schema
    is reset-corrected like rate/increase (mirroring the exec
    transformers), while delta on a gauge keeps raw differences."""
    mode, default_counter = COUNTER_FNS[fn]
    counter = default_counter if counter is None else counter

    def ev(ts, vals, valid, lo, hi, steps, window, cv=None, raw=None):
        def kernel(ts_l, vals_l, valid_l, lo_l, hi_l, steps_r,
                   window_r, *rest):
            cv_l = rest[0] if cv is not None else None
            raw_l = rest[-1] if raw is not None else None
            parts = _rate_partials_from_bounds(ts_l, vals_l, valid_l,
                                               lo_l, hi_l, cv=cv_l,
                                               raw=raw_l)
            gathered = lax.all_gather(parts, "time")  # [dt, P_l, K, 7]
            return _combine_time_partials(gathered, steps_r, window_r,
                                          mode=mode, counter=counter)

        in_specs = (P("shard", "time"),) * 5 + (P(None), P())
        args = (ts, vals, valid, lo, hi, steps, window)
        for extra in (cv, raw):
            if extra is not None:
                in_specs += (P("shard", "time"),)
                args += (extra,)
        return _shard_map(
            kernel, mesh=mesh, in_specs=in_specs,
            out_specs=P("shard", None), check_vma=False,
        )(*args)

    return jax.jit(ev)


def make_mesh_eval_simple(mesh: Mesh, fn: str):
    """Per-(batch version, grid, window) series evaluation for the
    prefix-summable over-time fns given cached prefixes + bounds (window
    min/max have no prefix form and stay on the fused kernel). Output
    [P, K] sharded on ``shard``, replicated over ``time`` — cached by the
    engine like the delta-family eval."""
    if fn not in _SIMPLE_SPLIT_FNS:
        raise ValueError(f"{fn} has no split (prefix) form")
    combine = _SIMPLE_COMBINE[fn]

    def ev(ts, vals, valid, csum, cnt, csum2, lo, hi, steps, window):
        def kernel(ts_l, vals_l, valid_l, cs_l, cn_l, cs2_l, lo_l, hi_l,
                   steps_r, window_r):
            parts = _simple_partials_from_bounds(
                ts_l, vals_l, valid_l, cs_l, cn_l, cs2_l, lo_l, hi_l,
                with_minmax=False)
            gathered = lax.all_gather(parts, "time")  # [dt, P_l, K, 7]
            return combine(gathered)

        in_specs = (P("shard", "time"),) * 8 + (P(None), P())
        return _shard_map(
            kernel, mesh=mesh, in_specs=in_specs,
            out_specs=P("shard", None), check_vma=False,
        )(ts, vals, valid, csum, cnt, csum2, lo, hi, steps, window)

    return jax.jit(ev)


def make_mesh_group_reduce(mesh: Mesh, num_groups: int, agg: str):
    """The per-query step of the split pipeline: cached per-series values
    [P, K] → [G, K] grouped aggregate — one segment reduce plus one psum
    over ``shard``, orders of magnitude less work than re-evaluating the
    windows. This is ALL a warm repeat query runs on device."""

    def step(series_vals, group_ids):
        def kernel(res_l, gid_l):
            return _group_reduce(res_l, gid_l, num_groups, agg)

        return _shard_map(
            kernel, mesh=mesh,
            in_specs=(P("shard", None), P("shard")),
            out_specs=P(None, None), check_vma=False,
        )(series_vals, group_ids)

    return jax.jit(step)


def make_distributed_sum_rate(mesh: Mesh, num_groups: int):
    """Build the jitted distributed ``sum(rate(x[w])) by (g)`` step.

    Inputs (global shapes):
      ts [P, S] int32 relative ms (padded TS_PAD), vals [P, S],
      valid [P, S] bool, group_ids [P] int32, steps [K] int32,
      window int32 scalar.
    Output: [G, K] group sums, fully replicated.
    """

    def step(ts, vals, valid, group_ids, steps, window, raw=None):
        def kernel(ts_l, vals_l, valid_l, gid_l, steps_r, window_r, *rest):
            parts = _local_rate_partials(ts_l, vals_l, valid_l, steps_r,
                                         window_r,
                                         raw=rest[0] if rest else None)
            gathered = lax.all_gather(parts, "time")  # [dt, P_l, K, 7]
            rate = _combine_time_partials(gathered, steps_r, window_r)
            present = ~jnp.isnan(rate)
            contrib = jnp.where(present, rate, 0.0)
            gsum = jax.ops.segment_sum(contrib, gid_l, num_groups)
            gcnt = jax.ops.segment_sum(present.astype(contrib.dtype), gid_l,
                                       num_groups)
            gsum = lax.psum(gsum, "shard")
            gcnt = lax.psum(gcnt, "shard")
            return jnp.where(gcnt > 0, gsum, jnp.nan)

        in_specs, args = _mesh_call(ts, vals, valid, group_ids, steps,
                                    window, raw)
        return _shard_map(
            kernel, mesh=mesh, in_specs=in_specs,
            out_specs=P(None, None),
            check_vma=False,
        )(*args)

    return jax.jit(step)


def shard_batch_arrays(mesh: Mesh, ts, vals, valid, group_ids, raw=None):
    """Place host arrays with (shard, time) shardings. ``raw`` [P, S]
    (optional — the uncorrected values accompanying the rebased lane)
    shards like ``vals``."""
    s2 = NamedSharding(mesh, P("shard", "time"))
    s1 = NamedSharding(mesh, P("shard"))
    placed = (jax.device_put(ts, s2), jax.device_put(vals, s2),
              jax.device_put(valid, s2), jax.device_put(group_ids, s1))
    if raw is not None:
        placed += (jax.device_put(raw, s2),)
    return placed


def pad_for_mesh(ts, vals, counts, group_ids, mesh: Mesh):
    """Pad P to a multiple of mesh 'shard' size and S to 'time' size;
    returns padded arrays + a validity mask (replaces counts, which don't
    shard along the time axis)."""
    ds = mesh.shape["shard"]
    dtm = mesh.shape["time"]
    P_, S_ = ts.shape
    Pp = -(-P_ // ds) * ds
    Sp = -(-S_ // dtm) * dtm
    ts_p = np.full((Pp, Sp), np.iinfo(np.int32).max, np.int32)
    vals_p = np.zeros((Pp, Sp), vals.dtype)
    valid = np.zeros((Pp, Sp), bool)
    ts_p[:P_, :S_] = ts
    vals_p[:P_, :S_] = np.nan_to_num(vals, nan=0.0)
    valid[:P_, :S_] = np.arange(S_)[None, :] < counts[:, None]
    gid_p = np.zeros(Pp, np.int32)
    gid_p[:P_] = group_ids
    if Pp > P_:
        # padding series join group 0 but contribute nothing (no valid samples)
        pass
    return ts_p, vals_p, valid, gid_p


def make_distributed_sum_rate_ring(mesh: Mesh, num_groups: int):
    """Ring variant of the distributed rate pipeline: instead of
    all-gathering every time-block's partials, carry state around the time
    axis with ``lax.ppermute`` (the literal ring-attention communication
    shape). Each of the dt-1 hops passes the running combine state
    [P_l, K, 8] to the next time block:

        (n_so_far, t_first, v_first, inc_so_far, has_prev, v_prev, t_last,
         v_first_raw)

    Memory per device stays O(P_l·K) regardless of dt (the all_gather
    version holds [dt, P_l, K, 7]); latency is dt-1 ICI hops.
    """
    dt_size = mesh.shape["time"]

    def step(ts, vals, valid, group_ids, steps, window, raw=None):
        def kernel(ts_l, vals_l, valid_l, gid_l, steps_r, window_r,
                   *rest):
            dtt = fdtype()
            parts = _local_rate_partials(ts_l, vals_l, valid_l, steps_r,
                                         window_r,
                                         raw=rest[0] if rest else None)
            n_l, tf_l, vf_l, tl_l, vl_l, inc_l, vfr_l = [
                parts[..., i] for i in range(7)]
            has_l = n_l > 0
            t_idx = lax.axis_index("time")

            # state flowing forward around the ring
            state = jnp.stack([
                n_l, tf_l, jnp.where(has_l, vf_l, 0.0), inc_l,
                has_l.astype(dtt), jnp.where(has_l, vl_l, 0.0), tl_l,
                jnp.where(has_l, vfr_l, 0.0)],
                axis=-1)

            perm = [(i, i + 1) for i in range(dt_size - 1)]

            def hop(state_in, _):
                prev = lax.ppermute(state_in, "time", perm)
                # devices with t_idx == 0 receive zeros (no source): mask the
                # counts/flags AND re-sentinel the min/max-combined fields so
                # zeros can't pollute t_first (min) / t_last (max)
                p_n, p_tf, p_vf, p_inc, p_has, p_vl, p_tl, p_vfr = [
                    prev[..., i] for i in range(8)]
                first_block = (t_idx == 0)
                p_n = jnp.where(first_block, 0.0, p_n)
                p_has = jnp.where(first_block, 0.0, p_has)
                no_prev = p_has == 0
                p_tf = jnp.where(no_prev, jnp.array(2**31 - 1, dtt), p_tf)
                p_tl = jnp.where(no_prev, jnp.array(-(2**31 - 1), dtt), p_tl)
                p_inc = jnp.where(first_block, 0.0, p_inc)
                # combine prev-state with the local block
                boundary = jnp.where(
                    has_l & (p_has > 0),
                    jnp.where(vf_l < p_vl, vf_l, vf_l - p_vl), 0.0)
                n_c = p_n + n_l
                inc_c = p_inc + inc_l + boundary
                tf_c = jnp.minimum(p_tf, tf_l)
                vf_c = jnp.where(p_has > 0, p_vf,
                                 jnp.where(has_l, vf_l, 0.0))
                p_vfr = jnp.where(first_block, 0.0, p_vfr)
                vfr_c = jnp.where(p_has > 0, p_vfr,
                                  jnp.where(has_l, vfr_l, 0.0))
                has_c = jnp.maximum(p_has, has_l.astype(dtt))
                vl_c = jnp.where(has_l, vl_l, p_vl)
                tl_c = jnp.maximum(p_tl, tl_l)
                out = jnp.stack([n_c, tf_c, vf_c, inc_c, has_c, vl_c, tl_c,
                                 vfr_c], axis=-1)
                return out, None

            state, _ = lax.scan(hop, state, None, length=max(dt_size - 1, 1)
                                if dt_size > 1 else 0)
            # after dt-1 hops the LAST time block holds the full combine;
            # broadcast it back to every block (masked psum: single
            # contributor)
            if dt_size > 1:
                full = lax.psum(
                    jnp.where(t_idx == dt_size - 1, state, 0.0), "time")
            else:
                full = state
            (n_tot, t_first_g, _, total_inc, _, _, t_last_g,
             v_first_raw_g) = [full[..., i] for i in range(8)]

            # Prometheus extrapolation (same as the gather variant)
            t_first_s = t_first_g / 1000.0
            t_last_s = t_last_g / 1000.0
            range_start = (steps_r[None, :] - window_r).astype(dtt) / 1000.0
            range_end = steps_r[None, :].astype(dtt) / 1000.0
            sampled = t_last_s - t_first_s
            avg_dur = sampled / jnp.maximum(n_tot - 1.0, 1.0)
            dur_start = t_first_s - range_start
            dur_end = range_end - t_last_s
            dur_zero = jnp.where(
                total_inc > 0,
                sampled * v_first_raw_g / jnp.maximum(total_inc, 1e-30),
                jnp.inf)
            dur_start = jnp.minimum(dur_start, dur_zero)
            threshold = avg_dur * 1.1
            extend = sampled
            extend = extend + jnp.where(dur_start < threshold, dur_start,
                                        avg_dur / 2)
            extend = extend + jnp.where(dur_end < threshold, dur_end,
                                        avg_dur / 2)
            rate = total_inc * extend / jnp.maximum(sampled, 1e-10) \
                / (window_r.astype(dtt) / 1000.0)
            rate = jnp.where(n_tot >= 2, rate, jnp.nan)

            present = ~jnp.isnan(rate)
            contrib = jnp.where(present, rate, 0.0)
            gsum = lax.psum(jax.ops.segment_sum(contrib, gid_l, num_groups),
                            "shard")
            gcnt = lax.psum(jax.ops.segment_sum(
                present.astype(contrib.dtype), gid_l, num_groups), "shard")
            return jnp.where(gcnt > 0, gsum, jnp.nan)

        in_specs, args = _mesh_call(ts, vals, valid, group_ids, steps,
                                    window, raw)
        return _shard_map(
            kernel, mesh=mesh, in_specs=in_specs,
            out_specs=P(None, None),
            check_vma=False,
        )(*args)

    return jax.jit(step)
