"""Mesh worker processes: the per-process half of the multi-process mesh.

Each worker owns a contiguous slice of a dataset's shard space and runs
the agg-stripped :class:`~filodb_tpu.coordinator.mesh_cluster.
LoweredDescriptor` through its own ``MeshQueryEngine`` over a
1-device-per-process mesh slice. Device-resident caches (decoded+placed
batches, window bounds, per-series evaluations — PR 14's dkey semantics)
live per process, so a warm worker's per-query cost is one window
evaluation over its local rows; the cross-process combine happens on the
root (``coordinator/mesh_cluster.py``).

Two data-ownership modes:

- ``--config server.json``: the worker tails the shared WAL read-only for
  its owned shards (``Node.start_shard`` — the same recover-then-tail
  path cluster members use), against its own in-process column store.
- ``--seed module:callable``: CI/benchmark harness — the callable returns
  a fully-ingested memstore (deterministic: ``ingestion_shard`` hashing
  is content-derived, so every process derives the same placement) and a
  shard-slice view restricts scans to the owned range.

``jax.distributed.initialize`` is wrapped by :func:`init_distributed` for
real multi-host hardware; the CI harness runs N spawned subprocesses × 1
CPU device each through the same descriptor/execute code path (see
``doc/mesh_engine.md`` for the recipe and the real-hardware re-anchor
procedure).
"""

from __future__ import annotations

import argparse
import importlib
import logging
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

log = logging.getLogger(__name__)


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int, local_device_ids=None) -> None:
    """Join a real multi-host JAX runtime (TPU pod / multi-host GPU):
    after this, ``jax.devices()`` spans every process and one global
    ``Mesh(("shard", "time"))`` can cover the pod. The CPU harness never
    calls this — its N×1 topology needs no cross-process device runtime,
    only the descriptor wire — so the call stays gated behind explicit
    hardware configuration (``FILODB_MESH_DISTRIBUTED=1``)."""
    if os.environ.get("FILODB_MESH_DISTRIBUTED") != "1":
        raise RuntimeError(
            "set FILODB_MESH_DISTRIBUTED=1 to initialize the multi-host "
            "device runtime (CPU harness runs without it)")
    import jax

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id,
                               local_device_ids=local_device_ids)


class _ShardSliceStore:
    """Read view of a memstore restricted to an owned shard range — what
    makes a seeded (fully-ingested) store behave like locally-owned
    slice data without copying anything."""

    def __init__(self, inner, dataset: str, lo: int, hi: int):
        self._inner = inner
        self._dataset = dataset
        self._lo = lo
        self._hi = hi

    def shards_for(self, dataset: str):
        shards = self._inner.shards_for(dataset)
        if dataset != self._dataset:
            return shards
        return [s for s in shards if self._lo <= s.shard_num < self._hi]

    def __getattr__(self, name):
        return getattr(self._inner, name)


class MeshWorker:
    """One mesh worker process: framed control server (same auth/hello
    protocol as the plan executor) + a 1-device mesh engine over the
    locally-owned shard slice."""

    def __init__(self, memstore, dataset: str, shard_range: tuple,
                 host: str = "127.0.0.1", port: int = 0,
                 secret: str | None = None):
        from filodb_tpu.coordinator.remote import (
            cluster_secret,
            make_authed_handler,
        )

        self.memstore = memstore
        self.dataset = dataset
        self.shard_range = shard_range
        self.secret = secret if secret is not None else cluster_secret()
        self._engine = None
        self._engine_lock = threading.Lock()
        self.queries = 0
        self.last_exec_s: float | None = None
        Handler = make_authed_handler(lambda: self.secret, self._handle,
                                      "mesh worker")

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        self.server = Server((host, port), Handler, bind_and_activate=True)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self.address = (host, self.port)
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True,
                                        name=f"mesh-worker-{self.port}")

    def engine(self):
        """1-device mesh slice engine, built lazily (device init must not
        gate the control plane coming up)."""
        with self._engine_lock:
            if self._engine is None:
                from filodb_tpu.parallel.mesh_engine import (
                    MeshQueryEngine,
                    make_query_mesh,
                )
                self._engine = MeshQueryEngine(
                    mesh=make_query_mesh(n_devices=1))
            return self._engine

    # ---- protocol --------------------------------------------------------

    def _handle(self, msg):
        kind = msg[0]
        if kind == "ping":
            return ("pong",)
        if kind == "mesh_status":
            try:
                return ("ok", self._status())
            except Exception as e:
                log.exception("mesh status failed")
                return ("err", repr(e))
        if kind == "mesh_exec":
            descs = msg[1]
            budget_s = msg[2] if len(msg) > 2 else None
            try:
                from filodb_tpu.coordinator.query_service import plan_tenant
                from filodb_tpu.utils.governor import (
                    EXPENSIVE,
                    QueryRejected,
                    governor,
                )
                from filodb_tpu.utils.resilience import Deadline

                dl = Deadline.after(budget_s) if budget_s else None
                try:
                    # same admission gate as shipped exec plans: root
                    # fan-out from many coordinators can't stampede a
                    # worker, and a shed is a typed verdict the root
                    # propagates as 503 + Retry-After
                    with governor().admit(deadline=dl, cost=EXPENSIVE,
                                          tenant=plan_tenant(descs[0])):
                        return ("ok", self._exec(descs))
                except QueryRejected as e:
                    return ("rejected", str(e), e.retry_after_s)
            except Exception as e:
                log.exception("mesh exec failed")
                return ("err", repr(e))
        return ("err", f"unknown message {kind!r}")

    def _exec(self, descs) -> dict:
        from filodb_tpu.query.model import QueryStats

        eng = self.engine()
        stats = QueryStats()
        t0 = time.perf_counter()
        results = []
        for desc in descs:
            low = desc.to_lowered(strip_agg=True)
            out = eng.execute_lowered_many([low], self.memstore,
                                           self.dataset, stats)[0]
            if out is not None:
                out.materialize()
            results.append(out)
        self.queries += len(descs)
        self.last_exec_s = time.perf_counter() - t0
        offsets = {s.shard_num: s.latest_offset
                   for s in self.memstore.shards_for(self.dataset)}
        return {"results": results, "offsets": offsets,
                "series": stats.series_scanned,
                "samples": stats.samples_scanned}

    def _status(self) -> dict:
        lo, hi = self.shard_range
        info = {"dataset": self.dataset, "shards": [lo, hi],
                "queries": self.queries, "last_exec_s": self.last_exec_s,
                "pid": os.getpid(),
                "offsets": {s.shard_num: s.latest_offset
                            for s in self.memstore.shards_for(self.dataset)}}
        eng = self._engine  # engine caches only once a query warmed them
        if eng is not None:
            info["devices"] = int(np_size(eng.mesh.devices)) \
                if eng.mesh is not None else 0
            info["descriptor_cache"] = len(eng._batch_cache)
            info["caches"] = {"batch": len(eng._batch_cache),
                              "programs": len(eng._fns),
                              "bounds": len(eng._bounds_cache),
                              "eval": len(eng._eval_cache),
                              "prep": len(eng._prep_cache)}
        else:
            info["devices"] = 0
            info["descriptor_cache"] = 0
            info["caches"] = {}
        return info

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "MeshWorker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()


def np_size(devices) -> int:
    import numpy as np

    return int(np.asarray(devices).size)


def _load_seed(spec: str):
    """``module:callable`` → the callable's return value (a fully
    ingested memstore)."""
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(f"--seed must be module:callable, got {spec!r}")
    obj = importlib.import_module(mod_name)
    for part in fn_name.split("."):
        obj = getattr(obj, part)
    return obj()


def _tail_shards(cfg, dataset: str, lo: int, hi: int):
    """Recover-then-tail the owned shard range from the shared WAL
    (read-only — the gateway/coordinator owns the append side)."""
    from filodb_tpu.coordinator.cluster import Node
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.kafka.log import SegmentedFileLog

    ms = TimeSeriesMemStore()
    node = Node(name=f"mesh-worker-{lo}-{hi}", memstore=ms)
    ing = cfg.datasets[dataset]
    root = cfg.wal_dir or os.path.join(cfg.data_dir, "wal")
    for shard in range(lo, hi):
        wal = SegmentedFileLog(os.path.join(root, dataset,
                                            f"shard-{shard}"),
                               read_only=True)
        node.start_shard(dataset, shard, ing, wal)
    return ms, node


def worker_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m filodb_tpu.parallel.multiproc",
        description="filodb mesh worker process (one mesh slice)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--dataset", required=True)
    ap.add_argument("--shards", required=True,
                    help="owned shard slice, lo:hi (half-open)")
    ap.add_argument("--num-shards", type=int, default=0,
                    help="global shard count (validation only)")
    ap.add_argument("--config", default=None,
                    help="server config JSON: tail the shared WAL")
    ap.add_argument("--seed", default=None,
                    help="module:callable returning an ingested memstore "
                    "(CI/benchmark harness)")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    lo_s, _, hi_s = args.shards.partition(":")
    lo, hi = int(lo_s), int(hi_s)
    if args.num_shards and not (0 <= lo < hi <= args.num_shards):
        raise SystemExit(f"shard slice {lo}:{hi} outside "
                         f"[0, {args.num_shards})")
    node = None
    if args.seed:
        store = _ShardSliceStore(_load_seed(args.seed), args.dataset, lo,
                                 hi)
    elif args.config:
        from filodb_tpu.config import ServerConfig

        cfg = ServerConfig.load(args.config)
        store, node = _tail_shards(cfg, args.dataset, lo, hi)
    else:
        raise SystemExit("one of --seed / --config is required")
    worker = MeshWorker(store, args.dataset, (lo, hi), host=args.host,
                        port=args.port).start()
    log.info("mesh worker serving %s[%d:%d) on %s:%d", args.dataset, lo,
             hi, args.host, worker.port)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        worker.stop()
        if node is not None:
            for shard in range(lo, hi):
                node.stop_shard(args.dataset, shard)
    return 0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class MeshWorkerSupervisor:
    """Spawns and supervises the N worker subprocesses of a multi-process
    mesh (the coordinator-address/N-process harness of the tentpole; on
    real hardware the pod scheduler owns process placement and this class
    only covers the local-launch path)."""

    def __init__(self, dataset: str, num_shards: int, workers: int,
                 base_port: int = 0, host: str = "127.0.0.1",
                 config_path: str | None = None, seed: str | None = None,
                 env: dict | None = None, python: str | None = None):
        if workers < 1:
            raise ValueError("need at least one mesh worker")
        self.dataset = dataset
        self.num_shards = num_shards
        self.host = host
        self.config_path = config_path
        self.seed = seed
        self.env = dict(env or {})
        self.python = python or sys.executable
        # contiguous near-equal slices tiling [0, num_shards)
        bounds = [round(i * num_shards / workers)
                  for i in range(workers + 1)]
        ports = [base_port + i if base_port else _free_port()
                 for i in range(workers)]
        self.slices = [(host, ports[i], (bounds[i], bounds[i + 1]))
                       for i in range(workers)]
        self.procs: list[subprocess.Popen] = []

    def spawn(self) -> "MeshWorkerSupervisor":
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               # one host device per process — the N×1 harness topology
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
               **self.env}
        for host, port, (lo, hi) in self.slices:
            cmd = [self.python, "-m", "filodb_tpu.parallel.multiproc",
                   "--host", host, "--port", str(port),
                   "--dataset", self.dataset,
                   "--shards", f"{lo}:{hi}",
                   "--num-shards", str(self.num_shards)]
            if self.seed:
                cmd += ["--seed", self.seed]
            elif self.config_path:
                cmd += ["--config", self.config_path]
            else:
                raise ValueError("supervisor needs seed or config_path")
            self.procs.append(subprocess.Popen(cmd, env=env))
        return self

    def addresses(self) -> list:
        return list(self.slices)

    def alive(self) -> list:
        return [p.poll() is None for p in self.procs]

    def wait_ready(self, timeout_s: float = 120.0) -> None:
        """Block until every worker answers a ping (device init + seed
        ingest happen before the socket accepts work in practice, but
        ping-ready is the contract; the runtime's staleness gate covers
        catch-up)."""
        from filodb_tpu.coordinator.mesh_cluster import MeshWorkerClient

        deadline = time.monotonic() + timeout_s
        for (host, port, _), proc in zip(self.slices, self.procs):
            cli = MeshWorkerClient(host, port, timeout=2.0)
            while not cli.ping():
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"mesh worker {host}:{port} exited with "
                        f"{proc.returncode} before becoming ready")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"mesh worker {host}:{port} not ready after "
                        f"{timeout_s}s")
                time.sleep(0.1)

    def stop(self, grace_s: float = 5.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + grace_s
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    sys.exit(worker_main())
