"""Adaptive two-lane query engine: device mesh + host lane, cost-routed.

The serving problem this solves: a query's end-to-end latency on an
accelerator is ``sync_floor + device_work``, where ``sync_floor`` is the
host↔device completion-notification latency. On co-located TPU hardware the
floor is ~0.1ms and every query belongs on the device; behind a
high-latency link (the axon tunnel measures ~70ms per blocking sync — see
``doc/serving_latency.md``) small scans are pure overhead on the device
lane while a host-backend evaluation of the SAME jitted kernels answers in
~1ms. Rather than hard-code either posture, this engine runs both lanes
behind one interface and routes each call to whichever lane is measured
faster for its batch-size bucket — so the same binary serves co-located
chips, tunneled chips, and CPU-only nodes at their respective optimum.

Reference boundary replaced: the reference has exactly one engine posture
(JVM iterators close to the data, ``QueryInMemoryBenchmark.scala:151-239``);
the two-lane design is what a TPU-native redesign needs to dominate it at
every concurrency level, not just under saturation.

Routing mechanics (all measurement, no configuration):

- per (lane, batch-size bucket) cost estimate in seconds/query, EWMA over
  post-warmup samples (each key's first sample is compilation-skewed and
  only seeds the estimate);
- the slower lane is re-probed by SHADOW traffic on a background worker —
  a duplicate of a live batch evaluated off the serving path — so estimates
  track workload drift, ingest churn, and tunnel weather without a single
  client ever paying the slow lane's latency (a bs=1 device probe through
  the tunnel would put the whole sync floor into that client's p99).
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from filodb_tpu.parallel.mesh_engine import MeshQueryEngine

log = logging.getLogger(__name__)

_BUCKETS = (1, 4, 16, 64, 256, 1024)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return _BUCKETS[-1]


def measure_sync_floor(device, tries: int = 3) -> float:
    """Median seconds for one dispatch→completion→fetch round trip of a
    trivial program on ``device`` — the per-sync latency floor any single
    blocking query pays on that backend. Indicative only (tunnel
    completion latency varies with traffic); routing uses live costs."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    # committed input pins execution to ``device``
    x = jax.device_put(jnp.zeros((8,), jnp.float32), device)
    f(x).block_until_ready()  # compile outside the timing
    samples = []
    for _ in range(tries):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


class _LaneCost:
    """Warmup-aware EWMA: the first sample of a key carries compilation
    and only seeds; later samples blend."""

    __slots__ = ("est", "n")

    def __init__(self):
        self.est = None
        self.n = 0

    def record(self, per_q: float, alpha: float = 0.3) -> None:
        self.n += 1
        if self.est is None or self.n <= 2:
            # seed and first post-warmup sample replace outright
            self.est = per_q
        else:
            self.est += alpha * (per_q - self.est)


class AdaptiveQueryEngine:
    """Drop-in for ``MeshQueryEngine`` in ``QueryService`` (same
    ``supports`` / ``execute`` / ``execute_many`` surface)."""

    SHADOW_EVERY = 32  # probe the slower lane once per N serving calls

    def __init__(self, mesh=None, variant: str = "gather"):
        self.device_engine = MeshQueryEngine(mesh=mesh, variant=variant)
        self._host_engine = None
        self._host_checked = False
        self._cost: dict[tuple, _LaneCost] = {}
        self._calls = 0
        self.sync_floor_s: float | None = None
        self.routed = {"device": 0, "host": 0}
        self.shadowed = {"device": 0, "host": 0}
        self._shadow_q: "queue.Queue|None" = None
        self._shadow_thread = None

    # -- MeshQueryEngine interface pass-throughs --

    def supports(self, plan) -> bool:
        return self.device_engine.supports(plan)

    @property
    def hits(self):
        return self.device_engine.hits

    @property
    def misses(self):
        return self.device_engine.misses

    # -- host lane construction --

    def _host(self):
        """Build the host lane lazily: a second mesh engine over the CPU
        backend, only when the default backend is NOT already the CPU (a
        CPU-only deployment has nothing to gain from a second copy)."""
        if self._host_checked:
            return self._host_engine
        self._host_checked = True
        try:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            default_platform = jax.devices()[0].platform
            if default_platform == "cpu":
                return None
            cpus = jax.devices("cpu")
            n = max(1, len(cpus))
            mesh = Mesh(np.array(cpus[:n]).reshape(n, 1), ("shard", "time"))
            self._host_engine = MeshQueryEngine(mesh=mesh)
            self.sync_floor_s = measure_sync_floor(jax.devices()[0])
            log.info("adaptive engine: host lane up (%d cpu), device sync "
                     "floor %.1fms", n, self.sync_floor_s * 1e3)
        except Exception:  # pragma: no cover — no cpu backend
            log.exception("host lane unavailable")
            self._host_engine = None
        return self._host_engine

    # -- routing --

    def _cost_of(self, lane: str, b: int) -> "_LaneCost":
        key = (lane, b)
        c = self._cost.get(key)
        if c is None:
            c = self._cost[key] = _LaneCost()
        return c

    def _route(self, n_queries: int) -> str:
        if self._host() is None:
            return "device"
        b = _bucket(n_queries)
        self._calls += 1
        dev = self._cost_of("device", b).est
        hst = self._cost_of("host", b).est
        if hst is None:
            # cold start: the host lane answers (it cannot be worse than
            # one tunnel sync by much, and a shadow probe prices the
            # device lane without any client waiting)
            return "host"
        if dev is None:
            return "host"
        return "device" if dev <= hst else "host"

    def _record(self, lane: str, n_queries: int, secs: float) -> None:
        self._cost_of(lane, _bucket(n_queries)).record(
            secs / max(n_queries, 1))

    # -- shadow probing --

    def _ensure_shadow_worker(self):
        if self._shadow_thread is None:
            self._shadow_q = queue.Queue(maxsize=1)

            def run():
                while True:
                    lane, lows, memstore, dataset = self._shadow_q.get()
                    try:
                        eng = self.device_engine if lane == "device" \
                            else self._host_engine
                        t0 = time.perf_counter()
                        outs = eng.execute_lowered_many(lows, memstore,
                                                        dataset)
                        for o in outs:
                            if o is not None:
                                o.materialize()
                        self._record(lane, len(lows),
                                     time.perf_counter() - t0)
                        self.shadowed[lane] += 1
                    except Exception:  # pragma: no cover
                        log.exception("shadow probe failed (%s)", lane)

            self._shadow_thread = threading.Thread(
                target=run, daemon=True, name="adaptive-shadow")
            self._shadow_thread.start()

    def _maybe_shadow(self, served_lane: str, plans: list, memstore,
                      dataset: str) -> None:
        """Duplicate this batch onto the OTHER lane off the serving path
        when its estimate is missing or stale-by-schedule. Never blocks;
        drops the probe if one is already in flight."""
        other = "host" if served_lane == "device" else "device"
        if other == "host" and self._host_engine is None:
            return
        b = _bucket(len(plans))
        due = self._cost_of(other, b).est is None \
            or self._calls % self.SHADOW_EVERY == 0
        if not due:
            return
        lows = [self.device_engine._lower(p) for p in plans]
        lows = [lo for lo in lows if lo is not None]
        if not lows:
            return
        self._ensure_shadow_worker()
        try:
            self._shadow_q.put_nowait((other, lows, memstore, dataset))
        except queue.Full:
            pass

    # -- execution --

    def execute(self, memstore, dataset: str, plan, stats=None):
        lane = self._route(1)
        eng = self.device_engine if lane == "device" else self._host_engine
        t0 = time.perf_counter()
        out = eng.execute(memstore, dataset, plan, stats)
        if out is not None:
            # the lane's true cost includes the result sync
            out.materialize()
            self._record(lane, 1, time.perf_counter() - t0)
            self.routed[lane] += 1
            self._maybe_shadow(lane, [plan], memstore, dataset)
        return out

    def execute_many(self, plans: list, memstore, dataset: str,
                     stats_list: list | None = None) -> list:
        lane = self._route(len(plans))
        eng = self.device_engine if lane == "device" else self._host_engine
        t0 = time.perf_counter()
        outs = eng.execute_many(plans, memstore, dataset, stats_list)
        done = [o for o in outs if o is not None]
        if done:
            for o in done:
                o.materialize()
            self._record(lane, len(done), time.perf_counter() - t0)
            self.routed[lane] += 1
            self._maybe_shadow(lane, plans, memstore, dataset)
        return outs
