"""Adaptive multi-lane query engine: sharded mesh, single-device, and
host lanes, cost-routed.

The serving problem this solves: a query's end-to-end latency on an
accelerator is ``sync_floor + device_work``, where ``sync_floor`` is the
host↔device completion-notification latency. On co-located TPU hardware the
floor is ~0.1ms and every query belongs on the device; behind a
high-latency link (the axon tunnel measures ~70ms per blocking sync — see
``doc/serving_latency.md``) small scans are pure overhead on the device
lane while a host-backend evaluation of the SAME jitted kernels answers in
~1ms. Rather than hard-code either posture, this engine runs both lanes
behind one interface and routes each call to whichever lane is measured
faster for its batch-size bucket — so the same binary serves co-located
chips, tunneled chips, and CPU-only nodes at their respective optimum.

Reference boundary replaced: the reference has exactly one engine posture
(JVM iterators close to the data, ``QueryInMemoryBenchmark.scala:151-239``);
the two-lane design is what a TPU-native redesign needs to dominate it at
every concurrency level, not just under saturation.

Routing mechanics (all measurement, no configuration):

- per (lane, batch-size bucket) cost estimate in seconds/query, EWMA over
  post-warmup samples (each key's first sample is compilation-skewed and
  only seeds the estimate);
- the slower lane is re-probed by SHADOW traffic on a background worker —
  a duplicate of a live batch evaluated off the serving path — so estimates
  track workload drift, ingest churn, and tunnel weather without a single
  client ever paying the slow lane's latency (a bs=1 device probe through
  the tunnel would put the whole sync floor into that client's p99).

Third lane — "single": when the default mesh spans multiple devices, a
one-device mesh engine over the same backend. The sharded SPMD form pays
per-call collective/dispatch overhead that a small batch never amortizes,
while a big scan wants every device; which batch size flips between them
is a property of the deployment (device count, interconnect, core count),
so it is measured per batch-size bucket exactly like device-vs-host, not
configured.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

import numpy as np

from filodb_tpu.parallel.mesh_engine import MeshQueryEngine, _M_ROUTED

log = logging.getLogger(__name__)

_BUCKETS = (1, 4, 16, 64, 256, 1024)


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return _BUCKETS[-1]


def measure_sync_floor(device, tries: int = 3) -> float:
    """Median seconds for one dispatch→completion→fetch round trip of a
    trivial program on ``device`` — the per-sync latency floor any single
    blocking query pays on that backend. Indicative only (tunnel
    completion latency varies with traffic); routing uses live costs."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    # committed input pins execution to ``device``
    x = jax.device_put(jnp.zeros((8,), jnp.float32), device)
    f(x).block_until_ready()  # compile outside the timing
    samples = []
    for _ in range(tries):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


class _LaneCost:
    """Warmup-aware EWMA: the first sample of a key carries compilation
    and only seeds; later samples blend."""

    __slots__ = ("est", "n")

    def __init__(self):
        self.est = None
        self.n = 0

    def record(self, per_q: float, alpha: float = 0.3) -> None:
        self.n += 1
        if self.est is None or self.n <= 2:
            # seed and first post-warmup sample replace outright
            self.est = per_q
        else:
            self.est += alpha * (per_q - self.est)


class AdaptiveQueryEngine:
    """Drop-in for ``MeshQueryEngine`` in ``QueryService`` (same
    ``supports`` / ``execute`` / ``execute_many`` surface)."""

    SHADOW_EVERY = 32  # probe the slower lane once per N serving calls

    def __init__(self, mesh=None, variant: str = "gather",
                 sidecars: bool = False):
        # sidecar delegation decides at the top (device lane's _lower):
        # declined plans route to the exec leaf before lane selection runs,
        # so the inner host/single lanes never see them
        self.device_engine = MeshQueryEngine(mesh=mesh, variant=variant,
                                             sidecars=sidecars)
        self._host_engine = None
        self._host_checked = False
        self._single_engine = None
        self._single_checked = False
        self._cost: dict[tuple, _LaneCost] = {}
        self._calls = 0
        self._dataset = ""  # bound on first execute; keys the shared model
        self.sync_floor_s: float | None = None
        self.routed = {"device": 0, "single": 0, "host": 0}
        self.shadowed = {"device": 0, "single": 0, "host": 0}
        self._shadow_q: "queue.Queue|None" = None
        self._shadow_thread = None

    # -- MeshQueryEngine interface pass-throughs --

    def supports(self, plan) -> bool:
        return self.device_engine.supports(plan)

    @property
    def hits(self):
        return self.device_engine.hits

    @property
    def misses(self):
        return self.device_engine.misses

    # -- host lane construction --

    def _host(self):
        """Build the host lane lazily: a second mesh engine over the CPU
        backend, only when the default backend is NOT already the CPU (a
        CPU-only deployment has nothing to gain from a second copy)."""
        if self._host_checked:
            return self._host_engine
        self._host_checked = True
        try:
            import jax
            import numpy as np
            from jax.sharding import Mesh

            default_platform = jax.devices()[0].platform
            if default_platform == "cpu":
                return None
            cpus = jax.devices("cpu")
            n = max(1, len(cpus))
            mesh = Mesh(np.array(cpus[:n]).reshape(n, 1), ("shard", "time"))
            self._host_engine = MeshQueryEngine(mesh=mesh)
            self.sync_floor_s = measure_sync_floor(jax.devices()[0])
            log.info("adaptive engine: host lane up (%d cpu), device sync "
                     "floor %.1fms", n, self.sync_floor_s * 1e3)
        except Exception:  # pragma: no cover — no cpu backend
            log.exception("host lane unavailable")
            self._host_engine = None
        return self._host_engine

    def _single(self):
        """Build the single-device lane lazily: a mesh engine pinned to a
        1×1 mesh on the default backend, only meaningful when the sharded
        mesh actually spans more than one device."""
        if self._single_checked:
            return self._single_engine
        self._single_checked = True
        try:
            from filodb_tpu.parallel.mesh_engine import make_query_mesh

            mesh = self.device_engine._ensure_mesh()
            if int(np.prod(list(mesh.shape.values()))) > 1:
                self._single_engine = MeshQueryEngine(
                    mesh=make_query_mesh(n_devices=1, time_axis=1))
                log.info("adaptive engine: single-device lane up")
        except Exception:  # pragma: no cover — device init failure
            log.exception("single-device lane unavailable")
            self._single_engine = None
        return self._single_engine

    # -- routing --

    def _lanes(self) -> list:
        lanes = ["device"]
        if self._single() is not None:
            lanes.append("single")
        if self._host() is not None:
            lanes.append("host")
        return lanes

    def _engine_for(self, lane: str):
        return {"device": self.device_engine, "single": self._single_engine,
                "host": self._host_engine}[lane]

    def _cost_of(self, lane: str, b: int) -> "_LaneCost":
        key = (lane, b)
        c = self._cost.get(key)
        if c is None:
            c = self._cost[key] = _LaneCost()
        return c

    def _route(self, n_queries: int) -> str:
        lanes = self._lanes()
        if len(lanes) == 1:
            return "device"
        b = _bucket(n_queries)
        self._calls += 1
        ests = {la: self._cost_of(la, b).est for la in lanes}
        known = {la: e for la, e in ests.items() if e is not None}
        if not known:
            # cold start: the cheapest-dispatch lane answers (host behind
            # a tunnel, else the single-device lane — neither pays the
            # sharded form's collective overhead) and shadow probes price
            # the others without any client waiting
            return "host" if "host" in lanes else "single"
        return min(known, key=known.get)

    def _record(self, lane: str, n_queries: int, secs: float) -> None:
        per_q = secs / max(n_queries, 1)
        b = _bucket(n_queries)
        self._cost_of(lane, b).record(per_q)
        # mirror into the shared cost model ("lane" decision site): same
        # EWMA semantics, but there the estimates persist through the
        # metastore and surface in coststats/calibration metrics
        from filodb_tpu.query import cost_model as cm
        cm.model_for(self._dataset).observe("lane", f"b{b}", lane, per_q)

    # -- shadow probing --

    def _ensure_shadow_worker(self):
        if self._shadow_thread is None:
            self._shadow_q = queue.Queue(maxsize=1)

            def run():
                while True:
                    lane, lows, memstore, dataset = self._shadow_q.get()
                    try:
                        eng = self._engine_for(lane)
                        t0 = time.perf_counter()
                        outs = eng.execute_lowered_many(lows, memstore,
                                                        dataset)
                        for o in outs:
                            if o is not None:
                                o.materialize()
                        self._record(lane, len(lows),
                                     time.perf_counter() - t0)
                        self.shadowed[lane] += 1
                    except Exception:  # pragma: no cover
                        log.exception("shadow probe failed (%s)", lane)

            self._shadow_thread = threading.Thread(
                target=run, daemon=True, name="adaptive-shadow")
            self._shadow_thread.start()

    def _maybe_shadow(self, served_lane: str, plans: list, memstore,
                      dataset: str) -> None:
        """Duplicate this batch onto ANOTHER lane off the serving path
        when its estimate is missing or stale-by-schedule (rotating through
        the others on schedule). Never blocks; drops the probe if one is
        already in flight."""
        others = [la for la in self._lanes() if la != served_lane]
        if not others:
            return
        b = _bucket(len(plans))
        missing = [la for la in others
                   if self._cost_of(la, b).est is None]
        if missing:
            other = missing[0]
        elif self._calls % self.SHADOW_EVERY == 0:
            other = others[(self._calls // self.SHADOW_EVERY) % len(others)]
        else:
            return
        lows = [self.device_engine._lower(p) for p in plans]
        lows = [lo for lo in lows if lo is not None]
        if not lows:
            return
        self._ensure_shadow_worker()
        try:
            self._shadow_q.put_nowait((other, lows, memstore, dataset))
        except queue.Full:
            pass

    # -- execution --

    def _shared_decision(self, lane: str, n_queries: int):
        """PR 14's local router stays authoritative while the shared model
        is cold (its pick is the decision's *static* arm); once the shared
        model has min_samples on every lane — mirrored serves, shadow
        probes, or estimates restored from the metastore — its
        predicted-cheapest lane wins. Identical update rules mean the two
        agree whenever both are warm, so behavior only changes when
        persistence knows something the fresh process doesn't."""
        lanes = self._lanes()
        if len(lanes) == 1:
            return lane, None, None
        from filodb_tpu.query import cost_model as cm
        model = cm.model_for(self._dataset)
        d = model.decide("lane", f"b{_bucket(n_queries)}", tuple(lanes),
                         static_arm=lane)
        # settle: the caller records the serve through record_actual
        # (observe=False; _record already mirrored the sample)
        return d.arm, d, model

    def execute(self, memstore, dataset: str, plan, stats=None):
        self._dataset = dataset
        lane, d, model = self._shared_decision(self._route(1), 1)
        eng = self._engine_for(lane)
        t0 = time.perf_counter()
        out = eng.execute(memstore, dataset, plan, stats)
        if out is not None:
            # the lane's true cost includes the result sync
            out.materialize()
            dt = time.perf_counter() - t0
            self._record(lane, 1, dt)
            if d is not None:
                model.record_actual(d, dt, observe=False)
            self.routed[lane] += 1
            _M_ROUTED[lane].inc()
            self._maybe_shadow(lane, [plan], memstore, dataset)
        return out

    def execute_many(self, plans: list, memstore, dataset: str,
                     stats_list: list | None = None) -> list:
        self._dataset = dataset
        lane, d, model = self._shared_decision(self._route(len(plans)),
                                               len(plans))
        eng = self._engine_for(lane)
        t0 = time.perf_counter()
        outs = eng.execute_many(plans, memstore, dataset, stats_list)
        done = [o for o in outs if o is not None]
        if done:
            for o in done:
                o.materialize()
            dt = time.perf_counter() - t0
            self._record(lane, len(done), dt)
            if d is not None:
                model.record_actual(d, dt / max(len(done), 1),
                                    observe=False)
            self.routed[lane] += 1
            _M_ROUTED[lane].inc()
            self._maybe_shadow(lane, plans, memstore, dataset)
        return outs
