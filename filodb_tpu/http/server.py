"""HTTP server exposing the Prometheus API + cluster admin routes.

Counterpart of reference ``FiloHttpServer.scala`` route composition
(endpoints per ``doc/http_api.md``):

- ``GET /promql/{dataset}/api/v1/query_range?query=&start=&end=&step=``
- ``GET/POST /promql/{dataset}/api/v1/query?query=&time=``
- ``GET /promql/{dataset}/api/v1/series?match[]=&start=&end=``
- ``GET /promql/{dataset}/api/v1/labels``
- ``GET /promql/{dataset}/api/v1/label/{name}/values``
- ``GET /api/v1/cluster/{dataset}/status`` (shard statuses)
- ``GET /__health``, ``GET /metrics`` (Prometheus exposition)

Two server fronts share one ``HttpDispatcher`` (all routing/rendering):

- ``FiloHttpServer`` — threaded stdlib server (one thread per connection);
  queries run on the request thread through the ``QueryBatcher``.
- ``filodb_tpu.http.fastserver.FastHttpServer`` — single-threaded selector
  event loop that coalesces every hot query parsed in one readiness pass
  into a single ``query_range_many`` engine batch (the serving-side analog
  of inference micro-batching, and the default standalone front end).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.http import promjson
from filodb_tpu.promql.parser import ParseError, TimeStepParams, parse_query
# imported for the side effect of registering the federation + ODP metric
# families at boot, so /metrics exposes them even before the first
# federated query (scrape-breadth test relies on this)
from filodb_tpu.query import federation as _federation  # noqa: F401
from filodb_tpu.query.model import QueryLimitExceeded
from filodb_tpu.utils.governor import QueryRejected
from filodb_tpu.utils.metrics import render_prometheus
from filodb_tpu.utils.resilience import DeadlineExceeded

log = logging.getLogger(__name__)

JSON_CT = "application/json"


def retry_after_headers(after_s: float | None = None) -> dict:
    """``Retry-After`` for 503/429 sheds, shared by both server fronts.
    The header carries whole seconds (RFC 9110), never less than 1."""
    if after_s is None:
        from filodb_tpu.utils.governor import config as governor_config
        after_s = governor_config().retry_after_s
    return {"Retry-After": str(max(1, int(round(float(after_s)))))}


class ResponseCache:
    """Rendered-response cache for hot query endpoints, invalidated by the
    dataset's ingest data_version (the query-frontend pattern: Prometheus
    deployments put an equivalent cache — Thanos/Cortex query-frontend — in
    front of the reference; here it is built in). Keys are the RESOLVED
    query parameters, so an instant query defaulting to server time never
    aliases across seconds. A version bump (any ingest into any shard of
    the dataset) orphans every entry for that service.

    Layering with the extent result cache
    (``filodb_tpu/query/result_cache.py``): this cache sits OUTSIDE it and
    memoizes fully-rendered JSON bytes — a hit here skips parse, execute,
    and render, but only for byte-identical requests against an unchanged
    dataset (idle servers, repeated panels). Under live ingest the version
    stamp bumps every row and this cache contributes nothing; the extent
    cache below still answers the immutable bulk of each query and
    recomputes only the mutable head."""

    def __init__(self, cap: int = 1024):
        from collections import OrderedDict
        self.cap = cap
        self.hits = 0
        self.misses = 0
        self._lru: "OrderedDict[tuple, tuple[int, bytes]]" = OrderedDict()
        # the threaded front mutates from concurrent handler threads
        self._lock = threading.Lock()

    def get(self, key: tuple, version: int) -> bytes | None:
        with self._lock:
            entry = self._lru.get(key)
            if entry is None or entry[0] != version:
                self.misses += 1
                return None
            self._lru.move_to_end(key)
            self.hits += 1
            return entry[1]

    def put(self, key: tuple, version: int, body: bytes) -> None:
        with self._lock:
            while len(self._lru) >= self.cap:
                self._lru.popitem(last=False)
            self._lru[key] = (version, body)


def service_version(svc) -> int | None:
    """Cache-invalidation stamp: total ingest progress across the
    dataset's shards (bumps on every applied write).

    Returns ``None`` when the service does not host every shard of the
    dataset locally — in that case some query results come from remote
    members whose ingest never bumps these local versions, so the stamp
    cannot witness staleness and the response cache must be bypassed."""
    shards = svc.memstore.shards_for(svc.dataset)
    if len(shards) < getattr(svc, "num_shards", 1):
        return None
    return sum(s.data_version for s in shards)


def response_cache_key(svc, kind: str, params: tuple) -> tuple:
    """Canonical response-cache key, shared by both fronts so entries are
    keyed identically regardless of which server parsed the request.
    ``params`` is (query, start, step, end) for ranges; instant queries
    key on (query, resolved_time) — extra positions are ignored.

    Services are identified by their monotonic construction ``serial``,
    never ``id()``: a new service allocated at a freed service's address
    would alias its cache entries (stale responses for a different
    dataset/epoch)."""
    serial = getattr(svc, "serial", None)
    if serial is None:  # not `or`: a legitimate serial of 0 must not
        serial = id(svc)  # fall back to an aliasable address

    if kind == "instant":
        return (serial, "instant", params[0], params[1])
    return (serial, "range", *params)


def parse_time(s: str) -> float:
    """Unix seconds (float) or RFC3339 (Grafana sends either)."""
    try:
        return float(s)
    except ValueError:
        import datetime as dt
        return dt.datetime.fromisoformat(s.replace("Z", "+00:00")) \
            .timestamp()


class HttpDispatcher:
    """All route handling, shared by the threaded and event-loop fronts.

    ``handle`` never raises: every outcome is a ``(status, headers, body)``
    triple, with errors rendered as Prom-style JSON error envelopes."""

    def __init__(self, app: "FiloHttpServer"):
        self.app = app

    # -- entry --

    def handle(self, command: str, path: str, raw: bytes = b"",
               content_type: str = "") -> tuple[int, dict, bytes]:
        try:
            url = urlparse(path)
            qs = parse_qs(url.query)
            parts = [p for p in url.path.split("/") if p]
            if command == "POST":
                if parts[-1:] == ["read"]:
                    return self._remote_read(parts, raw)
                if raw and "x-www-form-urlencoded" in content_type:
                    for k, v in parse_qs(raw.decode()).items():
                        qs.setdefault(k, v)
            return self._dispatch(parts, qs)
        except (ParseError, ValueError) as e:
            return self._json(400, promjson.error_json(str(e)))
        except QueryLimitExceeded as e:
            return self._json(422, promjson.error_json(str(e), "query_limit"))
        except QueryRejected as e:
            # shed by the admission gate (local or a remote peer's): 503 +
            # Retry-After with a DISTINCT errorType from a timeout, so
            # clients back off instead of hammering an overloaded node
            return self._json(503,
                              promjson.error_json(str(e), "unavailable"),
                              headers=retry_after_headers(e.retry_after_s))
        except DeadlineExceeded as e:
            return self._json(503, promjson.error_json(str(e), "timeout"),
                              headers=retry_after_headers())
        except Exception as e:  # pragma: no cover
            log.exception("request failed")
            return self._json(500, promjson.error_json(str(e), "internal"))

    # -- helpers --

    @staticmethod
    def _json(code: int, payload,
              headers: dict | None = None) -> tuple[int, dict, bytes]:
        body = payload.encode() if isinstance(payload, str) \
            else json.dumps(payload).encode()
        h = {"Content-Type": JSON_CT}
        if headers:
            h.update(headers)
        return code, h, body

    # -- routing --

    def _dispatch(self, parts: list[str], qs: dict):
        if parts == ["__health"]:
            return self._json(200, {"status": "healthy"})
        if parts == ["metrics"]:
            return (200, {"Content-Type": "text/plain; version=0.0.4"},
                    render_prometheus().encode())
        if len(parts) >= 4 and parts[0] == "promql" \
                and parts[2] == "api" and parts[3] == "v1":
            dataset = parts[1]
            svc = self.app.services.get(dataset)
            if svc is None:
                return self._json(404, promjson.error_json(
                    f"unknown dataset {dataset}"))
            return self._prom_api(svc, parts[4:], qs)
        if len(parts) >= 3 and parts[0] == "api" and parts[1] == "v1" \
                and parts[2] == "cluster":
            return self._cluster_api(parts[3:], qs)
        if parts == ["api", "v1", "rules"]:
            # top-level Prom-compat view aggregating every dataset's groups
            groups = []
            for mgr in self._rule_managers().values():
                groups.extend(mgr.rules_snapshot())
            return self._json(200, {"status": "success",
                                    "data": {"groups": groups}})
        if parts == ["api", "v1", "alerts"]:
            alerts = []
            for mgr in self._rule_managers().values():
                alerts.extend(mgr.alerts_snapshot())
            return self._json(200, {"status": "success",
                                    "data": {"alerts": alerts}})
        if parts == ["api", "v1", "status", "tsdb"]:
            return self._status_tsdb(qs)
        if parts == ["api", "v1", "status", "ingest"]:
            return self._status_ingest(qs)
        if parts == ["api", "v1", "status", "tiers"]:
            return self._status_tiers(qs)
        if parts == ["api", "v1", "status", "mesh"]:
            return self._status_mesh(qs)
        return self._json(404, promjson.error_json("not found", "not_found"))

    def _rule_managers(self) -> dict:
        return getattr(self.app, "rule_managers", None) or {}

    # -- status introspection --

    def _status_datasets(self, qs: dict) -> dict:
        """Services filtered by an optional ``?dataset=`` param."""
        want = qs.get("dataset", [None])[0]
        return {name: svc for name, svc in self.app.services.items()
                if want is None or name == want}

    def _status_tsdb(self, qs: dict):
        """Prometheus-shaped TSDB status: per-shard head/memory stats plus
        top-k series cardinality by metric name (from the shard-key
        cardinality trees) and by label name (distinct values from the
        part-key indexes)."""
        try:
            k = max(1, int(qs.get("topk", ["10"])[0]))
        except ValueError:
            k = 10
        data = {}
        for name, svc in self._status_datasets(qs).items():
            by_metric: dict[str, dict] = {}
            by_label: dict[str, int] = {}
            shards = []
            num_series = 0
            for sh in svc.memstore.shards_for(name):
                # the cardinality tree root counts every live series,
                # including ones created inside the native ingest core
                # that never touch the python key map
                root = sh.cardinality.cardinality([])
                num_series += root.active_ts
                shards.append({
                    "shard": sh.shard_num,
                    "numSeries": root.active_ts,
                    "totalSeries": root.total_ts,
                    "indexRamBytes": sh.index.ram_bytes,
                    "encodedBytes": sh.stats.encoded_bytes.value,
                    "samplesEncoded": sh.stats.samples_encoded.value,
                    "chunksFlushed": sh.stats.chunks_flushed.value,
                    "partitionsEvicted":
                        sh.stats.partitions_evicted.value,
                })
                tracker = sh.cardinality
                # tree walk ws -> ns -> metric; aggregate metric counts
                # across prefixes and shards, Prometheus-status style
                for ws in tracker.top_k([], 1000):
                    for ns in tracker.top_k([ws.name], 1000):
                        for mc in tracker.top_k([ws.name, ns.name], 1000):
                            agg = by_metric.setdefault(
                                mc.name, {"active": 0, "total": 0})
                            agg["active"] += mc.active_ts
                            agg["total"] += mc.total_ts
                for label in sh.label_names():
                    by_label[label] = max(by_label.get(label, 0),
                                          len(sh.label_values(label)))
            top_metrics = sorted(by_metric.items(),
                                 key=lambda kv: -kv[1]["active"])[:k]
            top_labels = sorted(by_label.items(),
                                key=lambda kv: -kv[1])[:k]
            data[name] = {
                "headStats": {"numSeries": num_series,
                              "numShards": len(shards)},
                "shards": shards,
                "seriesCountByMetricName": [
                    {"name": m, "value": v["active"],
                     "totalValue": v["total"]} for m, v in top_metrics],
                "labelValueCountByLabelName": [
                    {"name": label, "value": v} for label, v in top_labels],
            }
        return self._json(200, {"status": "success", "data": data})

    def _status_tiers(self, qs: dict):
        """Per-dataset retention-tier map: which tiers exist (memstore /
        downsample / objectstore), their time floors, and per-tier
        series/bytes — the introspection face of query federation."""
        from filodb_tpu.query import federation
        data = {name: federation.tier_status(name, svc)
                for name, svc in self._status_datasets(qs).items()}
        return self._json(200, {"status": "success", "data": data})

    def _status_mesh(self, qs: dict):
        """Multi-process mesh runtime status: per-worker mesh slice,
        device count, descriptor-cache occupancy, last collective
        latency (``filo-cli meshstat``). Datasets without a runtime
        report ``multiproc: false`` with single-process engine info."""
        data = {}
        for name, svc in self._status_datasets(qs).items():
            rt = getattr(svc, "mesh_cluster", None)
            if rt is not None:
                entry = dict(rt.status())
                entry["multiproc"] = True
            else:
                entry = {"multiproc": False}
            eng = getattr(svc, "mesh_engine", None)
            if eng is not None:
                entry["engine"] = {"hits": eng.hits, "misses": eng.misses,
                                   "batch_cache": len(eng._batch_cache),
                                   "programs": len(eng._fns)}
            data[name] = entry
        return self._json(200, {"status": "success", "data": data})

    def _status_ingest(self, qs: dict):
        """Per-shard ingest freshness: lag vs wall clock, replay-log
        offsets, checkpoint watermarks, write-behind queue state, rules
        watermark lag, and the ingest-side slow-operation ring."""
        import time as _time
        from filodb_tpu.core.store import objectstore as objstore
        from filodb_tpu.utils import metrics as metrics_mod
        from filodb_tpu.utils.tracing import slow_ingest
        cluster = getattr(self.app, "cluster", None)
        now = _time.time()
        try:
            limit = int(qs.get("limit", ["20"])[0])
        except ValueError:
            limit = 20
        data = {"datasets": {}}
        for name, svc in self._status_datasets(qs).items():
            shards = []
            for sh in svc.memstore.shards_for(name):
                lag = (None if sh.max_ingested_ts < 0
                       else max(0.0, now - sh.max_ingested_ts / 1000.0))
                entry = {
                    "shard": sh.shard_num,
                    "maxIngestedTs": sh.max_ingested_ts,
                    "ingestLagSeconds": lag,
                    "ingestedOffset": sh.latest_offset,
                    "groupWatermarks": list(sh.group_watermarks),
                }
                log_ = (cluster.logs.get((name, sh.shard_num))
                        if cluster is not None else None)
                if log_ is not None:
                    entry["logLatestOffset"] = log_.latest_offset
                    entry["offsetLag"] = log_.offset_lag(sh.latest_offset)
                    entry["checkpointLag"] = log_.offset_lag(
                        min(sh.group_watermarks, default=-1))
                shards.append(entry)
            data["datasets"][name] = {"shards": shards}
        data["objectstore"] = {
            "queueDepth": objstore.QUEUE_DEPTH.value,
            "oldestTaskAgeSeconds": objstore._oldest_task_age(),
        }
        # gauges owned by objects this server can't reach (gateway sink,
        # rule groups) are read back from the registry by family name
        with metrics_mod._lock:
            fams = list(metrics_mod._registry.values())
        for m in fams:
            if m.name == "gateway_queue_depth" and m.value is not None:
                data["gatewayQueueDepth"] = m.value
            elif m.name == "filodb_rules_watermark_lag_seconds" \
                    and m.tags.get("group"):  # skip the untagged anchor
                data.setdefault("rulesWatermarkLagSeconds", {})[
                    m.tags["group"]] = m.value
        data["slowIngest"] = slow_ingest(limit)
        return self._json(200, {"status": "success", "data": data})

    # -- Prom API --

    @staticmethod
    def range_params(qs: dict) -> tuple[str, int, int, int]:
        """(query, start, step, end) for a query_range request."""
        return (qs["query"][0], int(parse_time(qs["start"][0])),
                int(float(qs.get("step", ["60"])[0])),
                int(parse_time(qs["end"][0])))

    @staticmethod
    def instant_params(qs: dict) -> tuple[str, int]:
        """(query, time) for an instant query request."""
        if "time" in qs:
            t = int(parse_time(qs["time"][0]))
        else:
            # Prometheus defaults instant queries to server time
            import time as _time
            t = int(_time.time())
        return qs["query"][0], t

    def _cached_query(self, svc: QueryService, kind: str, params: tuple,
                      full_stats: bool = False):
        """Hot query with the rendered-response cache around it."""
        cache = self.app.response_cache
        key = version = None
        if cache is not None:
            version = service_version(svc)
            if version is None:
                cache = None  # remote shards: stamp can't witness staleness
            else:
                key = response_cache_key(svc, kind, params)
                if full_stats:
                    # ?stats=all renders a different body — distinct entry
                    key = key + ("stats",)
                body = cache.get(key, version)
                if body is not None:
                    return 200, {"Content-Type": JSON_CT}, body
        r = self.app.batched(svc).query_range(*params)
        rendered = promjson.matrix_json_str(r, full_stats=full_stats) \
            if kind == "range" \
            else promjson.vector_json_str(r, with_stats=full_stats)
        out = self._json(200, rendered)
        if cache is not None:
            cache.put(key, version, out[2])
        return out

    @staticmethod
    def _want_stats(qs: dict) -> bool:
        return qs.get("stats", [""])[0] == "all"

    def _prom_api(self, svc: QueryService, rest: list[str], qs: dict):
        if rest == ["query_range"]:
            params = self.range_params(qs)
            return self._cached_query(svc, "range", params,
                                      full_stats=self._want_stats(qs))
        if rest == ["query"]:
            query, t = self.instant_params(qs)
            return self._cached_query(svc, "instant", (query, t, 0, t),
                                      full_stats=self._want_stats(qs))
        if rest == ["series"]:
            matches = qs.get("match[]", [])
            start = int(parse_time(qs.get("start", ["0"])[0]))
            end = int(parse_time(qs.get("end", ["9999999999"])[0]))
            out = []
            for mtext in matches:
                plan = parse_query(mtext, TimeStepParams(start, 0, end))
                raw = getattr(plan, "raw", None)
                filters = raw.filters if raw is not None else ()
                for lm in svc.series(list(filters), start, end):
                    out.append({("__name__" if k == "_metric_" else k): v
                                for k, v in lm.items()})
            return self._json(200, {"status": "success", "data": out})
        if rest == ["labels"]:
            names = [("__name__" if n == "_metric_" else n)
                     for n in svc.memstore.label_names(svc.dataset)]
            return self._json(200, {"status": "success", "data": names})
        if len(rest) == 3 and rest[0] == "label" and rest[2] == "values":
            label = unquote(rest[1])
            if label == "__name__":
                label = "_metric_"
            vals = svc.memstore.label_values(svc.dataset, label)
            return self._json(200, {"status": "success", "data": vals})
        if rest == ["rules"]:
            mgr = self._rule_managers().get(svc.dataset)
            groups = mgr.rules_snapshot() if mgr is not None else []
            return self._json(200, {"status": "success",
                                    "data": {"groups": groups}})
        if rest == ["alerts"]:
            mgr = self._rule_managers().get(svc.dataset)
            alerts = mgr.alerts_snapshot() if mgr is not None else []
            return self._json(200, {"status": "success",
                                    "data": {"alerts": alerts}})
        if rest == ["debug", "trace"]:
            # span-traced execution (reference: Kamon spans around exec,
            # ExecPlan.scala:101 / startODPSpan — surfaced here as JSON
            # instead of a zipkin reporter). Force-samples this one query:
            # the active trace is joined by traced_query(), so remote
            # children ship their span trees back and they land here too.
            from filodb_tpu.utils.tracing import start_trace
            if "start" in qs:
                query, start, step, end = self.range_params(qs)
            else:
                query, t = self.instant_params(qs)
                start, step, end = t, 0, t
            with start_trace() as trace:
                r = svc.query_range(query, start, step, end)
            return self._json(200, {
                "status": "success",
                "data": {"spans": trace.as_dicts(),
                         "result_series": r.result.num_series,
                         "stats": {
                             "series_scanned": r.stats.series_scanned,
                             "samples_scanned": r.stats.samples_scanned,
                             "wall_time_s": r.stats.wall_time_s,
                         }}})
        if rest == ["debug", "slow_queries"]:
            # slow-query flight recorder: bounded ring of queries (and
            # traced operations) that exceeded slow_query_threshold_ms,
            # newest first, full span tree + stats when sampled
            from filodb_tpu.utils.tracing import slow_queries
            try:
                limit = int(qs.get("limit", ["0"])[0])
            except ValueError:
                limit = 0
            entries = [e for e in slow_queries()
                       if e.get("dataset") in (None, svc.dataset)]
            if limit > 0:
                entries = entries[:limit]
            return self._json(200, {"status": "success",
                                    "data": {"slow_queries": entries}})
        if rest == ["debug", "costmodel"]:
            # adaptive-planner introspection: per-site estimates with
            # warm state, calibration error, and recent predicted-vs-
            # actual pairs (served by `filo-cli coststats`)
            from filodb_tpu.query import cost_model
            model = cost_model.model_for(svc.dataset)
            snap = model.snapshot()
            try:
                limit = int(qs.get("limit", ["0"])[0])
            except ValueError:
                limit = 0
            if limit > 0:
                snap["estimates"] = snap["estimates"][:limit]
            return self._json(200, {"status": "success", "data": snap})
        return self._json(404, promjson.error_json("unknown endpoint"))

    def _remote_read(self, parts: list[str], body: bytes):
        """Prometheus remote-read (protobuf; reference remote-storage
        protocol endpoint in PrometheusApiRoute)."""
        from filodb_tpu.http import remote_read as rr
        if len(parts) < 2 or parts[0] != "promql":
            return self._json(404, promjson.error_json("not found"))
        svc = self.app.services.get(parts[1])
        if svc is None:
            return self._json(404, promjson.error_json(
                f"unknown dataset {parts[1]}"))
        data = rr.maybe_decompress(body)
        try:
            queries = rr.decode_read_request(data)
        except Exception:
            return self._json(501 if not rr.HAVE_SNAPPY else 400,
                              promjson.error_json(
                                  "could not decode read request "
                                  "(snappy unavailable?)"))
        results = []
        for q in queries:
            series = []
            for shard in svc.memstore.shards_for(svc.dataset):
                for pid in shard.lookup_partitions(
                        q["filters"], q["start_ms"], q["end_ms"]):
                    part = shard.partition(pid)
                    if part is None:
                        continue
                    ts, vals = part.read_samples(q["start_ms"], q["end_ms"])
                    import numpy as _np
                    if len(ts) and not isinstance(vals, _np.ndarray):
                        continue  # histograms not in remote-read v1
                    series.append((list(part.part_key.labels), ts, vals))
            results.append(series)
        payload = rr.maybe_compress(rr.encode_read_response(results))
        return (200, {"Content-Type": "application/x-protobuf",
                      "Content-Encoding":
                          "snappy" if rr.HAVE_SNAPPY else "identity"},
                payload)

    # -- cluster admin --

    def _cluster_api(self, rest: list[str], qs: dict):
        cluster = self.app.cluster
        if not rest:
            return self._json(200, {"status": "success",
                                    "data": list(self.app.services)})
        dataset = rest[0]
        if len(rest) == 2 and rest[1] in ("startshards", "stopshards") \
                and cluster is not None:
            # reference ClusterApiRoute start/stop shards commands
            from filodb_tpu.coordinator.shardmapper import (
                ShardEvent,
                ShardStatus,
            )
            shards = [int(s) for s in
                      qs.get("shards", [""])[0].split(",") if s]
            node = qs.get("node", [None])[0]
            sm = cluster.shard_managers.get(dataset)
            if sm is None:
                return self._json(404, promjson.error_json(
                    f"unknown dataset {dataset}"))
            done = []
            for shard in shards:
                if rest[1] == "stopshards":
                    owner = sm.mapper.node_for(shard)
                    if owner and owner in cluster.nodes:
                        cluster.nodes[owner].stop_shard(dataset, shard)
                        sm._publish(ShardEvent(shard, ShardStatus.STOPPED,
                                               None))
                        done.append(shard)
                else:
                    target = node or next(iter(cluster.nodes), None)
                    if target:
                        ev = ShardEvent(shard, ShardStatus.ASSIGNED, target)
                        sm._publish(ev)
                        cluster._on_event(dataset, ev)
                        done.append(shard)
            return self._json(200, {"status": "success", "data": done})
        if len(rest) == 2 and rest[1] == "status":
            if cluster is not None:
                data = cluster.shard_statuses(dataset)
            elif dataset in self.app.shard_maps:
                # member: serve the coordinator's state from the local
                # mirror (sequenced subscription with resync)
                data = self.app.shard_maps[dataset]().snapshot()
            else:
                svc = self.app.services.get(dataset)
                data = [{"shard": s.shard_num, "status": "active",
                         "numPartitions": s.num_partitions}
                        for s in svc.memstore.shards_for(dataset)] \
                    if svc else []
            return self._json(200, {"status": "success", "data": data})
        if len(rest) == 2 and rest[1] == "shardmap":
            return self._shardmap(dataset)
        if len(rest) == 2 and rest[1] == "migrate" and cluster is not None:
            try:
                shard = int(qs.get("shard", [""])[0])
            except ValueError:
                return self._json(400,
                                  promjson.error_json("shard must be an int"))
            dest = qs.get("dest", [""])[0]
            if not dest:
                return self._json(400, promjson.error_json("dest required"))
            import threading

            def _run():
                try:
                    cluster.migrate_shard(dataset, shard, dest)
                except Exception:
                    import logging
                    logging.getLogger(__name__).exception(
                        "migration of %s shard %d -> %s failed",
                        dataset, shard, dest)

            threading.Thread(target=_run, daemon=True,
                             name=f"migrate-{dataset}-{shard}").start()
            return self._json(200, {"status": "success",
                                    "data": {"dataset": dataset,
                                             "shard": shard, "dest": dest,
                                             "state": "started"}})
        return self._json(404, promjson.error_json("unknown cluster endpoint"))

    def _shardmap(self, dataset: str):
        """Shard → node/status/migration-phase map plus per-tenant
        cardinality-vs-quota usage (``filo-cli shardmap`` backend)."""
        cluster = self.app.cluster
        if cluster is not None:
            shards = cluster.shard_statuses(dataset)
            for entry in shards:
                mig = cluster.migrations.get((dataset, entry["shard"]))
                if mig is not None:
                    entry["migration"] = mig.snapshot()
                # leader covered offset + live follower watermarks: the
                # in-sync picture replicacheck/shardmap render
                owner = entry.get("node")
                node = cluster.nodes.get(owner) if owner else None
                if node is not None:
                    try:
                        entry["watermark"] = node.shard_offset(
                            dataset, entry["shard"])
                    except Exception:
                        pass
                for rep in entry.get("replicas", ()):
                    sy = cluster.replica_syncers.get(
                        (dataset, entry["shard"], rep["node"]))
                    if sy is not None:
                        rep["watermark"] = sy.applied
        elif dataset in self.app.shard_maps:
            shards = self.app.shard_maps[dataset]().snapshot()
        else:
            svc = self.app.services.get(dataset)
            shards = [{"shard": s.shard_num, "status": "active",
                       "node": None}
                      for s in svc.memstore.shards_for(dataset)] \
                if svc else []
        from filodb_tpu.utils.governor import config as gov_config
        svc = self.app.services.get(dataset)
        trackers = [s.cardinality for s in
                    svc.memstore.shards_for(dataset)] if svc else []
        tenants = []
        for tenant, tc in sorted(gov_config().tenants.items()):
            prefix = tenant.split("/")
            active = sum(t.cardinality(prefix).active_ts for t in trackers)
            tenants.append({
                "tenant": tenant,
                "active_series": active,
                "max_series": int(tc.get("max_series", 0) or 0),
                "max_inflight": int(tc.get("max_inflight", 0) or 0)})
        return self._json(200, {"status": "success",
                                "data": {"shards": shards,
                                         "tenants": tenants}})


class _ReusePortHTTPServer(ThreadingHTTPServer):
    """SO_REUSEPORT variant: N server processes bind the same port and the
    kernel load-balances connections across them — the multi-process
    serving plane (each worker is a log-tailing read replica), sidestepping
    the GIL the way the reference scales its Akka-HTTP dispatcher pool
    (``http/src/main/scala/filodb/http/FiloHttpServer.scala:23``)."""

    def server_bind(self):
        import socket
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class FiloHttpServer:
    def __init__(self, services: dict[str, QueryService], host="127.0.0.1",
                 port=8080, cluster=None, shard_maps=None,
                 reuse_port: bool = False, response_cache: bool = True,
                 rule_managers=None):
        self.services = services
        self.cluster = cluster
        # dataset -> RuleManager (standing queries); serves /api/v1/rules
        self.rule_managers = rule_managers or {}
        # member mode: dataset -> mirrored ShardMapper (StatusActor
        # subscription) so members answer cluster-status queries locally
        self.shard_maps = shard_maps or {}
        self.response_cache = ResponseCache() if response_cache else None
        self.dispatcher = HttpDispatcher(self)
        handler = _make_handler(self)
        cls = _ReusePortHTTPServer if reuse_port else ThreadingHTTPServer
        self.httpd = cls((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None
        # per-service micro-batchers: concurrent handler threads coalesce
        # into one engine batch (see coordinator.query_service.QueryBatcher)
        self._batchers: dict[int, object] = {}

    def batched(self, svc: QueryService):
        b = self._batchers.get(id(svc))
        if b is None:
            from filodb_tpu.coordinator.query_service import QueryBatcher
            b = self._batchers[id(svc)] = QueryBatcher(svc)
        return b

    def start(self) -> "FiloHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _make_handler(server: FiloHttpServer):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: HTTP/1.0 would pay a TCP connect + handler thread
        # spawn per request (the reference serves over a pooled Akka-HTTP
        # pipeline for the same reason, FiloHttpServer.scala:23)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            log.debug(fmt, *args)

        def do_GET(self):
            self._route()

        def do_POST(self):
            self._route()

        def _route(self):
            raw = b""
            if self.command == "POST":
                try:
                    ln = int(self.headers.get("Content-Length") or 0)
                    if ln < 0:
                        raise ValueError("negative Content-Length")
                except ValueError as e:
                    # unparseable length desyncs the keep-alive stream:
                    # answer 400 and drop the connection
                    self.close_connection = True
                    body = json.dumps(promjson.error_json(str(e))).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", JSON_CT)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                raw = self.rfile.read(ln) if ln else b""
            code, headers, body = server.dispatcher.handle(
                self.command, self.path, raw,
                self.headers.get("Content-Type", ""))
            self.send_response(code)
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return Handler
