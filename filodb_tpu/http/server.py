"""HTTP server exposing the Prometheus API + cluster admin routes.

Counterpart of reference ``FiloHttpServer.scala`` route composition
(endpoints per ``doc/http_api.md``):

- ``GET /promql/{dataset}/api/v1/query_range?query=&start=&end=&step=``
- ``GET/POST /promql/{dataset}/api/v1/query?query=&time=``
- ``GET /promql/{dataset}/api/v1/series?match[]=&start=&end=``
- ``GET /promql/{dataset}/api/v1/labels``
- ``GET /promql/{dataset}/api/v1/label/{name}/values``
- ``GET /api/v1/cluster/{dataset}/status`` (shard statuses)
- ``GET /__health``, ``GET /metrics`` (Prometheus exposition)

Threaded stdlib server: queries run on the request thread; the memstore's
read path is immutable-snapshot based so no global lock is needed (mirrors
the reference's reader/ingester separation).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.http import promjson
from filodb_tpu.promql.parser import ParseError, TimeStepParams, parse_query
from filodb_tpu.query.model import QueryLimitExceeded
from filodb_tpu.utils.metrics import render_prometheus

log = logging.getLogger(__name__)


class _ReusePortHTTPServer(ThreadingHTTPServer):
    """SO_REUSEPORT variant: N server processes bind the same port and the
    kernel load-balances connections across them — the multi-process
    serving plane (each worker is a log-tailing read replica), sidestepping
    the GIL the way the reference scales its Akka-HTTP dispatcher pool
    (``http/src/main/scala/filodb/http/FiloHttpServer.scala:23``)."""

    def server_bind(self):
        import socket
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class FiloHttpServer:
    def __init__(self, services: dict[str, QueryService], host="127.0.0.1",
                 port=8080, cluster=None, shard_maps=None,
                 reuse_port: bool = False):
        self.services = services
        self.cluster = cluster
        # member mode: dataset -> mirrored ShardMapper (StatusActor
        # subscription) so members answer cluster-status queries locally
        self.shard_maps = shard_maps or {}
        handler = _make_handler(self)
        cls = _ReusePortHTTPServer if reuse_port else ThreadingHTTPServer
        self.httpd = cls((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None
        # per-service micro-batchers: concurrent handler threads coalesce
        # into one engine batch (see coordinator.query_service.QueryBatcher)
        self._batchers: dict[int, object] = {}

    def batched(self, svc: QueryService):
        b = self._batchers.get(id(svc))
        if b is None:
            from filodb_tpu.coordinator.query_service import QueryBatcher
            b = self._batchers[id(svc)] = QueryBatcher(svc)
        return b

    def start(self) -> "FiloHttpServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _parse_time(s: str) -> float:
    """Unix seconds (float) or RFC3339 (Grafana sends either)."""
    try:
        return float(s)
    except ValueError:
        import datetime as dt
        return dt.datetime.fromisoformat(s.replace("Z", "+00:00")) \
            .timestamp()


def _make_handler(server: FiloHttpServer):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: HTTP/1.0 would pay a TCP connect + handler thread
        # spawn per request (the reference serves over a pooled Akka-HTTP
        # pipeline for the same reason, FiloHttpServer.scala:23)
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            log.debug(fmt, *args)

        def _send(self, code: int, payload):
            # str payloads are pre-rendered JSON (vectorized fast path)
            body = payload.encode() if isinstance(payload, str) \
                else json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._route()

        def do_POST(self):
            self._route()

        def _route(self):
            try:
                url = urlparse(self.path)
                qs = parse_qs(url.query)
                parts = [p for p in url.path.split("/") if p]
                if self.command == "POST":
                    ln = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(ln) if ln else b""
                    if parts[-1:] == ["read"]:
                        return self._remote_read(parts, raw)
                    if raw:
                        ctype = self.headers.get("Content-Type", "")
                        if "x-www-form-urlencoded" in ctype:
                            for k, v in parse_qs(raw.decode()).items():
                                qs.setdefault(k, v)
                self._dispatch(parts, qs)
            except (ParseError, ValueError) as e:
                self._send(400, promjson.error_json(str(e)))
            except QueryLimitExceeded as e:
                self._send(422, promjson.error_json(str(e), "query_limit"))
            except Exception as e:  # pragma: no cover
                log.exception("request failed")
                self._send(500, promjson.error_json(str(e), "internal"))

        def _dispatch(self, parts: list[str], qs: dict):
            if parts == ["__health"]:
                return self._send(200, {"status": "healthy"})
            if parts == ["metrics"]:
                body = render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if len(parts) >= 4 and parts[0] == "promql" \
                    and parts[2] == "api" and parts[3] == "v1":
                dataset = parts[1]
                svc = server.services.get(dataset)
                if svc is None:
                    return self._send(404, promjson.error_json(
                        f"unknown dataset {dataset}"))
                return self._prom_api(svc, parts[4:], qs)
            if len(parts) >= 3 and parts[0] == "api" and parts[1] == "v1" \
                    and parts[2] == "cluster":
                return self._cluster_api(parts[3:], qs)
            self._send(404, promjson.error_json("not found", "not_found"))

        # -- Prom API --

        def _prom_api(self, svc: QueryService, rest: list[str], qs: dict):
            if rest == ["query_range"]:
                query = qs["query"][0]
                start = int(_parse_time(qs["start"][0]))
                end = int(_parse_time(qs["end"][0]))
                step = int(float(qs.get("step", ["60"])[0]))
                r = server.batched(svc).query_range(query, start, step, end)
                return self._send(200, promjson.matrix_json_str(r))
            if rest == ["query"]:
                query = qs["query"][0]
                if "time" in qs:
                    t = int(_parse_time(qs["time"][0]))
                else:
                    # Prometheus defaults instant queries to server time
                    import time as _time
                    t = int(_time.time())
                r = server.batched(svc).query_range(query, t, 0, t)
                return self._send(200, promjson.vector_json_str(r))
            if rest == ["series"]:
                matches = qs.get("match[]", [])
                start = int(_parse_time(qs.get("start", ["0"])[0]))
                end = int(_parse_time(qs.get("end", ["9999999999"])[0]))
                out = []
                for mtext in matches:
                    plan = parse_query(mtext, TimeStepParams(start, 0, end))
                    raw = getattr(plan, "raw", None)
                    filters = raw.filters if raw is not None else ()
                    for lm in svc.series(list(filters), start, end):
                        out.append({("__name__" if k == "_metric_" else k): v
                                    for k, v in lm.items()})
                return self._send(200, {"status": "success", "data": out})
            if rest == ["labels"]:
                names = [("__name__" if n == "_metric_" else n)
                         for n in svc.memstore.label_names(svc.dataset)]
                return self._send(200, {"status": "success", "data": names})
            if len(rest) == 3 and rest[0] == "label" and rest[2] == "values":
                label = unquote(rest[1])
                if label == "__name__":
                    label = "_metric_"
                vals = svc.memstore.label_values(svc.dataset, label)
                return self._send(200, {"status": "success", "data": vals})
            self._send(404, promjson.error_json("unknown endpoint"))

        def _remote_read(self, parts: list[str], body: bytes):
            """Prometheus remote-read (protobuf; reference remote-storage
            protocol endpoint in PrometheusApiRoute)."""
            from filodb_tpu.http import remote_read as rr
            if len(parts) < 2 or parts[0] != "promql":
                return self._send(404, promjson.error_json("not found"))
            svc = server.services.get(parts[1])
            if svc is None:
                return self._send(404, promjson.error_json(
                    f"unknown dataset {parts[1]}"))
            data = rr.maybe_decompress(body)
            try:
                queries = rr.decode_read_request(data)
            except Exception:
                return self._send(501 if not rr.HAVE_SNAPPY else 400,
                                  promjson.error_json(
                                      "could not decode read request "
                                      "(snappy unavailable?)"))
            results = []
            for q in queries:
                series = []
                for shard in svc.memstore.shards_for(svc.dataset):
                    for pid in shard.lookup_partitions(
                            q["filters"], q["start_ms"], q["end_ms"]):
                        part = shard.partition(pid)
                        if part is None:
                            continue
                        ts, vals = part.read_samples(q["start_ms"],
                                                     q["end_ms"])
                        import numpy as _np
                        if len(ts) and not isinstance(vals, _np.ndarray):
                            continue  # histograms not in remote-read v1
                        series.append((list(part.part_key.labels), ts, vals))
                results.append(series)
            payload = rr.maybe_compress(rr.encode_read_response(results))
            self.send_response(200)
            self.send_header("Content-Type", "application/x-protobuf")
            self.send_header("Content-Encoding",
                             "snappy" if rr.HAVE_SNAPPY else "identity")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        # -- cluster admin --

        def _cluster_api(self, rest: list[str], qs: dict):
            cluster = server.cluster
            if not rest:
                return self._send(200, {"status": "success",
                                        "data": list(server.services)})
            dataset = rest[0]
            if len(rest) == 2 and rest[1] in ("startshards", "stopshards") \
                    and cluster is not None:
                # reference ClusterApiRoute start/stop shards commands
                from filodb_tpu.coordinator.shardmapper import (
                    ShardEvent,
                    ShardStatus,
                )
                shards = [int(s) for s in
                          qs.get("shards", [""])[0].split(",") if s]
                node = qs.get("node", [None])[0]
                sm = cluster.shard_managers.get(dataset)
                if sm is None:
                    return self._send(404, promjson.error_json(
                        f"unknown dataset {dataset}"))
                done = []
                for shard in shards:
                    if rest[1] == "stopshards":
                        owner = sm.mapper.node_for(shard)
                        if owner and owner in cluster.nodes:
                            cluster.nodes[owner].stop_shard(dataset, shard)
                            sm._publish(ShardEvent(shard, ShardStatus.STOPPED,
                                                   None))
                            done.append(shard)
                    else:
                        target = node or next(iter(cluster.nodes), None)
                        if target:
                            ev = ShardEvent(shard, ShardStatus.ASSIGNED,
                                            target)
                            sm._publish(ev)
                            cluster._on_event(dataset, ev)
                            done.append(shard)
                return self._send(200, {"status": "success", "data": done})
            if len(rest) == 2 and rest[1] == "status":
                if cluster is not None:
                    data = cluster.shard_statuses(dataset)
                elif dataset in server.shard_maps:
                    # member: serve the coordinator's state from the local
                    # mirror (sequenced subscription with resync)
                    data = server.shard_maps[dataset]().snapshot()
                else:
                    svc = server.services.get(dataset)
                    data = [{"shard": s.shard_num, "status": "active",
                             "numPartitions": s.num_partitions}
                            for s in svc.memstore.shards_for(dataset)] \
                        if svc else []
                return self._send(200, {"status": "success", "data": data})
            self._send(404, promjson.error_json("unknown cluster endpoint"))

    return Handler
