"""Prometheus HTTP API JSON rendering.

Counterpart of reference ``query/PrometheusModel.scala:13-51`` +
``PromCirceSupport.scala``: StepMatrix → Prom ``matrix``/``vector``/``scalar``
response payloads. NaN entries are gaps and are omitted; first-class histogram
results are flattened to ``le``-labelled bucket series (as the reference does
when converting histogram RangeVectors to the Prom wire model).
"""

from __future__ import annotations

import json
import math

import numpy as np

from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.query.model import QueryResult, StepMatrix


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def _labels_json(key) -> dict:
    out = {}
    for k, v in key.labels:
        out["__name__" if k == METRIC_LABEL else k] = v
    return out


def _flatten_histograms(m: StepMatrix) -> StepMatrix:
    """[P,K,B] histogram matrix -> per-bucket series with le labels."""
    from filodb_tpu.query.model import RangeVectorKey

    keys, rows = [], []
    les = m.les if m.les is not None else np.arange(m.values.shape[2])
    for i, k in enumerate(m.keys):
        for b, le in enumerate(les):
            lm = k.label_map
            lm["le"] = _fmt(float(le))
            keys.append(RangeVectorKey.of(lm))
            rows.append(m.values[i, :, b])
    return StepMatrix(keys, np.stack(rows) if rows
                      else np.zeros((0, m.num_steps)), m.steps_ms)


def _stats_json(result: QueryResult, full: bool = False) -> dict:
    s = result.stats
    out = {"seriesScanned": s.series_scanned,
           "samplesScanned": s.samples_scanned,
           "resultSeries": s.result_series,
           "wallTimeMs": round(s.wall_time_s * 1000.0, 3)}
    if full:
        # ?stats=all — the expanded per-query counters merged across
        # remote children (distributed tracing / flight-recorder stats)
        out.update({
            "chunksTouched": s.chunks_touched,
            "cacheHits": s.cache_hits,
            "cacheMisses": s.cache_misses,
            "wireBytes": s.wire_bytes,
            "admissionWaitMs": round(s.admission_wait_s * 1000.0, 3),
            "decodeMs": round(s.decode_s * 1000.0, 3),
            "reduceMs": round(s.reduce_s * 1000.0, 3),
        })
        if s.tiers:
            # federated query: per-tier attribution (query/federation.py)
            out["tiers"] = {
                tier: {k: (round(v, 3) if isinstance(v, float) else v)
                       for k, v in bucket.items()}
                for tier, bucket in s.tiers.items()}
        if s.pyramid:
            # cold folds served from stored aggregate levels
            # (query/engine/pyramid_lane.py)
            out["pyramid"] = {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in s.pyramid.items()}
    return out


def _partial_fields(result: QueryResult) -> dict:
    """``partial``/``warnings`` response fields for a degraded scatter-gather
    result (Prom API ``warnings`` convention); empty when complete."""
    if not getattr(result, "partial", False) \
            and not getattr(result, "warnings", None):
        return {}
    out = {}
    if result.partial:
        out["partial"] = True
    if result.warnings:
        out["warnings"] = list(result.warnings)
    return out


def _partial_fields_str(result: QueryResult) -> str:
    """String-renderer form of :func:`_partial_fields` — ``""`` or a
    leading-comma fragment to splice before the closing brace."""
    fields = _partial_fields(result)
    if not fields:
        return ""
    return "," + json.dumps(fields, separators=(",", ":"))[1:-1]


def matrix_json(result: QueryResult) -> dict:
    m = result.result
    if m.is_histogram:
        m = _flatten_histograms(m)
    series = []
    for i, key in enumerate(m.keys):
        vals = []
        row = m.values[i]
        for k in range(m.num_steps):
            v = row[k]
            if not math.isnan(v):
                vals.append([m.steps_ms[k] / 1000.0, _fmt(v)])
        if vals:
            series.append({"metric": _labels_json(key), "values": vals})
    return {"status": "success",
            "data": {"resultType": "matrix", "result": series},
            "queryStats": _stats_json(result),
            **_partial_fields(result)}


def _labels_json_str(key) -> str:
    """Serialized metric-label object, memoized per key instance (keys
    repeat across queries and series)."""
    s = key.__dict__.get("_json_str")
    if s is None:
        s = json.dumps(_labels_json(key), separators=(",", ":"))
        object.__setattr__(key, "_json_str", s)
    return s


def _value_strings(vals: np.ndarray) -> np.ndarray:
    """Shortest-round-trip value strings, vectorized (numpy's float→str is
    the same shortest-repr algorithm as Python's repr); Prom spellings for
    the specials."""
    sv = vals.astype("U24")
    if not np.isfinite(vals).all():
        sv = np.where(np.isposinf(vals), "+Inf", sv)
        sv = np.where(np.isneginf(vals), "-Inf", sv)
        sv = np.where(np.isnan(vals), "NaN", sv)
    return sv


def matrix_json_str(result: QueryResult, full_stats: bool = False) -> str:
    """Prom matrix response rendered straight to a JSON string — numpy
    formats every sample value in one vectorized pass instead of a
    per-value Python loop (the reference leans on Jackson streaming for the
    same reason, ``PromCirceSupport``)."""
    m = result.result
    if m.is_histogram:
        m = _flatten_histograms(m)
    m.materialize()
    vals = np.asarray(m.values, np.float64)
    ok = ~np.isnan(vals)
    sv = _value_strings(vals)
    ts_str = [repr(t / 1000.0) for t in m.steps_ms.tolist()]
    parts = []
    for i, key in enumerate(m.keys):
        idx = np.flatnonzero(ok[i])
        if not len(idx):
            continue
        row = sv[i]
        body = ",".join(f'[{ts_str[k]},"{row[k]}"]' for k in idx.tolist())
        parts.append('{"metric":%s,"values":[%s]}'
                     % (_labels_json_str(key), body))
    stats = json.dumps(_stats_json(result, full=full_stats),
                       separators=(",", ":"))
    return ('{"status":"success","data":{"resultType":"matrix","result":[%s'
            ']},"queryStats":%s%s}' % (",".join(parts), stats,
                                       _partial_fields_str(result)))


def vector_json_str(result: QueryResult, with_stats: bool = False) -> str:
    """Prom vector response rendered straight to a JSON string."""
    m = result.result
    if m.is_histogram:
        m = _flatten_histograms(m)
    m.materialize()
    statstr = ""
    if with_stats:
        statstr = ',"queryStats":%s' % json.dumps(
            _stats_json(result, full=True), separators=(",", ":"))
    if not m.num_steps or not m.num_series:
        return ('{"status":"success","data":{"resultType":"vector",'
                '"result":[]}%s%s}' % (statstr, _partial_fields_str(result)))
    k = m.num_steps - 1
    vals = np.asarray(m.values[:, k], np.float64)
    ok = ~np.isnan(vals)
    sv = _value_strings(vals)
    t = repr(float(m.steps_ms[k]) / 1000.0)
    parts = [
        '{"metric":%s,"value":[%s,"%s"]}' % (_labels_json_str(m.keys[i]),
                                             t, sv[i])
        for i in np.flatnonzero(ok).tolist()]
    return ('{"status":"success","data":{"resultType":"vector","result":'
            '[%s]}%s%s}' % (",".join(parts), statstr,
                            _partial_fields_str(result)))


def vector_json(result: QueryResult, with_stats: bool = False) -> dict:
    m = result.result
    if m.is_histogram:
        m = _flatten_histograms(m)
    out = []
    k = m.num_steps - 1
    for i, key in enumerate(m.keys):
        v = m.values[i, k] if m.num_steps else float("nan")
        if not math.isnan(v):
            out.append({"metric": _labels_json(key),
                        "value": [m.steps_ms[k] / 1000.0, _fmt(v)]})
    resp = {"status": "success",
            "data": {"resultType": "vector", "result": out},
            **_partial_fields(result)}
    if with_stats:
        resp["queryStats"] = _stats_json(result, full=True)
    return resp


def scalar_json(result: QueryResult) -> dict:
    m = result.result
    k = m.num_steps - 1
    v = m.values[0, k] if m.num_series else float("nan")
    return {"status": "success",
            "data": {"resultType": "scalar",
                     "result": [m.steps_ms[k] / 1000.0, _fmt(v)]}}


def error_json(message: str, error_type: str = "bad_data") -> dict:
    return {"status": "error", "errorType": error_type, "error": message}
