"""Prometheus HTTP API JSON rendering.

Counterpart of reference ``query/PrometheusModel.scala:13-51`` +
``PromCirceSupport.scala``: StepMatrix → Prom ``matrix``/``vector``/``scalar``
response payloads. NaN entries are gaps and are omitted; first-class histogram
results are flattened to ``le``-labelled bucket series (as the reference does
when converting histogram RangeVectors to the Prom wire model).
"""

from __future__ import annotations

import math

import numpy as np

from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.query.model import QueryResult, StepMatrix


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def _labels_json(key) -> dict:
    out = {}
    for k, v in key.labels:
        out["__name__" if k == METRIC_LABEL else k] = v
    return out


def _flatten_histograms(m: StepMatrix) -> StepMatrix:
    """[P,K,B] histogram matrix -> per-bucket series with le labels."""
    from filodb_tpu.query.model import RangeVectorKey

    keys, rows = [], []
    les = m.les if m.les is not None else np.arange(m.values.shape[2])
    for i, k in enumerate(m.keys):
        for b, le in enumerate(les):
            lm = k.label_map
            lm["le"] = _fmt(float(le))
            keys.append(RangeVectorKey.of(lm))
            rows.append(m.values[i, :, b])
    return StepMatrix(keys, np.stack(rows) if rows
                      else np.zeros((0, m.num_steps)), m.steps_ms)


def _stats_json(result: QueryResult) -> dict:
    s = result.stats
    return {"seriesScanned": s.series_scanned,
            "samplesScanned": s.samples_scanned,
            "resultSeries": s.result_series,
            "wallTimeMs": round(s.wall_time_s * 1000.0, 3)}


def matrix_json(result: QueryResult) -> dict:
    m = result.result
    if m.is_histogram:
        m = _flatten_histograms(m)
    series = []
    for i, key in enumerate(m.keys):
        vals = []
        row = m.values[i]
        for k in range(m.num_steps):
            v = row[k]
            if not math.isnan(v):
                vals.append([m.steps_ms[k] / 1000.0, _fmt(v)])
        if vals:
            series.append({"metric": _labels_json(key), "values": vals})
    return {"status": "success",
            "data": {"resultType": "matrix", "result": series},
            "queryStats": _stats_json(result)}


def vector_json(result: QueryResult) -> dict:
    m = result.result
    if m.is_histogram:
        m = _flatten_histograms(m)
    out = []
    k = m.num_steps - 1
    for i, key in enumerate(m.keys):
        v = m.values[i, k] if m.num_steps else float("nan")
        if not math.isnan(v):
            out.append({"metric": _labels_json(key),
                        "value": [m.steps_ms[k] / 1000.0, _fmt(v)]})
    return {"status": "success",
            "data": {"resultType": "vector", "result": out}}


def scalar_json(result: QueryResult) -> dict:
    m = result.result
    k = m.num_steps - 1
    v = m.values[0, k] if m.num_series else float("nan")
    return {"status": "success",
            "data": {"resultType": "scalar",
                     "result": [m.steps_ms[k] / 1000.0, _fmt(v)]}}


def error_json(message: str, error_type: str = "bad_data") -> dict:
    return {"status": "error", "errorType": error_type, "error": message}
