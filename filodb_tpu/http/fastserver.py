"""Single-threaded selector HTTP front end with event-loop query batching.

The TPU-native serving design: one thread owns every socket (no handler
threads, no GIL hand-offs), and all hot queries (``query``/``query_range``)
that arrive within one readiness pass are evaluated as ONE
``QueryService.query_range_many`` engine batch — the device executes a
single micro-batched program and results come back in one coalesced fetch.
This replaces thread-per-connection + ``QueryBatcher`` coalescing with the
event loop's natural batching: under load a pass drains every ready socket,
so batch size tracks concurrency with zero added latency when idle.

Reference boundary replaced: the Akka-HTTP dispatcher pool in
``http/src/main/scala/filodb/http/FiloHttpServer.scala:23`` (thread-pool
concurrency → event-loop + device micro-batching).

Cold paths (metadata, admin, remote-read, POST forms) run inline through
the shared ``HttpDispatcher`` — identical routing to the threaded server.
"""

from __future__ import annotations

import json
import logging
import selectors
import socket
import threading
from urllib.parse import parse_qs, urlparse

from filodb_tpu.http import promjson
from filodb_tpu.http.server import (
    JSON_CT,
    HttpDispatcher,
    ResponseCache,
    response_cache_key,
    retry_after_headers,
    service_version,
)
from filodb_tpu.promql.parser import ParseError
from filodb_tpu.query.model import QueryLimitExceeded
from filodb_tpu.utils.governor import QueryRejected
from filodb_tpu.utils.resilience import DeadlineExceeded

log = logging.getLogger(__name__)

_MAX_BUF = 1 << 20          # drop connections with >1MB of pending request
_MAX_BODY = 10 << 20
_STATUS = {200: b"200 OK", 400: b"400 Bad Request", 404: b"404 Not Found",
           413: b"413 Content Too Large", 422: b"422 Unprocessable Entity",
           429: b"429 Too Many Requests", 431: b"431 Headers Too Large",
           500: b"500 Internal Server Error", 501: b"501 Not Implemented",
           503: b"503 Service Unavailable"}


def _response_bytes(code: int, headers: dict, body: bytes,
                    close: bool) -> bytes:
    head = [b"HTTP/1.1 " + _STATUS.get(code, str(code).encode())]
    for k, v in headers.items():
        head.append(f"{k}: {v}".encode())
    head.append(b"Content-Length: " + str(len(body)).encode())
    if close:
        head.append(b"Connection: close")
    return b"\r\n".join(head) + b"\r\n\r\n" + body


class _Conn:
    __slots__ = ("sock", "inbuf", "out", "slots", "base", "close_after")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.inbuf = b""
        self.out = b""
        # responses must leave in request order (HTTP/1.1 pipelining):
        # each parsed request claims an ABSOLUTE slot number; completed
        # prefix slots are shifted out by _flush, so ``base`` tracks the
        # absolute number of slots[0] (hot queries fill theirs after the
        # batch runs, by which time earlier slots may have flushed)
        self.slots: list[bytes | None] = []
        self.base = 0
        self.close_after = False

    def fill(self, slot: int, resp: bytes) -> None:
        i = slot - self.base
        if 0 <= i < len(self.slots):
            self.slots[i] = resp

    def is_last(self, slot: int) -> bool:
        return slot == self.base + len(self.slots) - 1


class _HotReq:
    __slots__ = ("conn", "slot", "svc", "kind", "params", "ckey", "version")

    def __init__(self, conn, slot, svc, kind, params):
        self.conn = conn
        self.slot = slot
        self.svc = svc
        self.kind = kind          # "range" | "instant"
        self.params = params      # (query, start, step, end)
        self.ckey = None          # response-cache key (set when cache is on)
        self.version = 0


class FastHttpServer:
    """Drop-in alternative front end to ``FiloHttpServer`` (same
    constructor surface and attributes; ``standalone`` picks via config)."""

    def __init__(self, services: dict, host="127.0.0.1", port=8080,
                 cluster=None, shard_maps=None, reuse_port: bool = False,
                 response_cache: bool = True, rule_managers=None):
        self.services = services
        self.cluster = cluster
        # dataset -> RuleManager (standing queries); serves /api/v1/rules
        self.rule_managers = rule_managers or {}
        self.shard_maps = shard_maps or {}
        self.response_cache = ResponseCache() if response_cache else None
        self.dispatcher = HttpDispatcher(self)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port:
            self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._listen.bind((host, port))
        self._listen.listen(512)
        self._listen.setblocking(False)
        self.port = self._listen.getsockname()[1]
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._running = False
        self._thread: threading.Thread | None = None

    # the dispatcher's cold query paths call app.batched(svc).query_range;
    # on the event loop the service itself is the right executor (no
    # cross-thread coalescing needed — hot batching happens per pass)
    def batched(self, svc):
        return svc

    def start(self) -> "FastHttpServer":
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fast-http")
        self._thread.start()
        return self

    def stop(self):
        if not self._running:
            return
        self._running = False
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for key in list(self._sel.get_map().values()):
            if isinstance(key.data, _Conn):
                try:
                    key.data.sock.close()
                except OSError:
                    pass
        self._sel.close()
        self._listen.close()
        self._wake_r.close()
        self._wake_w.close()

    # -- event loop --

    def _loop(self):
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        while self._running:
            try:
                events = self._sel.select(timeout=1.0)
                hot: list[_HotReq] = []
                for key, mask in events:
                    if key.data is None:
                        self._accept()
                    elif key.data == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    else:
                        conn: _Conn = key.data
                        if mask & selectors.EVENT_READ:
                            self._read(conn, hot)
                        if mask & selectors.EVENT_WRITE:
                            self._flush(conn)
                if hot:
                    self._run_hot_batch(hot)
                    for req in hot:
                        self._flush(req.conn)
            except Exception:  # pragma: no cover — the loop must survive
                # any per-connection handler bug; affected sockets are
                # dropped, everything else keeps serving
                log.exception("event loop pass failed")
                for req in locals().get("hot") or []:
                    self._close(req.conn)

    def _accept(self):
        while True:
            try:
                sock, _ = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sel.register(sock, selectors.EVENT_READ, _Conn(sock))

    def _close(self, conn: _Conn):
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _read(self, conn: _Conn, hot: list[_HotReq]):
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        conn.inbuf += data
        self._parse_requests(conn, hot)
        self._flush(conn)

    def _reject(self, conn: _Conn, code: int, message: str):
        conn.slots.append(_response_bytes(
            code, {"Content-Type": JSON_CT},
            json.dumps(promjson.error_json(message)).encode(), True))
        conn.close_after = True
        conn.inbuf = b""

    def _parse_requests(self, conn: _Conn, hot: list[_HotReq]):
        while conn.inbuf and not conn.close_after:
            end = conn.inbuf.find(b"\r\n\r\n")
            if end < 0:
                if len(conn.inbuf) > _MAX_BUF:
                    # unterminated header block — the body limit is
                    # enforced separately once Content-Length is known
                    self._reject(conn, 431, "headers too large")
                return
            head = conn.inbuf[:end]
            lines = head.split(b"\r\n")
            try:
                method, target, version = lines[0].split(b" ", 2)
            except ValueError:
                self._close(conn)
                return
            clen = 0
            seen_clen = None
            ctype = ""
            keep = version.strip() == b"HTTP/1.1"
            chunked = False
            for ln in lines[1:]:
                lower = ln.lower()
                if lower.startswith(b"transfer-encoding:"):
                    # chunked bodies are not framed by this parser; treating
                    # them as body-less would desync the pipeline (the body
                    # bytes would parse as the next request)
                    chunked = True
                elif lower.startswith(b"content-length:"):
                    try:
                        clen = int(ln.split(b":", 1)[1])
                    except ValueError:
                        self._close(conn)
                        return
                    if seen_clen is not None and seen_clen != clen:
                        # differing duplicate Content-Length is the CL.CL
                        # smuggling vector (RFC 9112 §6.3: must reject)
                        self._close(conn)
                        return
                    seen_clen = clen
                elif lower.startswith(b"content-type:"):
                    ctype = ln.split(b":", 1)[1].strip().decode(
                        "latin-1", "replace")
                elif lower.startswith(b"connection:"):
                    v = lower.split(b":", 1)[1].strip()
                    keep = v != b"close" if keep else v == b"keep-alive"
            if chunked:
                self._reject(conn, 501, "Transfer-Encoding not supported")
                return
            if clen < 0:
                # a negative length would rewind the request boundary into
                # the current header block — classic smuggling vector
                self._close(conn)
                return
            if clen > _MAX_BODY:
                self._reject(conn, 413, "request body too large")
                return
            total = end + 4 + clen
            if len(conn.inbuf) < total:
                return  # wait for the body
            body = conn.inbuf[end + 4:total]
            conn.inbuf = conn.inbuf[total:]
            if not keep:
                conn.close_after = True
            slot = conn.base + len(conn.slots)
            conn.slots.append(None)
            path = target.decode("latin-1", "replace")
            req = self._classify_hot(conn, slot, method, path)
            if req is not None:
                cache = self.response_cache
                svc_version = service_version(req.svc) \
                    if cache is not None else None
                if svc_version is not None:
                    req.ckey = response_cache_key(req.svc, req.kind,
                                                  req.params)
                    req.version = svc_version
                    body = cache.get(req.ckey, req.version)
                    if body is not None:
                        conn.fill(slot, _response_bytes(
                            200, {"Content-Type": JSON_CT}, body,
                            conn.close_after and conn.is_last(slot)))
                        continue
                hot.append(req)
            else:
                code, headers, resp = self.dispatcher.handle(
                    method.decode("latin-1", "replace"), path, body, ctype)
                conn.fill(slot, _response_bytes(
                    code, headers, resp,
                    conn.close_after and conn.is_last(slot)))

    def _classify_hot(self, conn, slot, method: bytes, path: str):
        """A GET query/query_range for a known dataset with well-formed
        parameters; anything else takes the generic dispatcher."""
        if method != b"GET" or not path.startswith("/promql/"):
            return None
        url = urlparse(path)
        parts = url.path.split("/")
        # ['', 'promql', ds, 'api', 'v1', endpoint]
        if len(parts) != 6 or parts[3] != "api" or parts[4] != "v1" \
                or parts[5] not in ("query_range", "query"):
            return None
        svc = self.services.get(parts[2])
        if svc is None:
            return None
        qs = parse_qs(url.query)
        if qs.get("stats", [""])[0] == "all":
            # expanded-stats rendering isn't batched — generic path
            return None
        try:
            if parts[5] == "query_range":
                q, start, step, end = HttpDispatcher.range_params(qs)
                return _HotReq(conn, slot, svc, "range",
                               (q, start, step, end))
            q, t = HttpDispatcher.instant_params(qs)
            return _HotReq(conn, slot, svc, "instant", (q, t, 0, t))
        except (KeyError, ValueError, IndexError):
            return None  # malformed → generic path renders the 400

    # -- hot batch execution --

    def _run_hot_batch(self, hot: list[_HotReq]):
        by_svc: dict[int, list[_HotReq]] = {}
        for req in hot:
            by_svc.setdefault(id(req.svc), []).append(req)
        for reqs in by_svc.values():
            svc = reqs[0].svc
            try:
                results = svc.query_range_many([r.params for r in reqs])
            except Exception:
                # isolate the failing query: run each alone so errors are
                # attributed to their own request
                results = None
            for i, req in enumerate(reqs):
                if results is not None:
                    code, headers, body = (200, {"Content-Type": JSON_CT},
                                           self._render(req, results[i]))
                else:
                    code, headers, body = self._run_single(req)
                if code == 200 and req.ckey is not None \
                        and self.response_cache is not None:
                    self.response_cache.put(req.ckey, req.version, body)
                req.conn.fill(req.slot, _response_bytes(
                    code, headers, body,
                    req.conn.close_after and req.conn.is_last(req.slot)))

    @staticmethod
    def _render(req: _HotReq, result) -> bytes:
        if req.kind == "range":
            return promjson.matrix_json_str(result).encode()
        return promjson.vector_json_str(result).encode()

    def _run_single(self, req: _HotReq) -> tuple[int, dict, bytes]:
        ct = {"Content-Type": JSON_CT}
        try:
            return (200, ct,
                    self._render(req, req.svc.query_range(*req.params)))
        except (ParseError, ValueError) as e:
            return 400, ct, json.dumps(promjson.error_json(str(e))).encode()
        except QueryLimitExceeded as e:
            return 422, ct, json.dumps(
                promjson.error_json(str(e), "query_limit")).encode()
        except QueryRejected as e:
            # shed by the admission gate: distinct errorType + Retry-After
            # so clients back off instead of hammering an overloaded node
            return 503, {**ct, **retry_after_headers(e.retry_after_s)}, \
                json.dumps(promjson.error_json(str(e), "unavailable")).encode()
        except DeadlineExceeded as e:
            return 503, {**ct, **retry_after_headers()}, json.dumps(
                promjson.error_json(str(e), "timeout")).encode()
        except Exception as e:  # noqa: BLE001
            log.exception("hot query failed")
            return 500, ct, json.dumps(
                promjson.error_json(str(e), "internal")).encode()

    # -- writes --

    def _flush(self, conn: _Conn):
        # move contiguous completed slots into the out buffer
        done = 0
        for resp in conn.slots:
            if resp is None:
                break
            conn.out += resp
            done += 1
        if done:
            del conn.slots[:done]
            conn.base += done
        if not conn.out:
            if conn.close_after and not conn.slots:
                self._close(conn)
            return
        try:
            sent = conn.sock.send(conn.out)
            conn.out = conn.out[sent:]
        except (BlockingIOError, InterruptedError):
            sent = 0
        except OSError:
            self._close(conn)
            return
        try:
            if conn.out:
                self._sel.modify(conn.sock,
                                 selectors.EVENT_READ | selectors.EVENT_WRITE,
                                 conn)
            else:
                self._sel.modify(conn.sock, selectors.EVENT_READ, conn)
                if conn.close_after and not conn.slots:
                    self._close(conn)
        except (KeyError, ValueError):
            pass
