"""Prometheus remote-read protocol support.

Counterpart of reference ``prometheus/src/main/proto/remote-storage.proto`` +
``PrometheusModel.toPromReadResponse`` (``query/PrometheusModel.scala:13-51``)
and the remote-read route in ``PrometheusApiRoute``.

The message schema is tiny, so the wire codec is implemented directly
(varint/length-delimited protobuf encoding) — no generated code needed:

  ReadRequest  { repeated Query queries = 1; }
  Query        { int64 start_timestamp_ms = 1; int64 end_timestamp_ms = 2;
                 repeated LabelMatcher matchers = 3; }
  LabelMatcher { enum Type { EQ NEQ RE NRE } type = 1;
                 string name = 2; string value = 3; }
  ReadResponse { repeated QueryResult results = 1; }
  QueryResult  { repeated TimeSeries timeseries = 1; }
  TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
  Label        { string name = 1; string value = 2; }
  Sample       { double value = 1; int64 timestamp = 2; }

Bodies are snappy-framed by Prometheus; when the snappy module is absent the
endpoint accepts/produces raw protobuf (clients can disable compression) and
reports 501 for snappy payloads.
"""

from __future__ import annotations

import struct

from filodb_tpu.core.filters import (
    ColumnFilter,
    Equals,
    EqualsRegex,
    NotEquals,
    NotEqualsRegex,
)
from filodb_tpu.core.partkey import METRIC_LABEL

try:
    import snappy  # type: ignore

    HAVE_SNAPPY = True
except ImportError:  # pragma: no cover - env dependent
    snappy = None
    HAVE_SNAPPY = False


# ---- minimal protobuf wire codec ------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _iter_fields(data: bytes):
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(data, pos)
        elif wire == 1:
            val = data[pos : pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(data, pos)
            val = data[pos : pos + ln]
            pos += ln
        elif wire == 5:
            val = data[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# ---- request decode --------------------------------------------------------

_MATCHER_TYPES = {0: Equals, 1: NotEquals, 2: EqualsRegex, 3: NotEqualsRegex}


def decode_read_request(data: bytes) -> list[dict]:
    """ReadRequest → [{start_ms, end_ms, filters}]."""
    queries = []
    for field, _, val in _iter_fields(data):
        if field == 1:
            queries.append(_decode_query(val))
    return queries


def _decode_query(data: bytes) -> dict:
    out = {"start_ms": 0, "end_ms": 0, "filters": []}
    for field, _, val in _iter_fields(data):
        if field == 1:
            out["start_ms"] = val if isinstance(val, int) else 0
        elif field == 2:
            out["end_ms"] = val if isinstance(val, int) else 0
        elif field == 3:
            out["filters"].append(_decode_matcher(val))
    return out


def _decode_matcher(data: bytes) -> ColumnFilter:
    mtype, name, value = 0, "", ""
    for field, _, val in _iter_fields(data):
        if field == 1:
            mtype = val
        elif field == 2:
            name = val.decode()
        elif field == 3:
            value = val.decode()
    if name == "__name__":
        name = METRIC_LABEL
    return ColumnFilter(name, _MATCHER_TYPES[mtype](value))


# ---- response encode -------------------------------------------------------

def encode_read_response(query_results: list) -> bytes:
    """Encode raw series into a ReadResponse.

    ``query_results``: one entry per request query, each a list of
    (labels: list[(name, value)], ts_ms int64[n], values float64[n]).
    Remote read returns RAW samples (the reference converts RangeVectors via
    ``toPromReadResponse``).
    """
    import math

    results = []
    for series_list in query_results:
        series_msgs = []
        for labels_kv, ts, vals in series_list:
            labels = b"".join(
                _ld(1, _ld(1, ("__name__" if k == METRIC_LABEL else k)
                           .encode()) + _ld(2, v.encode()))
                for k, v in labels_kv)
            samples = bytearray()
            for k in range(len(ts)):
                v = float(vals[k])
                if math.isnan(v):
                    continue
                body = (_key(1, 1) + struct.pack("<d", v)
                        + _key(2, 0) + _varint(int(ts[k])))
                samples += _ld(2, body)
            series_msgs.append(_ld(1, labels + bytes(samples)))
        results.append(_ld(1, b"".join(series_msgs)))
    return b"".join(results)


def maybe_compress(data: bytes) -> bytes:
    return snappy.compress(data) if HAVE_SNAPPY else data


def maybe_decompress(data: bytes) -> bytes:
    if HAVE_SNAPPY:
        try:
            return snappy.decompress(data)
        except Exception:
            return data
    return data
