"""HTTP API server: Prometheus-compatible query API + admin/health routes.

Counterpart of reference ``http/`` module (``FiloHttpServer.scala:23``,
``PrometheusApiRoute.scala:37-82``, ``ClusterApiRoute``, ``HealthRoute``; full
endpoint list in reference ``doc/http_api.md:25-264``).
"""
