"""Finding/baseline model shared by every filolint pass.

A finding's identity (``key``) is deliberately line-number-free: it
hashes the pass code, the repo-relative path, the enclosing symbol and a
pass-chosen detail string (lock name + blocked call, attribute name,
metric name, ...). Unrelated edits that shift lines therefore never
invalidate the baseline, while moving the offending code to another
function or file — a real change — does.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    code: str          # e.g. "LD101"
    path: str          # repo-relative posix path
    line: int          # 1-based; diagnostic only, not part of identity
    symbol: str        # "Class.method", "Class", or "<module>"
    detail: str        # stable pass-chosen identity fragment
    message: str       # human-readable description

    @property
    def key(self) -> str:
        return f"{self.code}:{self.path}:{self.symbol}:{self.detail}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} [{self.symbol}] "
                f"{self.message}")


# inline suppression: a trailing  "# filolint: disable=LD101"  (or a
# comma list, or "all") on the finding's line suppresses it in place —
# for one-off intentional patterns where a baseline entry would be noise
_SUPPRESS_RE = re.compile(r"#\s*filolint:\s*disable=([A-Za-z0-9,_ ]+)")


def suppressed(source_lines: list[str], line: int, code: str) -> bool:
    if not (1 <= line <= len(source_lines)):
        return False
    m = _SUPPRESS_RE.search(source_lines[line - 1])
    if not m:
        return False
    codes = {c.strip() for c in m.group(1).split(",")}
    return "all" in codes or code in codes


@dataclass
class Baseline:
    """Checked-in set of accepted findings, each with a one-line
    justification. The gate fails only on findings NOT in here; stale
    entries (baselined finding no longer produced) are surfaced so the
    file shrinks as debts are paid."""

    entries: dict[str, dict] = field(default_factory=dict)  # key -> entry

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return cls()
        return cls({e["key"]: e for e in doc.get("entries", [])})

    def save(self, path: str) -> None:
        doc = {
            "version": 1,
            "comment": "filolint accepted-findings baseline; every entry "
                       "needs a one-line justification (see "
                       "doc/static_analysis.md)",
            "entries": sorted(self.entries.values(),
                              key=lambda e: e["key"]),
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")

    def diff(self, findings: list[Finding]
             ) -> tuple[list[Finding], list[dict]]:
        """Split into (new findings, stale baseline entries)."""
        seen = {f.key for f in findings}
        new = [f for f in findings if f.key not in self.entries]
        stale = [e for k, e in sorted(self.entries.items())
                 if k not in seen]
        return new, stale

    def update(self, findings: list[Finding]) -> None:
        """Absorb current findings: add new keys with a TODO note (to be
        replaced by a human justification), drop stale ones."""
        seen = {}
        for f in findings:
            prev = self.entries.get(f.key)
            seen[f.key] = {
                "key": f.key,
                "code": f.code,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
                "justification": (prev or {}).get(
                    "justification", "TODO: justify or fix"),
            }
        self.entries = seen
