"""Resource-lifecycle pass (RL4xx): acquire/release pairs through
exception paths and call closures.

The PR-review shape this mechanizes is ``coordinator/remote.py``'s
``_roundtrip``: a socket checked out of the pool, used across calls that
can raise, and checked back in only on the straight-line path — a
``KeyboardInterrupt`` or an encode ``TypeError`` between checkout and
checkin leaks the socket forever. Four codes:

- **RL401 leak-on-exception** — a tracked resource (pool checkout,
  ``socket.create_connection``, bare ``open``, a local helper whose
  summary returns a fresh resource, an armed fault site) is live across
  a statement that can raise, and no ``with`` scope, ``finally``, or
  *broad* except handler (bare / ``Exception`` / ``BaseException``)
  releases it. Narrow handler tuples — ``except self.TRANSPORT_ERRORS``
  — deliberately do NOT count: that is exactly the remote.py bug, where
  only transport errors closed the socket.
- **RL402 resource-not-released** — a tracked resource is acquired and
  neither released (``close``/``shutdown``/``checkin``/release-helper)
  nor has its ownership transferred (returned, stored, passed to an
  unknown callee) anywhere in the function.
- **RL403 thread-not-joined** — a ``Thread`` is started without
  ``daemon=True`` and is never joined (locally or, for ``self.X``
  threads, anywhere in the class) and never escapes.
- **RL404 task-ack-outside-finally** — a ``.task_done()`` queue ack
  that is not lexically inside a ``finally`` block: an exception in the
  work body skips the ack and wedges ``queue.join()`` forever (the
  objectstore write-behind drain relies on ack-in-finally).

Interprocedural layer: per-module function summaries — *releases-param*
(``_close_quietly(sock)`` closes its argument, transitively through
local helpers) and *returns-fresh-resource* (``self._dial`` returns a
socket it created) — composed through memoized recursion, the same
shape as ``lockdiscipline``'s ``_method_closure``. Passing a resource
to a summarized local callee that does not release it is a borrow;
passing it to an unresolvable callee transfers ownership (silences the
finding) — conservative in the false-negative direction, so every
report is actionable.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from filodb_tpu.analysis.model import Finding
from filodb_tpu.analysis.runner import AnalysisContext, ModuleInfo

# --------------------------------------------------------------------------
# registries

# attribute calls that produce an owned resource regardless of receiver
ACQUIRE_ATTRS = {
    "checkout": "socket",           # _SocketPool.checkout
    "create_connection": "socket",  # socket.create_connection
}
# receiver-release: ``sock.close()``
RELEASE_ATTRS = {"close", "shutdown", "release"}
# argument-release: ``pool.checkin(key, sock)``, ``pool.drop(sock)``
RELEASE_ARG_ATTRS = {"checkin", "drop", "put_back"}
# broad except types whose release counts as exception-path protection
BROAD_HANDLERS = {"Exception", "BaseException"}


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


def _attr_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(_attr_name(n) in BROAD_HANDLERS for n in names)


# --------------------------------------------------------------------------
# per-module function summaries

@dataclass
class _FnSummary:
    params: list[str]                      # without self/cls
    has_self: bool
    releases: set[str] = field(default_factory=set)  # param names released
    returns_kind: str | None = None        # fresh resource kind, if any


def _collect_functions(mi: ModuleInfo) -> dict[str, ast.FunctionDef]:
    """``{"fn": def, "Cls.meth": def}`` for top-level defs and methods."""
    out: dict[str, ast.FunctionDef] = {}
    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out


def _params_of(fdef: ast.FunctionDef) -> tuple[list[str], bool]:
    names = [a.arg for a in fdef.args.args]
    has_self = bool(names) and names[0] in ("self", "cls")
    return (names[1:] if has_self else names), has_self


def _direct_acquire_kind(call: ast.Call) -> tuple[str, str] | None:
    """Registry-only acquisition classification (no summaries)."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "open":
        return "file", "open()"
    if isinstance(fn, ast.Attribute):
        if fn.attr in ACQUIRE_ATTRS:
            return ACQUIRE_ATTRS[fn.attr], f"{_src(fn)}()"
        if fn.attr == "socket" and isinstance(fn.value, ast.Name) \
                and fn.value.id == "socket":
            return "socket", "socket.socket()"
        if fn.attr == "arm":
            return "fault-site", f"{_src(fn)}()"
    return None


def _releases_of(fns: dict[str, ast.FunctionDef], key: str,
                 memo: dict, active: set) -> set[str]:
    """Param names ``key`` releases, expanded through local call chains
    (``_close_quietly`` -> ``sock.close``), cycles cut by ``active``."""
    if key in memo:
        return memo[key]
    if key in active:
        return set()
    fdef = fns.get(key)
    if fdef is None:
        memo[key] = set()
        return memo[key]
    active.add(key)
    params, _ = _params_of(fdef)
    pset = set(params)
    cls_prefix = key.rsplit(".", 1)[0] + "." if "." in key else None
    released: set[str] = set()
    for node in ast.walk(fdef):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in RELEASE_ATTRS and \
                    isinstance(fn.value, ast.Name) and fn.value.id in pset:
                released.add(fn.value.id)
            if fn.attr in RELEASE_ARG_ATTRS:
                released |= {a.id for a in node.args
                             if isinstance(a, ast.Name) and a.id in pset}
            callee_key = None
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                    and cls_prefix is not None:
                callee_key = cls_prefix + fn.attr
        elif isinstance(fn, ast.Name) and fn.id in fns:
            callee_key = fn.id
        else:
            continue
        if callee_key is not None and callee_key in fns:
            sub = _releases_of(fns, callee_key, memo, active)
            if sub:
                callee_params, _ = _params_of(fns[callee_key])
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name) and a.id in pset \
                            and i < len(callee_params) \
                            and callee_params[i] in sub:
                        released.add(a.id)
    active.discard(key)
    memo[key] = released
    return released


def _returns_kind_of(fdef: ast.FunctionDef) -> str | None:
    """Does the function return a resource it freshly acquired?"""
    acquired: dict[str, str] = {}
    ret: str | None = None
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            kind = _direct_acquire_kind(node.value)
            if kind is not None:
                acquired[node.targets[0].id] = kind[0]
        elif isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Name) and \
                    node.value.id in acquired:
                ret = acquired[node.value.id]
            elif isinstance(node.value, ast.Call):
                kind = _direct_acquire_kind(node.value)
                if kind is not None:
                    ret = kind[0]
    return ret


def _build_summaries(mi: ModuleInfo) -> dict[str, _FnSummary]:
    fns = _collect_functions(mi)
    memo: dict[str, set] = {}
    out: dict[str, _FnSummary] = {}
    for key, fdef in fns.items():
        params, has_self = _params_of(fdef)
        out[key] = _FnSummary(
            params=params, has_self=has_self,
            releases=_releases_of(fns, key, memo, set()),
            returns_kind=_returns_kind_of(fdef))
    return out


# --------------------------------------------------------------------------
# leak walk (RL401/RL402)

@dataclass
class _Res:
    name: str
    kind: str
    desc: str          # acquisition expression, line-free
    line: int
    released: bool = False
    escaped: bool = False
    exposure: tuple | None = None   # (line, risky statement text)


class _LeakWalker:
    """Ordered statement walk of one function body. Tracks live owned
    resources per local name, the lexically-protected name set (``with``
    scope on the resource, ``finally`` release, broad-except release),
    and records the first unprotected may-raise exposure per resource."""

    def __init__(self, ps: "_PassState", mi: ModuleInfo, symbol: str,
                 summaries: dict[str, _FnSummary], cls_name: str | None):
        self.ps = ps
        self.mi = mi
        self.symbol = symbol
        self.summaries = summaries
        self.cls_name = cls_name
        self.live: dict[str, list[_Res]] = {}
        self.all: list[_Res] = []

    # ---- classification helpers

    def _summary_for_call(self, fn: ast.AST) -> _FnSummary | None:
        if isinstance(fn, ast.Name):
            return self.summaries.get(fn.id)
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and self.cls_name is not None:
            return self.summaries.get(f"{self.cls_name}.{fn.attr}")
        return None

    def _acquire_from(self, value: ast.AST) -> tuple[str, str] | None:
        if not isinstance(value, ast.Call):
            return None
        direct = _direct_acquire_kind(value)
        if direct is not None:
            return direct
        summ = self._summary_for_call(value.func)
        if summ is not None and summ.returns_kind is not None:
            return summ.returns_kind, f"{_src(value.func)}()"
        return None

    def _released_names(self, stmt: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in RELEASE_ATTRS and \
                        isinstance(fn.value, ast.Name):
                    out.add(fn.value.id)
                if fn.attr in RELEASE_ARG_ATTRS:
                    out |= {a.id for a in node.args
                            if isinstance(a, ast.Name)}
                if fn.attr == "reset":
                    # FaultInjector.reset() disarms every live fault site
                    out |= {n for n, rs in self.live.items()
                            if any(r.kind == "fault-site" for r in rs)}
            summ = self._summary_for_call(fn)
            if summ is not None and summ.releases:
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name) and i < len(summ.params) \
                            and summ.params[i] in summ.releases:
                        out.add(a.id)
        return out

    def _escapes_in(self, stmt: ast.AST, name: str) -> bool:
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(stmt):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and node.id == name and \
                    isinstance(node.ctx, ast.Load):
                if self._use_escapes(node, parents):
                    return True
        return False

    def _use_escapes(self, node: ast.AST, parents: dict) -> bool:
        p = parents.get(node)
        if isinstance(p, ast.keyword):
            p = parents.get(p)
        if isinstance(p, ast.Attribute):
            return False                       # sock.settimeout(...)
        if isinstance(p, ast.Call):
            fn = p.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in RELEASE_ARG_ATTRS:
                return False                   # release, handled already
            if self._summary_for_call(fn) is not None:
                return False                   # borrow by a local callee
            return True                        # unknown callee: transfer
        if isinstance(p, (ast.Compare, ast.BoolOp, ast.UnaryOp)):
            return False                       # ``if sock is None``
        if isinstance(p, (ast.If, ast.While)):
            return False                       # bare test position
        if isinstance(p, ast.withitem):
            return False                       # ``with sock:`` = release
        if isinstance(p, ast.Expr):
            return False
        return True    # return/store/append/subscript/yield/...

    # ---- statement dispatch

    def run(self, body: list) -> None:
        self._block(body, frozenset())
        for res in self.all:
            if not res.released and not res.escaped:
                self.ps.finding(
                    "RL402", self.mi.path, res.line, self.symbol,
                    detail=f"{res.name}|{res.desc}",
                    message=(f"{res.kind} '{res.name}' from {res.desc} is "
                             f"never released (no close/checkin/shutdown "
                             f"on any path) and never escapes this "
                             f"function"))
            elif res.exposure is not None:
                eline, edesc = res.exposure
                self.ps.finding(
                    "RL401", self.mi.path, eline, self.symbol,
                    detail=f"{res.name}|{res.desc}",
                    message=(f"{res.kind} '{res.name}' from {res.desc} "
                             f"leaks if `{edesc}` raises: no with-scope, "
                             f"finally, or broad except handler releases "
                             f"it on the exception path (narrow handler "
                             f"tuples do not cover e.g. KeyboardInterrupt "
                             f"or encode errors)"))

    def _block(self, stmts: list, protected: frozenset) -> None:
        for stmt in stmts:
            self._stmt(stmt, protected)

    def _stmt(self, stmt: ast.stmt, protected: frozenset) -> None:
        if isinstance(stmt, ast.Try):
            self._try(stmt, protected)
        elif isinstance(stmt, ast.If):
            self._if(stmt, protected)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            self._effects(stmt, protected, header_only=True)
            self._block(stmt.body, protected)
            self._block(stmt.orelse, protected)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._with(stmt, protected)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef, ast.Lambda)):
            # nested scope: a captured resource's lifetime leaves this
            # frame — ownership transfer
            for name, rs in list(self.live.items()):
                if any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(stmt)):
                    for r in rs:
                        r.escaped = True
                    self.live.pop(name, None)
        else:
            self._effects(stmt, protected)

    def _effects(self, stmt: ast.stmt, protected: frozenset,
                 header_only: bool = False) -> None:
        # 1. releases
        scan = stmt
        if header_only:
            # loop headers: only the test/iter expression, not the body
            scan = stmt.test if isinstance(stmt, ast.While) else stmt.iter
        for n in self._released_names(scan):
            for r in self.live.pop(n, ()):  # any-path release semantics
                r.released = True
        # 2. escapes
        for n, rs in list(self.live.items()):
            if self._escapes_in(scan, n):
                for r in rs:
                    r.escaped = True
                self.live.pop(n, None)
        # 3. may-raise exposure for the still-live, unprotected names
        may_raise = isinstance(stmt, ast.Raise) or any(
            isinstance(x, ast.Call) for x in ast.walk(scan))
        if may_raise:
            for n, rs in self.live.items():
                if n in protected:
                    continue
                for r in rs:
                    if r.exposure is None:
                        r.exposure = (stmt.lineno,
                                      _src(scan).split("\n")[0][:80])
        # 4. acquisitions bind last (the bound name is live AFTER the
        #    acquiring statement)
        if not header_only and isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            if value is not None and len(targets) == 1 and \
                    isinstance(targets[0], ast.Name):
                acq = self._acquire_from(value)
                if acq is not None:
                    kind, desc = acq
                    res = _Res(targets[0].id, kind, desc, stmt.lineno)
                    self.all.append(res)
                    self.live[targets[0].id] = [res]

    @staticmethod
    def _none_tested(test: ast.AST) -> tuple[set[str], set[str]]:
        """Names known None in the body / in the orelse."""
        body_none: set[str] = set()
        orelse_none: set[str] = set()
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.left, ast.Name) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                body_none.add(test.left.id)
            elif isinstance(test.ops[0], ast.IsNot):
                orelse_none.add(test.left.id)
        elif isinstance(test, ast.UnaryOp) and \
                isinstance(test.op, ast.Not) and \
                isinstance(test.operand, ast.Name):
            body_none.add(test.operand.id)
        return body_none, orelse_none

    def _if(self, node: ast.If, protected: frozenset) -> None:
        # the test itself may raise (attribute/call in the condition)
        if any(isinstance(x, ast.Call) for x in ast.walk(node.test)):
            for n, rs in self.live.items():
                if n in protected:
                    continue
                for r in rs:
                    if r.exposure is None:
                        r.exposure = (node.lineno,
                                      _src(node.test).split("\n")[0][:80])
        body_none, orelse_none = self._none_tested(node.test)
        base = {k: list(v) for k, v in self.live.items()}
        for n in body_none:
            self.live.pop(n, None)   # ``if sock is None:`` — not live here
        self._block(node.body, protected)
        after_body = self.live
        self.live = {k: list(v) for k, v in base.items()}
        for n in orelse_none:
            self.live.pop(n, None)
        self._block(node.orelse, protected)
        merged: dict[str, list[_Res]] = {}
        for branch in (after_body, self.live):
            for k, rs in branch.items():
                out = merged.setdefault(k, [])
                for r in rs:
                    if r not in out and not r.released and not r.escaped:
                        out.append(r)
        self.live = {k: v for k, v in merged.items() if v}

    def _try(self, node: ast.Try, protected: frozenset) -> None:
        fin_released: set[str] = set()
        for s in node.finalbody:
            fin_released |= self._released_names(s)
        broad_released: set[str] = set()
        for h in node.handlers:
            if _is_broad_handler(h):
                for s in h.body:
                    broad_released |= self._released_names(s)
        self._block(node.body, protected | fin_released | broad_released)
        self._block(node.orelse, protected | fin_released)
        # handlers run on the exception path: isolated live view, so a
        # narrow handler's close counts as "released somewhere" (no
        # RL402) without ending the main path's liveness (RL401 stays)
        saved = {k: list(v) for k, v in self.live.items()}
        for h in node.handlers:
            self.live = {k: list(v) for k, v in saved.items()}
            self._block(h.body, protected | fin_released)
        self.live = saved
        self._block(node.finalbody, protected)

    def _with(self, node: ast.With, protected: frozenset) -> None:
        prot = set(protected)
        for item in node.items:
            ce = item.context_expr
            if self._acquire_from(ce) is not None:
                # ``with open(p) as f:`` — fully managed, never tracked
                continue
            name = None
            if isinstance(ce, ast.Name):
                name = ce.id                    # ``with sock:``
            elif isinstance(ce, ast.Call) and \
                    _attr_name(ce.func) == "closing" and ce.args and \
                    isinstance(ce.args[0], ast.Name):
                name = ce.args[0].id            # contextlib.closing(sock)
            if name is not None and name in self.live:
                for r in self.live.pop(name):
                    r.released = True
                prot.add(name)
            elif isinstance(ce, ast.Call):
                # other context managers may raise on __enter__
                for n, rs in self.live.items():
                    if n in prot:
                        continue
                    for r in rs:
                        if r.exposure is None:
                            r.exposure = (node.lineno,
                                          _src(ce).split("\n")[0][:80])
        self._block(node.body, frozenset(prot))


# --------------------------------------------------------------------------
# RL403 threads / RL404 queue acks

def _thread_call(call: ast.Call) -> bool | None:
    """None if not a Thread creation; else its daemon flag."""
    name = _attr_name(call.func)
    if name != "Thread":
        return None
    for kw in call.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and \
                bool(kw.value.value)
    return False


def _scan_threads(ps: "_PassState", mi: ModuleInfo, symbol: str,
                  fdef: ast.FunctionDef) -> None:
    local: dict[str, tuple[int, str]] = {}       # name -> (line, desc)
    self_attrs: dict[str, tuple[int, str]] = {}  # self.X -> (line, desc)
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.value, ast.Call):
            daemon = _thread_call(node.value)
            if daemon is None or daemon:
                continue
            t = node.targets[0]
            if isinstance(t, ast.Name):
                local[t.id] = (node.lineno, _src(node.value.func))
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                self_attrs[t.attr] = (node.lineno, _src(node.value.func))
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            # Thread(...).start() — fire-and-forget, no binding
            fn = node.value.func
            if isinstance(fn, ast.Attribute) and fn.attr == "start" and \
                    isinstance(fn.value, ast.Call) and \
                    _thread_call(fn.value) is False:
                ps.finding(
                    "RL403", mi.path, node.lineno, symbol,
                    detail=f"<anon>|{_src(fn.value.func)}",
                    message=("thread started without daemon=True and "
                             "never joined: a hung worker blocks "
                             "interpreter shutdown forever"))
    for name, (line, desc) in local.items():
        started = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "start"
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == name for n in ast.walk(fdef))
        if not started:
            continue
        joined = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == name for n in ast.walk(fdef))
        daemon_set = any(
            isinstance(n, ast.Assign)
            and any(isinstance(t, ast.Attribute) and t.attr == "daemon"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == name for t in n.targets)
            for n in ast.walk(fdef))
        escaped = any(
            isinstance(n, ast.Return) and isinstance(n.value, ast.Name)
            and n.value.id == name for n in ast.walk(fdef)) or any(
            isinstance(n, ast.Assign)
            and isinstance(n.value, ast.Name) and n.value.id == name
            for n in ast.walk(fdef))
        if not joined and not daemon_set and not escaped:
            ps.finding(
                "RL403", mi.path, line, symbol,
                detail=f"{name}|{desc}",
                message=(f"thread '{name}' started without daemon=True "
                         f"and never joined in this function: a hung "
                         f"worker blocks interpreter shutdown forever"))
    if self_attrs:
        ps.pending_self_threads.append((mi, symbol, self_attrs))


def _resolve_self_threads(ps: "_PassState",
                          class_bodies: dict) -> None:
    """``self.X = Thread(...)`` without daemon: the class must join it
    somewhere (any method) or set ``self.X.daemon``."""
    for mi, symbol, attrs in ps.pending_self_threads:
        cls = symbol.split(".", 1)[0]
        cdef = class_bodies.get((mi.path, cls))
        joined: set[str] = set()
        daemon_set: set[str] = set()
        if cdef is not None:
            for node in ast.walk(cdef):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "join":
                    v = node.func.value
                    if isinstance(v, ast.Attribute) and \
                            isinstance(v.value, ast.Name) and \
                            v.value.id == "self":
                        joined.add(v.attr)
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                t.attr == "daemon" and \
                                isinstance(t.value, ast.Attribute) and \
                                isinstance(t.value.value, ast.Name) and \
                                t.value.value.id == "self":
                            daemon_set.add(t.value.attr)
        for attr, (line, desc) in attrs.items():
            if attr in joined or attr in daemon_set:
                continue
            ps.finding(
                "RL403", mi.path, line, symbol,
                detail=f"self.{attr}|{desc}",
                message=(f"thread 'self.{attr}' is created without "
                         f"daemon=True and no method of {cls} joins it "
                         f"or sets .daemon: shutdown hangs on it"))


def _scan_task_done(ps: "_PassState", mi: ModuleInfo, symbol: str,
                    fdef: ast.FunctionDef) -> None:
    def emit(node: ast.Call) -> None:
        ps.finding(
            "RL404", mi.path, node.lineno, symbol,
            detail=_src(node.func),
            message=(f"{_src(node.func)}() is not inside a finally "
                     f"block: an exception in the work body skips the "
                     f"ack and wedges queue.join() forever"))

    def check_exprs(roots, in_finally: bool) -> None:
        if in_finally:
            return
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "task_done":
                    emit(node)

    def visit(stmts, in_finally: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Try):
                visit(stmt.body, in_finally)
                for h in stmt.handlers:
                    visit(h.body, in_finally)
                visit(stmt.orelse, in_finally)
                visit(stmt.finalbody, True)
            elif isinstance(stmt, (ast.If, ast.While, ast.For, ast.With,
                                   ast.AsyncWith, ast.AsyncFor)):
                headers = [getattr(stmt, a) for a in
                           ("test", "iter") if hasattr(stmt, a)]
                for item in getattr(stmt, "items", []):
                    headers.append(item.context_expr)
                check_exprs(headers, in_finally)
                visit(stmt.body, in_finally)
                visit(getattr(stmt, "orelse", []), in_finally)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested scope scanned separately
            else:
                check_exprs([stmt], in_finally)

    visit(fdef.body, False)


# --------------------------------------------------------------------------
# driver

@dataclass
class _PassState:
    findings: list = field(default_factory=list)
    pending_self_threads: list = field(default_factory=list)

    def finding(self, code, path, line, symbol, detail, message):
        self.findings.append(Finding(code, path, line, symbol, detail,
                                     message))


def run(ctx: AnalysisContext) -> list[Finding]:
    ps = _PassState()
    class_bodies: dict[tuple[str, str], ast.ClassDef] = {}
    for mi in ctx.modules:
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef):
                class_bodies[(mi.path, node.name)] = node
    for mi in ctx.modules:
        summaries = _build_summaries(mi)

        def walk_fn(fdef, cls_name, symbol):
            w = _LeakWalker(ps, mi, symbol, summaries, cls_name)
            w.run(fdef.body)
            _scan_threads(ps, mi, symbol, fdef)
            _scan_task_done(ps, mi, symbol, fdef)

        for node in mi.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(node, None, node.name)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        walk_fn(sub, node.name,
                                f"{node.name}.{sub.name}")
    _resolve_self_threads(ps, class_bodies)
    return ps.findings
