"""Lock-discipline pass: the PR 1 / PR 5 / PR 7 bug classes, as AST checks.

Three findings, built from one walk that tracks the lexically-held lock
set per function:

- **LD101 blocking-under-lock** — a call from the blocking registry
  (sleeps, socket/HTTP I/O, ``Future.result``, thread joins, bounded
  queue ops, ``RetryPolicy.call``, ``QueryService`` evaluation) made
  while a ``with <lock>:`` scope is open, directly or through a
  transitively-expanded ``self._method()`` chain. This is exactly the
  PR 7 priority inversion: rule
  evaluation ran under the state lock, so lock-free readers stalled
  behind a slow query.
- **LD102 lock-order-cycle** — ``with`` scopes that nest lock B inside
  lock A add a static edge A→B (one-level ``self._method()`` calls
  expand too); a cycle in the resulting cross-class graph is a
  potential deadlock. Edges between two instances created at the SAME
  site are ignored — static analysis cannot order instances, so a
  self-edge is reported by the runtime checker
  (``utils/lockcheck.py``) instead.
- **LD103 mixed-guard-attribute** — a ``self.X`` assigned both inside
  and outside ``with <lock>`` scopes (``__init__``/``__post_init__``
  excluded): either the lock is unnecessary or the unguarded store is a
  race (the PR 1 shared-``ExecContext`` class of bug). Methods named
  ``*_locked`` assert by convention that their caller holds the
  relevant lock, and their stores count as guarded.

Known approximations, by design: lock identity is lexical (class +
attribute name), call expansion is ``self.``-only (cross-object chains
are invisible), and receiver types are guessed from names (a ``.get``
only counts as a queue op when the receiver looks like a queue). The
runtime checker covers what static approximation cannot.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from filodb_tpu.analysis.model import Finding
from filodb_tpu.analysis.runner import AnalysisContext, ModuleInfo

# --------------------------------------------------------------------------
# blocking-call registry (configurable: tests/tools may extend)

# attribute names that block regardless of receiver
BLOCKING_ATTRS = {
    "sleep", "recv", "recv_into", "recvfrom", "sendall", "accept",
    "getresponse", "urlopen", "create_connection", "result",
    # QueryService evaluation — the PR 7 bug class
    "query_range", "execute_logical", "_execute_uncached",
}
# .connect blocks except for sqlite3.connect (local file open)
CONNECT_EXEMPT_RECEIVERS = {"sqlite3"}
# .join blocks only on thread-like receivers (str.join is everywhere)
JOIN_RECEIVER_HINTS = ("thread", "uploader", "worker")
# .get/.put block only on queue-like receivers (dict.get is everywhere)
QUEUE_RECEIVER_HINTS = ("queue", "_q")
# .call blocks on retry-policy receivers (it sleeps between attempts)
CALL_RECEIVER_HINTS = ("retry",)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


def _is_lock_factory(call: ast.AST) -> str | None:
    """Return the factory name if ``call`` creates a lock primitive:
    ``threading.Lock()``, ``Lock()``, ``_threading.RLock()``,
    ``field(default_factory=threading.Lock)``."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name in _LOCK_FACTORIES:
        return name
    if name == "field":
        for kw in call.keywords:
            if kw.arg == "default_factory":
                v = kw.value
                vn = v.attr if isinstance(v, ast.Attribute) else (
                    v.id if isinstance(v, ast.Name) else None)
                if vn in _LOCK_FACTORIES:
                    return vn
    return None


def blocking_desc(call: ast.Call) -> str | None:
    """Classify a call as blocking; returns a short stable description
    or None."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    recv = _src(fn.value)
    if attr in BLOCKING_ATTRS:
        return f"{recv}.{attr}()"
    if attr == "connect" and recv not in CONNECT_EXEMPT_RECEIVERS:
        return f"{recv}.{attr}()"
    low = recv.lower()
    if attr == "join" and any(h in low for h in JOIN_RECEIVER_HINTS):
        return f"{recv}.join()"
    if attr in ("get", "put") and (
            any(h in low for h in QUEUE_RECEIVER_HINTS)
            or low.endswith("_q") or low == "q"):
        return f"{recv}.{attr}()"
    if attr == "call" and any(h in low for h in CALL_RECEIVER_HINTS):
        return f"{recv}.call()"
    return None


# --------------------------------------------------------------------------
# per-module model

@dataclass
class _ClassInfo:
    name: str
    lock_attrs: set[str] = field(default_factory=set)   # self.X / cls.X
    cond_attrs: set[str] = field(default_factory=set)
    cond_wraps: dict[str, str] = field(default_factory=dict)  # cond -> lock
    methods: dict[str, "_MethodSummary"] = field(default_factory=dict)


@dataclass
class _MethodSummary:
    # locks acquired anywhere in the method: (lock_id, line)
    acquires: list = field(default_factory=list)
    # blocking calls NOT under any lock in the method: (desc, line)
    top_blocking: list = field(default_factory=list)
    # self-method calls NOT under any lock: (method_name, line) — these
    # propagate the callee's blocking/acquiring behavior to the caller
    # during transitive summary resolution
    top_self_calls: list = field(default_factory=list)


@dataclass
class _Deferred:
    """A self-method call made under held locks, resolved once every
    method summary exists (one-level interprocedural expansion)."""
    path: str
    cls: str
    method: str       # callee
    caller: str       # symbol of the calling method
    held: tuple       # lock ids held at the call
    line: int


def _collect_class_prelude(mi: ModuleInfo, cdef: ast.ClassDef
                           ) -> _ClassInfo:
    """First pass over a class: find its lock/condition attributes from
    ``self.X = threading.Lock()``-style stores (any method), class-body
    assignments, and dataclass ``field(default_factory=...)`` fields."""
    info = _ClassInfo(cdef.name)
    for node in ast.walk(cdef):
        target = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if target is None:
            continue
        factory = _is_lock_factory(value)
        if factory is None:
            continue
        attr = None
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in ("self", "cls"):
            attr = target.attr
        elif isinstance(target, ast.Name):
            attr = target.id     # class-body lock (FaultInjector style)
        if attr is None:
            continue
        info.lock_attrs.add(attr)
        if factory == "Condition":
            info.cond_attrs.add(attr)
            # Condition(self._lock) aliases an existing lock
            if isinstance(value, ast.Call) and value.args:
                a0 = value.args[0]
                if isinstance(a0, ast.Attribute) and \
                        isinstance(a0.value, ast.Name) and \
                        a0.value.id == "self":
                    info.cond_wraps[attr] = a0.attr
    return info


def _module_locks(mi: ModuleInfo) -> set[str]:
    out = set()
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _is_lock_factory(node.value):
            out.add(node.targets[0].id)
    return out


class _FunctionWalker(ast.NodeVisitor):
    """Walk one function/method body tracking the lexically-held lock
    stack; emits LD101 findings, lock-graph edges, deferred self-calls,
    and attribute-store records as it goes."""

    def __init__(self, pass_state: "_PassState", mi: ModuleInfo,
                 cls: _ClassInfo | None, symbol: str,
                 summary: _MethodSummary):
        self.ps = pass_state
        self.mi = mi
        self.cls = cls
        self.symbol = symbol
        self.summary = summary
        self.held: list[str] = []

    # ---- lock resolution

    def _lock_id(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if self.cls is not None and base in ("self", "cls") and \
                    attr in self.cls.lock_attrs:
                return f"{self.mi.path}::{self.cls.name}.{attr}"
            # ClassName._lock (class-body lock referenced by class name)
            if self.cls is not None and base == self.cls.name and \
                    attr in self.cls.lock_attrs:
                return f"{self.mi.path}::{self.cls.name}.{attr}"
        if isinstance(expr, ast.Name) and \
                expr.id in self.ps.module_locks.get(self.mi.path, ()):
            return f"{self.mi.path}::{expr.id}"
        return None

    def _canonical(self, lock_id: str) -> str:
        """Collapse a condition onto the lock it wraps, so ``with
        self._cond:`` and ``with self._lock:`` guard the same node."""
        if self.cls is None:
            return lock_id
        prefix = f"{self.mi.path}::{self.cls.name}."
        if lock_id.startswith(prefix):
            attr = lock_id[len(prefix):]
            wrapped = self.cls.cond_wraps.get(attr)
            if wrapped is not None and wrapped in self.cls.lock_attrs:
                return prefix + wrapped
        return lock_id

    # ---- visitors

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                lid = self._canonical(lid)
                self.summary.acquires.append((lid, node.lineno))
                for held in self.held:
                    if held != lid:
                        self.ps.add_edge(held, lid, self.mi.path,
                                         node.lineno, self.symbol)
                acquired.append(lid)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(acquired):]
        # with-items with side effects (calls) still need visiting
        for item in node.items:
            if not self._lock_id(item.context_expr):
                self.visit(item.context_expr)

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        desc = blocking_desc(node)
        if desc is not None:
            if self.held:
                blamed = self._blamed_locks(node)
                if blamed:
                    self.ps.finding(
                        "LD101", self.mi.path, node.lineno, self.symbol,
                        detail=f"{_short(blamed[-1])}|{desc}",
                        message=(f"blocking call {desc} while holding "
                                 f"{', '.join(_short(h) for h in blamed)}"))
            else:
                self.summary.top_blocking.append((desc, node.lineno))
        # self-call expansion (resolved transitively after all summaries
        # exist): under a lock it becomes a deferred check; outside any
        # lock it propagates the callee's behavior to this summary
        fn = node.func
        if self.cls is not None and \
                isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self":
            if self.held:
                self.ps.deferred.append(_Deferred(
                    self.mi.path, self.cls.name, fn.attr, self.symbol,
                    tuple(self.held), node.lineno))
            else:
                self.summary.top_self_calls.append((fn.attr,
                                                    node.lineno))
        self.generic_visit(node)

    def _blamed_locks(self, call: ast.Call) -> list[str]:
        """Held locks a blocking call is charged against. ``cond.wait``
        releases the condition's own lock, so only OTHER held locks are
        blamed for a wait."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in ("wait",
                                                         "wait_for"):
            lid = self._lock_id(fn.value)
            if lid is not None:
                released = self._canonical(lid)
                return [h for h in self.held if h != released]
        return list(self.held)

    def _record_store(self, target: ast.AST, line: int) -> None:
        if self.cls is None or not isinstance(target, ast.Attribute):
            return
        if not (isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            return
        attr = target.attr
        if attr in self.cls.lock_attrs or attr.startswith("__"):
            return
        # naming convention: a method named *_locked asserts its caller
        # holds the relevant lock, so its stores count as guarded
        under = bool(self.held) or any(
            part.endswith("_locked") for part in self.symbol.split("."))
        self.ps.attr_stores.setdefault(
            (self.mi.path, self.cls.name, attr), []).append(
                (under, line, self.symbol))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_store(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_store(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_store(node.target, node.lineno)
        self.generic_visit(node)

    # nested defs/lambdas run in their own frame (often another thread):
    # the held stack does not flow in, and their bodies get their own walk
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        inner = _FunctionWalker(self.ps, self.mi, self.cls,
                                f"{self.symbol}.{node.name}",
                                _MethodSummary())
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = _FunctionWalker(self.ps, self.mi, self.cls,
                                f"{self.symbol}.<lambda>",
                                _MethodSummary())
        inner.visit(node.body)


def _short(lock_id: str) -> str:
    return lock_id.rsplit("::", 1)[-1]


@dataclass
class _PassState:
    findings: list = field(default_factory=list)
    module_locks: dict = field(default_factory=dict)  # path -> set[str]
    classes: dict = field(default_factory=dict)       # (path, name) -> info
    # (path, cls, attr) -> [(under_lock, line, symbol)]
    attr_stores: dict = field(default_factory=dict)
    deferred: list = field(default_factory=list)
    # src -> {dst -> (path, line, symbol)} first-seen edge site
    edges: dict = field(default_factory=dict)

    def finding(self, code, path, line, symbol, detail, message):
        self.findings.append(Finding(code, path, line, symbol, detail,
                                     message))

    def add_edge(self, src, dst, path, line, symbol):
        self.edges.setdefault(src, {}).setdefault(dst,
                                                  (path, line, symbol))


def run(ctx: AnalysisContext) -> list[Finding]:
    ps = _PassState()
    for mi in ctx.modules:
        ps.module_locks[mi.path] = _module_locks(mi)
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef):
                ps.classes[(mi.path, node.name)] = \
                    _collect_class_prelude(mi, node)
    for mi in ctx.modules:
        _walk_module(ps, mi)
    _resolve_deferred(ps)
    _emit_mixed_guard(ps)
    _emit_cycles(ps)
    return ps.findings


def _walk_module(ps: _PassState, mi: ModuleInfo) -> None:
    def walk_fn(fdef, cls, symbol):
        summary = _MethodSummary()
        if cls is not None:
            cls.methods[fdef.name] = summary
        w = _FunctionWalker(ps, mi, cls, symbol, summary)
        for stmt in fdef.body:
            w.visit(stmt)

    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_fn(node, None, node.name)
        elif isinstance(node, ast.ClassDef):
            cls = ps.classes[(mi.path, node.name)]
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    walk_fn(sub, cls, f"{node.name}.{sub.name}")


def _method_closure(cls: _ClassInfo, method: str,
                    memo: dict, active: set
                    ) -> tuple[list, list]:
    """Transitive summary for ``self.<method>()``: the blocking calls
    (as ``(desc, call_chain)``) and lock acquisitions it performs while
    its own held set is empty — i.e. what a caller inherits by calling
    it. Self-recursive chains are cut by the ``active`` guard."""
    if method in memo:
        return memo[method]
    if method in active:
        return [], []
    summary = cls.methods.get(method)
    if summary is None:
        memo[method] = ([], [])
        return memo[method]
    active.add(method)
    blocking = [(desc, (method,)) for desc, _ln in summary.top_blocking]
    acquires = [lid for lid, _ln in summary.acquires]
    for callee, _ln in summary.top_self_calls:
        sub_b, sub_a = _method_closure(cls, callee, memo, active)
        blocking.extend((desc, (method,) + chain)
                        for desc, chain in sub_b)
        acquires.extend(sub_a)
    active.discard(method)
    # dedupe while keeping order stable
    blocking = list(dict.fromkeys(blocking))
    acquires = list(dict.fromkeys(acquires))
    memo[method] = (blocking, acquires)
    return memo[method]


def _resolve_deferred(ps: _PassState) -> None:
    """``self._method()`` calls made under a held lock inherit the
    callee's (transitively computed) blocking calls and lock
    acquisitions. Cross-object chains (``self.other.method()``) remain
    out of static scope — runtime checker territory."""
    memos: dict[tuple, dict] = {}
    for d in ps.deferred:
        cls = ps.classes.get((d.path, d.cls))
        if cls is None:
            continue
        memo = memos.setdefault((d.path, d.cls), {})
        blocking, acquires = _method_closure(cls, d.method, memo, set())
        for desc, chain in blocking:
            via = " -> ".join(f"self.{m}()" for m in chain)
            ps.finding(
                "LD101", d.path, d.line, d.caller,
                detail=f"{_short(d.held[-1])}|{'.'.join(chain)}:{desc}",
                message=(f"{via} makes blocking call {desc} while "
                         f"{', '.join(_short(h) for h in d.held)} "
                         f"is held here"))
        for lid in acquires:
            for held in d.held:
                if held != lid:
                    ps.add_edge(held, lid, d.path, d.line, d.caller)


def _emit_mixed_guard(ps: _PassState) -> None:
    skip_methods = ("__init__", "__post_init__")
    for (path, cls, attr), stores in sorted(ps.attr_stores.items()):
        live = [(u, ln, sym) for u, ln, sym in stores
                if not any(sym.endswith(m) for m in skip_methods)]
        under = [s for s in live if s[0]]
        outside = [s for s in live if not s[0]]
        if under and outside:
            _u, _uln, usym = under[0]
            _o, oln, osym = outside[0]
            ps.finding(
                "LD103", path, oln, f"{cls}",
                detail=attr,
                message=(f"self.{attr} is written under a lock in {usym} "
                         f"but without one in {osym} (first unguarded "
                         f"store shown); guard it or document why the "
                         f"race is benign"))


def _emit_cycles(ps: _PassState) -> None:
    # iterative Tarjan SCC over the static lock graph
    graph = {src: set(dsts) for src, dsts in ps.edges.items()}
    for dsts in list(graph.values()):
        for d in dsts:
            graph.setdefault(d, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)

    for scc in sccs:
        path, line, symbol = min(
            ps.edges[src][dst]
            for src in scc for dst in ps.edges.get(src, {})
            if dst in scc)
        cyc = " -> ".join(_short(n) for n in scc)
        ps.finding(
            "LD102", path, line, symbol,
            detail="|".join(scc),
            message=(f"potential lock-order cycle: {cyc} (locks "
                     f"acquired in both orders somewhere in the tree); "
                     f"impose a single acquisition order"))
