"""JAX hot-path hygiene pass for ``query/engine``.

Inside a jitted kernel a host sync (``.item()``, ``float(arr)``,
``np.asarray`` on a traced value) either fails under tracing or —
worse — silently forces a device round-trip per call, which is exactly
the per-step transfer cost the paper's batched design exists to avoid.
Python-side ``time``/``random`` calls are traced once at compile time
and frozen into the kernel, an outright correctness bug.

- **HP301 host-sync-in-kernel**: ``.item()``, ``.tolist()``,
  ``.block_until_ready()``, ``np.asarray``/``np.array``/
  ``np.frombuffer``, and ``float()``/``int()``/``bool()`` applied to an
  attribute/subscript expression (plain-``Name`` casts are skipped:
  they are usually static args, and flagging them would drown the pass
  in false positives).
- **HP302 wallclock-in-kernel**: ``time.*``, ``random.*``,
  ``np.random.*`` calls.

A function counts as a kernel when decorated ``@jax.jit`` / ``@jit`` /
``@partial(jax.jit, ...)``, when passed to ``pl.pallas_call``, when
wrapped in call form (``jit(fn)`` / ``jax.jit(fn)`` or
``shard_map(fn, ...)`` / ``_shard_map(fn, ...)`` — the factory idiom
``parallel/dist_query.py`` builds its SPMD programs with), or when
lexically nested inside a kernel.

The pass covers ``query/engine/`` and ``parallel/`` — the two places
jitted kernels live.
"""

from __future__ import annotations

import ast

from filodb_tpu.analysis.model import Finding
from filodb_tpu.analysis.runner import AnalysisContext

ENGINE_PREFIXES = ("filodb_tpu/query/engine/", "filodb_tpu/parallel/")

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_SYNC_FUNCS = {"asarray", "array", "frombuffer"}
_CAST_FUNCS = {"float", "int", "bool"}
_CLOCK_MODULES = {"time", "random"}


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


def _is_jit_decorator(dec: ast.AST) -> bool:
    # @jax.jit / @jit
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return True
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return True
    # @partial(jax.jit, ...) / @functools.partial(jit, ...) / @jit(...)
    if isinstance(dec, ast.Call):
        fn = dec.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if fname == "jit":
            return True
        if fname == "partial" and dec.args:
            return _is_jit_decorator(dec.args[0])
    return False


def _pallas_kernel_names(tree: ast.Module) -> set[str]:
    """Function names passed (positionally or as ``kernel=``) to
    ``pl.pallas_call``."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if fname != "pallas_call":
            continue
        cands = list(node.args[:1]) + [kw.value for kw in node.keywords
                                       if kw.arg == "kernel"]
        for c in cands:
            if isinstance(c, ast.Name):
                names.add(c.id)
            elif isinstance(c, ast.Call):  # partial(kernel_fn, ...)
                for a in c.args:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
    return names


def _wrapped_kernel_names(tree: ast.Module) -> set[str]:
    """Function names made kernels by call-form wrapping: the callee of
    ``shard_map(f, ...)`` / ``_shard_map(f, ...)`` and call-form
    ``jit(f)`` / ``jax.jit(f)`` (the ``parallel/dist_query.py`` factory
    idiom, which the decorator check cannot see)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if fname in ("shard_map", "_shard_map"):
            cands = list(node.args[:1]) + [kw.value for kw in node.keywords
                                           if kw.arg == "f"]
        elif fname == "jit":
            cands = list(node.args[:1])
        else:
            continue
        for c in cands:
            if isinstance(c, ast.Name):
                names.add(c.id)
            elif isinstance(c, ast.Call):  # partial(kernel_fn, ...)
                for a in c.args:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
    return names


class _KernelWalker(ast.NodeVisitor):
    def __init__(self, path: str, symbol: str, out: list[Finding]):
        self.path = path
        self.symbol = symbol
        self.out = out

    def _finding(self, code: str, node: ast.AST, detail: str,
                 message: str) -> None:
        self.out.append(Finding(code, self.path, node.lineno,
                                self.symbol, detail, message))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            recv_root = recv
            while isinstance(recv_root, ast.Attribute):
                recv_root = recv_root.value
            root_name = recv_root.id if isinstance(recv_root, ast.Name) \
                else None
            if fn.attr in _SYNC_METHODS:
                self._finding(
                    "HP301", node, f"{fn.attr}:{_src(recv)}",
                    f"host sync .{fn.attr}() on {_src(recv)} inside a "
                    f"jitted kernel")
            elif root_name == "np" and fn.attr in _NP_SYNC_FUNCS:
                self._finding(
                    "HP301", node, f"np.{fn.attr}:{_src(node.args[0]) if node.args else ''}",
                    f"np.{fn.attr}(...) materializes on host inside a "
                    f"jitted kernel; use jnp or hoist out of the kernel")
            elif root_name in _CLOCK_MODULES or (
                    root_name == "np" and isinstance(recv, ast.Attribute)
                    and recv.attr == "random"):
                self._finding(
                    "HP302", node, f"{_src(fn)}",
                    f"{_src(fn)}() is traced once at compile time and "
                    f"frozen into the kernel; pass values in as "
                    f"arguments instead")
        elif isinstance(fn, ast.Name) and fn.id in _CAST_FUNCS and \
                node.args and isinstance(node.args[0],
                                         (ast.Attribute, ast.Subscript)):
            self._finding(
                "HP301", node, f"{fn.id}:{_src(node.args[0])}",
                f"{fn.id}({_src(node.args[0])}) forces a host sync "
                f"inside a jitted kernel")
        self.generic_visit(node)

    # nested defs are scanned separately (with their own symbol) by the
    # scope walk in run(); don't double-report them here
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for mi in ctx.modules:
        if not mi.path.startswith(ENGINE_PREFIXES):
            continue
        pallas = _pallas_kernel_names(mi.tree) | _wrapped_kernel_names(
            mi.tree)

        def scan(fdef: ast.FunctionDef, symbol: str) -> None:
            w = _KernelWalker(mi.path, symbol, out)
            for stmt in fdef.body:
                w.visit(stmt)

        def visit_scope(body, prefix: str, inside_kernel: bool) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    sym = f"{prefix}{node.name}"
                    is_kernel = (inside_kernel
                                 or node.name in pallas
                                 or any(_is_jit_decorator(d)
                                        for d in node.decorator_list))
                    if is_kernel:
                        scan(node, sym)
                    # nested defs inherit kernel-ness lexically
                    visit_scope(node.body, f"{sym}.", is_kernel)
                elif isinstance(node, ast.ClassDef):
                    visit_scope(node.body, f"{node.name}.",
                                inside_kernel)

        visit_scope(mi.tree.body, "", False)
    return out
