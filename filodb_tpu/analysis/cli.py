"""filolint command-line driver.

Usage::

    python tools/filolint.py [--root REPO] [--baseline PATH]
                             [--update-baseline] [--format text|json]

Exit status: 0 when every finding is baselined (stale baseline entries
are warnings), 1 when new findings exist, 2 on analyzer errors (a file
that fails to parse is an analyzer error, not a clean run).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from filodb_tpu.analysis.model import Baseline
from filodb_tpu.analysis.runner import AnalysisContext, run_all

DEFAULT_BASELINE = os.path.join("conf", "filolint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="filolint",
        description="FiloDB concurrency-discipline and invariant "
                    "static analysis")
    ap.add_argument("--root", default=".",
                    help="repo root containing filodb_tpu/ "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: "
                         f"<root>/{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current finding "
                         "set (existing justifications are kept; new "
                         "entries get a TODO)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)

    # parse errors must fail loudly — an unparseable file is unanalyzed
    ctx = AnalysisContext.build(root)
    if ctx.errors:
        for e in ctx.errors:
            print(f"filolint: parse error: {e}", file=sys.stderr)
        return 2

    findings = run_all(root)

    if args.update_baseline:
        bl = Baseline.load(baseline_path)
        bl.update(findings)
        bl.save(baseline_path)
        print(f"filolint: wrote {len(bl.entries)} entries to "
              f"{baseline_path}")
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        bl = Baseline.load(baseline_path)
        new, stale = bl.diff(findings)

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) | {"key": f.key} for f in new],
            "stale_baseline": stale,
            "total_findings": len(findings),
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"filolint: warning: stale baseline entry "
                  f"{e['key']} (finding no longer produced; remove it)",
                  file=sys.stderr)
        if new:
            print(f"filolint: {len(new)} new finding(s) "
                  f"({len(findings)} total, "
                  f"{len(findings) - len(new)} baselined)",
                  file=sys.stderr)
        else:
            print(f"filolint: clean ({len(findings)} baselined "
                  f"finding(s))", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
