"""filolint command-line driver.

Usage::

    python tools/filolint.py [--root REPO] [--baseline PATH]
                             [--update-baseline]
                             [--format text|json|sarif]
                             [--changed-only]

Exit status: 0 when every finding is baselined (stale baseline entries
are warnings), 1 when new findings exist, 2 on analyzer errors (a file
that fails to parse is an analyzer error, not a clean run).

``--changed-only`` is the pre-commit fast path: the whole tree is still
parsed and every pass still runs (the passes need whole-repo context —
call closures, wire registry, dispatcher subclasses), but reported
findings are restricted to files in ``git diff --name-only HEAD`` plus
their reverse-import dependents, and stale-baseline warnings are
suppressed (an unchanged file's entries are out of scope).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import subprocess
import sys

from filodb_tpu.analysis.model import Baseline
from filodb_tpu.analysis.runner import AnalysisContext, run_all

DEFAULT_BASELINE = os.path.join("conf", "filolint_baseline.json")

# one-line rule descriptions for SARIF's tool.driver.rules
RULE_DESCRIPTIONS = {
    "LD101": "blocking call while holding a lock",
    "LD102": "statically-approximated lock-order cycle",
    "LD103": "attribute written both under and outside a lock",
    "RL401": "resource leaks on an exception path",
    "RL402": "resource acquired but never released",
    "RL403": "non-daemon thread started but never joined",
    "RL404": "queue task ack outside a finally block",
    "CP501": "dispatch blocks without consulting a deadline",
    "CP502": "query execution outside governor admission",
    "CP503": "breaker bookkeeping outside resilience.py",
    "CP504": "multiple breaker outcomes on one calling() path",
    "PR201": "wire registry closure violation",
    "PR202": "wire registry closure violation",
    "PR203": "metric name parity violation",
    "PR204": "metric name parity violation",
    "PR205": "Prometheus metric name charset violation",
    "HP301": "host sync inside a jitted kernel",
    "HP302": "wall-clock/randomness inside a jitted kernel",
}


def _changed_files(root: str) -> set[str] | None:
    """Repo-relative paths changed vs HEAD (staged + unstaged), or None
    when git is unavailable — the caller falls back to a full run."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return {line.strip().replace(os.sep, "/")
            for line in out.stdout.splitlines() if line.strip()}


def _module_name(path: str) -> str:
    # filodb_tpu/coordinator/remote.py -> filodb_tpu.coordinator.remote
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _dependent_closure(ctx: AnalysisContext, changed: set[str]) -> set[str]:
    """``changed`` plus every module that transitively imports one of
    them — a changed helper invalidates its callers' summaries."""
    by_name = {_module_name(m.path): m.path for m in ctx.modules}
    importers: dict[str, set[str]] = {}   # imported path -> {importer path}
    for m in ctx.modules:
        for node in ast.walk(m.tree):
            targets = []
            if isinstance(node, ast.Import):
                targets = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                targets = [node.module] + \
                    [f"{node.module}.{a.name}" for a in node.names]
            for t in targets:
                path = by_name.get(t)
                if path is not None:
                    importers.setdefault(path, set()).add(m.path)
    scope = set(changed)
    frontier = list(changed)
    while frontier:
        cur = frontier.pop()
        for dep in importers.get(cur, ()):
            if dep not in scope:
                scope.add(dep)
                frontier.append(dep)
    return scope


def _sarif(new, stale) -> dict:
    codes = sorted({f.code for f in new})
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "filolint",
                "informationUri": "doc/static_analysis.md",
                "rules": [{
                    "id": c,
                    "shortDescription": {"text": RULE_DESCRIPTIONS.get(
                        c, "filolint finding")},
                } for c in codes],
            }},
            "results": [{
                "ruleId": f.code,
                "level": "error",
                "message": {"text": f"[{f.symbol}] {f.message}"},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": f.line},
                    },
                }],
                # line-free identity so CI result matching survives
                # unrelated edits shifting line numbers
                "partialFingerprints": {"filolintKey": f.key},
            } for f in new],
            "invocations": [{
                "executionSuccessful": True,
                "toolExecutionNotifications": [{
                    "level": "warning",
                    "message": {"text": f"stale baseline entry "
                                        f"{e['key']}"},
                } for e in stale],
            }],
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="filolint",
        description="FiloDB concurrency-discipline and invariant "
                    "static analysis")
    ap.add_argument("--root", default=".",
                    help="repo root containing filodb_tpu/ "
                         "(default: cwd)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: "
                         f"<root>/{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current finding "
                         "set (existing justifications are kept; new "
                         "entries get a TODO)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files changed vs "
                         "HEAD plus their reverse-import dependents "
                         "(pre-commit fast mode; falls back to a full "
                         "run when git is unavailable)")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root)
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)

    # parse errors must fail loudly — an unparseable file is unanalyzed
    ctx = AnalysisContext.build(root)
    if ctx.errors:
        for e in ctx.errors:
            print(f"filolint: parse error: {e}", file=sys.stderr)
        return 2

    findings = run_all(root)

    changed_scope = None
    if args.changed_only:
        changed = _changed_files(root)
        if changed is None:
            print("filolint: warning: git diff unavailable, running on "
                  "the full tree", file=sys.stderr)
        else:
            changed_scope = _dependent_closure(ctx, changed)

    if args.update_baseline:
        bl = Baseline.load(baseline_path)
        bl.update(findings)
        bl.save(baseline_path)
        print(f"filolint: wrote {len(bl.entries)} entries to "
              f"{baseline_path}")
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        bl = Baseline.load(baseline_path)
        new, stale = bl.diff(findings)

    if changed_scope is not None:
        new = [f for f in new if f.path in changed_scope]
        # out-of-scope files were not (conceptually) analyzed, so their
        # stale entries are not evidence of anything
        stale = []

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) | {"key": f.key} for f in new],
            "stale_baseline": stale,
            "total_findings": len(findings),
        }, indent=2))
    elif args.format == "sarif":
        print(json.dumps(_sarif(new, stale), indent=2))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"filolint: warning: stale baseline entry "
                  f"{e['key']} (finding no longer produced; remove it)",
                  file=sys.stderr)
        if new:
            print(f"filolint: {len(new)} new finding(s) "
                  f"({len(findings)} total, "
                  f"{len(findings) - len(new)} baselined)",
                  file=sys.stderr)
        else:
            print(f"filolint: clean ({len(findings)} baselined "
                  f"finding(s))", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
