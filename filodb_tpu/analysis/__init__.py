"""filolint — concurrency-discipline and invariant static analysis.

Every review round before this package existed caught a concurrency
defect by hand: PR 1's thread-unsafe shared ``ExecContext`` in gather
workers, PR 5's compaction read race, PR 7's priority inversion from
rule evaluation blocking behind the state lock. With 30+ lock
instantiations across the tree, those bug classes are caught by a tool
now — the ThreadSanitizer/MapReduce-linter move of shifting a defect
class from review into CI.

Passes (each a ``run(ctx) -> list[Finding]`` module):

- :mod:`~filodb_tpu.analysis.lockdiscipline` — per-class lock graphs
  from ``with self._lock:`` scopes; blocking calls under a held lock
  (LD101), statically-approximated lock-order cycles (LD102), and
  attributes mutated both under and outside any lock (LD103).
- :mod:`~filodb_tpu.analysis.lifecycle` — interprocedural resource
  lifecycle: acquire/release pairs through exception paths and local
  call closures. Leak-on-exception (RL401), never-released (RL402),
  non-daemon thread never joined (RL403), queue ack outside finally
  (RL404).
- :mod:`~filodb_tpu.analysis.chokepoint` — whole-repo choke-point
  proofs: dispatch without a deadline (CP501), query execution outside
  governor admission (CP502), breaker bookkeeping outside resilience.py
  (CP503), double outcome in one ``calling()`` path (CP504).
- :mod:`~filodb_tpu.analysis.parity` — wire-registry closure (PR201/2),
  ``filodb_*`` metric name parity with the scrape test's expected lists
  (PR203/4), Prometheus name charset (PR205).
- :mod:`~filodb_tpu.analysis.hotpath` — host syncs and Python-side
  wall-clock/randomness inside jitted ``query/engine`` kernels
  (HP301/2).
- :mod:`~filodb_tpu.analysis.decisionparity` — adaptive-planner settle
  parity: every ``cost_model.decide()``/``classify()`` site must settle
  its decision (``record_actual``/``defer``) or return it to a caller
  that does, or the learned estimates silently drift (DC601).

Findings diff against a checked-in baseline (``conf/
filolint_baseline.json``) so the CI gate (``tests/test_filolint.py``)
fails only on NEW violations; see ``doc/static_analysis.md``.
"""

from filodb_tpu.analysis.model import Baseline, Finding
from filodb_tpu.analysis.runner import AnalysisContext, run_all

__all__ = ["AnalysisContext", "Baseline", "Finding", "run_all"]
