"""Adaptive-decision settle-parity pass (DC601).

The trace-driven planner (``query/cost_model.py``) only stays
calibrated if every routed decision is eventually *settled* with the
observed wall time: a ``decide()``/``classify()`` call whose outcome is
never fed back leaves that arm's estimate frozen at whatever it last
learned, silently mis-routing every future query with that signature.
That failure mode is invisible at the decision site — the query still
returns the right answer — so it is exactly the kind of defect this
package exists to move from review into CI.

DC601: a function that calls ``.decide(...)`` or ``.classify(...)`` on
a cost model must, in the same function, do one of:

- call ``.record_actual(...)`` (inline settle, e.g. tier paging);
- call ``.defer(...)`` (carrier hand-off; settled later by
  ``settle_deferred`` at the timing boundary, e.g. the sidecar gate);
- ``return`` the name the decision was bound to (explicit hand-off to
  the caller, which then owns the settle — e.g. the lane router's
  ``_shared_decision``).

Static approximations: receiver types are not resolved — any
``.decide``/``.classify`` attribute call counts, which is fine in this
tree because only the cost model exposes those names; the return
hand-off matches any ``return`` whose expression mentions a name bound
from the decision call (covers ``return d.arm, d, model``). The model's
own module is exempt — it constructs ``Decision`` objects internally.
"""

from __future__ import annotations

import ast

from filodb_tpu.analysis.model import Finding
from filodb_tpu.analysis.runner import AnalysisContext

_DECIDE_ATTRS = ("decide", "classify")
_SETTLE_ATTRS = ("record_actual", "defer")
_EXEMPT = ("filodb_tpu/query/cost_model.py",)


def _attr_name(node: ast.Call) -> str | None:
    return node.func.attr if isinstance(node.func, ast.Attribute) else None


def _own_nodes(fn: ast.AST):
    """Walk a function body without descending into nested defs, so a
    decision made in a closure is attributed to the closure."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _check_function(fn, symbol: str, path: str, out: list[Finding]) -> None:
    decides: list[tuple[int, str]] = []      # (line, detail)
    bound: set[str] = set()                  # names assigned from decide()
    settled = False
    returned: set[str] = set()               # names mentioned in returns

    for node in _own_nodes(fn):
        if isinstance(node, ast.Call):
            attr = _attr_name(node)
            if attr in _DECIDE_ATTRS:
                site = node.args[0].value if node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) else attr
                decides.append((node.lineno, f"{attr}:{site}"))
            elif attr in _SETTLE_ATTRS:
                settled = True
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call) and \
                    _attr_name(node.value) in _DECIDE_ATTRS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    returned.add(sub.id)

    if not decides or settled or bound & returned:
        return
    for line, detail in decides:
        out.append(Finding(
            "DC601", path, line, symbol, detail,
            f"{detail.split(':', 1)[0]}() routes by learned cost but this "
            f"function neither settles the decision (record_actual/defer) "
            f"nor returns it to a caller that could — the arm's estimate "
            f"never updates and the model drifts"))


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for mi in ctx.modules:
        if mi.path in _EXEMPT:
            continue

        def walk(node, symbol):
            for child in ast.iter_child_nodes(node):
                sym = symbol
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    sym = f"{symbol}.{child.name}" \
                        if symbol not in ("<module>",) else child.name
                    _check_function(child, sym, mi.path, out)
                elif isinstance(child, ast.ClassDef):
                    sym = child.name
                walk(child, sym)

        walk(mi.tree, "<module>")
    return out
