"""Choke-point taint pass (CP5xx): whole-repo reachability proofs for
the resilience invariants that were hand-restored in PR 1 / PR 4 review
and that nothing previously stopped a new call site from bypassing.

- **CP501 deadline-dropped dispatch** — every ``PlanDispatcher``
  subclass whose ``dispatch`` closure (nested defs and transitive
  self-calls included) performs blocking work must reference a
  ``deadline`` somewhere in that closure. A dispatcher that blocks on
  the network without consulting ``ctx.deadline`` turns one slow peer
  into an unbounded client hang. The multi-process mesh transport's
  ``exec_descriptors`` is a second network entry point on the same
  class family and is held to the same proof.
- **CP502 governor-admission bypass** — outside the plan-tree internals
  (``filodb_tpu/query/``, ``filodb_tpu/parallel/``, which sit *below*
  the admission gate), any ``<x>.dispatcher.dispatch(...)`` call,
  mesh-engine or mesh-cluster ``execute*`` call, or raw
  ``<x>.do_execute(...)`` call
  must be lexically inside a ``with ...admit(...)`` scope. Entry paths
  that skip governor admission starve the overload protections the
  soak tests exercise. ``query/federation.py`` is carved OUT of the
  below-gate skip: federated tier sub-queries must stay provably under
  the single admit() at ``_execute_uncached`` (i.e. route through
  ``gather``), never grow their own dispatch entry path.
- **CP503 breaker bookkeeping outside resilience.py** — direct calls to
  ``guard`` / ``record_success`` / ``record_failure`` /
  ``cancel_probe`` anywhere except ``utils/resilience.py`` bypass the
  one-outcome-per-admission accounting that ``calling()`` enforces;
  ``force_open`` is exempt (a failure-detector verdict, not a call
  outcome).
- **CP504 breaker double outcome** — inside
  ``with <x>.calling(...) as out:``, the maximum number of
  ``out.success()`` / ``out.failure()`` calls along any single path
  must be <= 1 (the ``_BreakerOutcome`` is one-shot; a second call on
  the same path is dead bookkeeping at best and a double-count race at
  worst). Alternative paths — if/else branches, distinct except
  handlers — each get their own budget.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from filodb_tpu.analysis.lockdiscipline import blocking_desc
from filodb_tpu.analysis.model import Finding
from filodb_tpu.analysis.runner import AnalysisContext, ModuleInfo

BREAKER_BOOKKEEPING = {"guard", "record_success", "record_failure",
                       "cancel_probe"}
RESILIENCE_PATH = "filodb_tpu/utils/resilience.py"
# modules below the admission gate: plan-tree / engine internals where
# dispatcher.dispatch recursion is expected to already be admitted
BELOW_GATE_PREFIXES = ("filodb_tpu/query/", "filodb_tpu/parallel/")
# carve-out from the below-gate skip: federation composes whole tier
# sub-queries and is the one query/ module that could plausibly grow a
# direct dispatch / do_execute entry path around the governor — scan it
# like coordinator code so federated sub-query execution stays provably
# under the admit() gate (TierExec must route through self.gather)
GATED_QUERY_MODULES = ("filodb_tpu/query/federation.py",)
# coordinator modules that are nonetheless below the gate:
# ReplicaDispatcher is a PlanDispatcher routing layer — its dispatch()
# is only ever reached through an already-admitted plan tree, and its
# candidate fan-out (hedge/failover recursion into the wrapped
# per-node dispatchers) must not re-admit: one query, one admission
BELOW_GATE_MODULES = ("filodb_tpu/coordinator/replication.py",)
DISPATCHER_BASE = "PlanDispatcher"


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


# --------------------------------------------------------------------------
# CP501: deadline-dropped dispatch

def _dispatcher_classes(ctx: AnalysisContext) -> list[tuple[ModuleInfo,
                                                            ast.ClassDef]]:
    """Fixpoint over base-name edges seeded at ``PlanDispatcher``."""
    classes: list[tuple[ModuleInfo, ast.ClassDef]] = []
    for mi in ctx.modules:
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef):
                classes.append((mi, node))
    dispatcher_names = {DISPATCHER_BASE}
    changed = True
    while changed:
        changed = False
        for _, cdef in classes:
            if cdef.name in dispatcher_names:
                continue
            for base in cdef.bases:
                name = base.attr if isinstance(base, ast.Attribute) else (
                    base.id if isinstance(base, ast.Name) else None)
                if name in dispatcher_names:
                    dispatcher_names.add(cdef.name)
                    changed = True
    return [(mi, cdef) for mi, cdef in classes
            if cdef.name in dispatcher_names
            and cdef.name != DISPATCHER_BASE]


def _methods(cdef: ast.ClassDef) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in cdef.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _closure_scan(cdef: ast.ClassDef, method: str, memo: dict,
                  active: set) -> tuple[list[tuple[int, str]], bool]:
    """(blocking sites, references-deadline) over ``method`` plus its
    transitive self-call closure, nested defs included."""
    if method in memo:
        return memo[method]
    if method in active:
        return [], False
    methods = _methods(cdef)
    fdef = methods.get(method)
    if fdef is None:
        return [], False
    active.add(method)
    blocking: list[tuple[int, str]] = []
    deadline = False
    for node in ast.walk(fdef):
        if isinstance(node, ast.Name) and node.id == "deadline":
            deadline = True
        elif isinstance(node, ast.Attribute) and node.attr == "deadline":
            deadline = True
        elif isinstance(node, ast.Call):
            desc = blocking_desc(node)
            if desc is not None:
                blocking.append((node.lineno, desc))
            fn = node.func
            if isinstance(fn, ast.Attribute) and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id == "self" and fn.attr in methods:
                sub_b, sub_d = _closure_scan(cdef, fn.attr, memo, active)
                blocking.extend(
                    (node.lineno, f"{d} (via self.{fn.attr})")
                    for _, d in sub_b)
                deadline = deadline or sub_d
    active.discard(method)
    memo[method] = (blocking, deadline)
    return memo[method]


# dispatcher entry points that take network-bound work on behalf of a
# query: the classic plan-tree dispatch plus the multi-process mesh
# transport's descriptor fan-out
DISPATCH_ENTRY_METHODS = ("dispatch", "exec_descriptors")


def _check_cp501(ps: "_PassState", ctx: AnalysisContext) -> None:
    for mi, cdef in _dispatcher_classes(ctx):
        methods = _methods(cdef)
        for entry in DISPATCH_ENTRY_METHODS:
            if entry not in methods:
                continue
            blocking, deadline = _closure_scan(cdef, entry, {}, set())
            if blocking and not deadline:
                line, desc = blocking[0]
                ps.finding(
                    "CP501", mi.path, line, f"{cdef.name}.{entry}",
                    detail=desc,
                    message=(f"{entry} blocks on {desc} but never "
                             f"references a deadline anywhere in its "
                             f"call closure: one slow peer hangs the "
                             f"caller unboundedly (thread the "
                             f"ctx.deadline budget into the blocking "
                             f"call)"))


# --------------------------------------------------------------------------
# CP502: governor-admission bypass

def _is_admit_with(node: ast.With) -> bool:
    for item in node.items:
        ce = item.context_expr
        if isinstance(ce, ast.Call) and \
                isinstance(ce.func, ast.Attribute) and \
                ce.func.attr == "admit":
            return True
    return False


def _is_gated_call(call: ast.Call) -> str | None:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    if fn.attr == "dispatch" and isinstance(fn.value, ast.Attribute) \
            and fn.value.attr == "dispatcher":
        return f"{_src(fn)}()"
    if fn.attr.startswith("execute") and "mesh_engine" in _src(fn.value):
        return f"{_src(fn)}()"
    # the multi-process mesh runtime fans a query out to worker
    # processes: same admission contract as the in-process engine
    if fn.attr.startswith("execute") and "mesh_cluster" in _src(fn.value):
        return f"{_src(fn)}()"
    # raw plan-node execution: calling do_execute bypasses BOTH the
    # admission gate and ExecPlan.execute's span/limit bookkeeping
    if fn.attr == "do_execute":
        return f"{_src(fn)}()"
    return None


def _check_cp502(ps: "_PassState", ctx: AnalysisContext) -> None:
    for mi in ctx.modules:
        if (mi.path.startswith(BELOW_GATE_PREFIXES)
                or mi.path in BELOW_GATE_MODULES) \
                and mi.path not in GATED_QUERY_MODULES:
            continue

        def scan(stmts, admitted: bool, symbol: str):
            for stmt in stmts:
                inner = admitted
                if isinstance(stmt, (ast.With, ast.AsyncWith)) and \
                        _is_admit_with(stmt):
                    inner = True
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # nested defs inherit the lexical admission scope
                    scan(stmt.body, admitted, f"{symbol}.{stmt.name}")
                    continue
                if not inner:
                    for node in ast.iter_child_nodes(stmt):
                        if not isinstance(node, (ast.stmt,)):
                            for sub in ast.walk(node):
                                if isinstance(sub, ast.Call):
                                    desc = _is_gated_call(sub)
                                    if desc is not None:
                                        ps.finding(
                                            "CP502", mi.path,
                                            sub.lineno, symbol,
                                            detail=desc,
                                            message=_CP502_MSG % desc)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        scan(sub, inner, symbol)
                for h in getattr(stmt, "handlers", []):
                    scan(h.body, inner, symbol)

        for node in mi.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(node.body, False, node.name)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        scan(sub.body, False, f"{node.name}.{sub.name}")


_CP502_MSG = ("%s executes query work outside any governor admit() "
              "scope: this entry path bypasses overload admission "
              "(wrap it in `with governor().admit(...)` like "
              "_execute_uncached / PlanExecutorServer._handle)")


# --------------------------------------------------------------------------
# CP503: direct breaker bookkeeping

def _check_cp503(ps: "_PassState", ctx: AnalysisContext) -> None:
    for mi in ctx.modules:
        if mi.path == RESILIENCE_PATH:
            continue
        symbol_of = _symbol_index(mi)
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in BREAKER_BOOKKEEPING:
                recv = _src(node.func.value).lower()
                # record_success/record_failure/cancel_probe are
                # breaker-specific names; the generic `guard` only
                # counts on a breaker-shaped receiver
                if node.func.attr == "guard" and "breaker" not in recv:
                    continue
                sym = symbol_of(node.lineno)
                ps.finding(
                    "CP503", mi.path, node.lineno, sym,
                    detail=f"{_src(node.func)}",
                    message=(f"direct breaker bookkeeping "
                             f"`{_src(node.func)}()` outside "
                             f"utils/resilience.py bypasses the "
                             f"one-outcome-per-admission contract of "
                             f"calling(); use `with breaker.calling()` "
                             f"or justify in the baseline"))


def _symbol_index(mi: ModuleInfo):
    spans: list[tuple[int, int, str]] = []
    for node in mi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.lineno, node.end_lineno or node.lineno,
                          node.name))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    spans.append((sub.lineno, sub.end_lineno or
                                  sub.lineno, f"{node.name}.{sub.name}"))

    def lookup(line: int) -> str:
        for lo, hi, name in spans:
            if lo <= line <= hi:
                return name
        return "<module>"

    return lookup


# --------------------------------------------------------------------------
# CP504: breaker double outcome

def _max_outcomes(stmts, out_name: str) -> int:
    """Max count of ``out.success()``/``out.failure()`` on any single
    path through ``stmts``. Sequential statements sum; branches take
    the max of their alternatives."""
    total = 0
    for stmt in stmts:
        total += _stmt_outcomes(stmt, out_name)
    return total


def _expr_outcomes(node: ast.AST, out_name: str) -> int:
    n = 0
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in ("success", "failure") and \
                isinstance(sub.func.value, ast.Name) and \
                sub.func.value.id == out_name:
            n += 1
    return n


def _stmt_outcomes(stmt: ast.stmt, out_name: str) -> int:
    if isinstance(stmt, ast.If):
        return _expr_outcomes(stmt.test, out_name) + max(
            _max_outcomes(stmt.body, out_name),
            _max_outcomes(stmt.orelse, out_name))
    if isinstance(stmt, ast.Try):
        main = _max_outcomes(stmt.body, out_name) + \
            _max_outcomes(stmt.orelse, out_name)
        handlers = max(
            (_max_outcomes(h.body, out_name) for h in stmt.handlers),
            default=0)
        # body and handler are treated as alternative paths (the common
        # body-records-or-handler-records shape must stay clean), so
        # max rather than sum
        return max(main, handlers) + _max_outcomes(stmt.finalbody,
                                                   out_name)
    if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
        return _max_outcomes(stmt.body, out_name) + \
            _max_outcomes(stmt.orelse, out_name)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _max_outcomes(stmt.body, out_name)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return 0
    return _expr_outcomes(stmt, out_name)


def _check_cp504(ps: "_PassState", ctx: AnalysisContext) -> None:
    for mi in ctx.modules:
        symbol_of = _symbol_index(mi)
        for node in ast.walk(mi.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                ce = item.context_expr
                if not (isinstance(ce, ast.Call) and
                        isinstance(ce.func, ast.Attribute) and
                        ce.func.attr == "calling"):
                    continue
                if not isinstance(item.optional_vars, ast.Name):
                    continue   # no ``as out`` -> calling() does it all
                out_name = item.optional_vars.id
                worst = _max_outcomes(node.body, out_name)
                if worst > 1:
                    ps.finding(
                        "CP504", mi.path, node.lineno,
                        symbol_of(node.lineno),
                        detail=f"{_src(ce.func)} as {out_name}",
                        message=(f"some path through this calling() "
                                 f"block records {worst} outcomes on "
                                 f"'{out_name}': _BreakerOutcome is "
                                 f"one-shot, so the extras are dead "
                                 f"bookkeeping or a double-count"))


# --------------------------------------------------------------------------
# driver

@dataclass
class _PassState:
    findings: list = field(default_factory=list)

    def finding(self, code, path, line, symbol, detail, message):
        self.findings.append(Finding(code, path, line, symbol, detail,
                                     message))


def run(ctx: AnalysisContext) -> list[Finding]:
    ps = _PassState()
    _check_cp501(ps, ctx)
    _check_cp502(ps, ctx)
    _check_cp503(ps, ctx)
    _check_cp504(ps, ctx)
    return ps.findings
