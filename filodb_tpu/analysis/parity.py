"""Invariant/registry parity pass.

Two registries in this tree are correctness-critical and historically
hand-maintained:

- the **wire registry** (``coordinator/wire.py``): decode instantiates
  only registered classes, so a dataclass that rides inside a
  registered class but is itself unregistered fails at runtime, on the
  first frame that carries it (PR201); a registry entry naming a class
  that no longer exists is dead weight and hides typos (PR202);
- the **scrape-test name lists** (``tests/test_metrics_scrape.py``):
  the breadth test asserts exposition families by name, so a metric
  created at import time but missing from the lists is silently
  untested (PR203), and a listed name nothing produces any more is a
  stale assertion waiting to fail (PR204).

PR205 checks every metric name literal against the Prometheus data-model
charset (``[a-zA-Z_:][a-zA-Z0-9_:]*``).

PR206 hardens the freshness-telemetry families: any metric whose name
starts with ``filodb_ingest_`` or ``filodb_selfmon_`` must appear in the
scrape-test lists REGARDLESS of the lazy/GaugeFn exemptions PR203 grants
— these series are the self-monitoring substrate (``_meta`` dataset,
default lag alerts), so an unasserted family here means the monitoring
of the monitor is untested.

PR207 extends the same no-exemption treatment to the aggregate-pyramid
families (``filodb_pyramid_``): the cold-tier zero-payload guarantee is
asserted through these counters (``core/store/pyramid.py``,
``query/engine/pyramid_lane.py``), and they all register when
objectstore imports pyramid at boot, so every family must be pinned in
the scrape test.

Static approximations: the wire walk mirrors ``_build_registry`` by
reading its two loops from the AST (explicit tuple + subclass-walked
bases) and closing over AST-declared subclasses; metric creations made
lazily inside functions are exempt from PR203 (they register on first
use, which the breadth test cannot see) but still count as producers
for PR204.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from filodb_tpu.analysis.model import Finding
from filodb_tpu.analysis.runner import AnalysisContext

_METRIC_FACTORIES = {
    # factory -> exposition-name suffixes rendered for base name N
    "Counter": ("_total",),
    "get_counter": ("_total",),
    "Gauge": ("",),
    "get_gauge": ("",),
    "GaugeFn": ("",),
    "Histogram": ("_bucket", "_count", "_sum"),
}

_PROM_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


@dataclass
class _MetricSite:
    name: str
    path: str
    line: int
    symbol: str
    factory: str
    module_level: bool

    @property
    def exposed(self) -> list[str]:
        return [self.name + sfx
                for sfx in _METRIC_FACTORIES[self.factory]]


def _call_factory(node: ast.Call) -> str | None:
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    return name if name in _METRIC_FACTORIES else None


def _collect_metric_sites(ctx: AnalysisContext) -> list[_MetricSite]:
    sites: list[_MetricSite] = []

    def walk(node, path, symbol, in_function):
        for child in ast.iter_child_nodes(node):
            sym, in_fn = symbol, in_function
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                sym = f"{symbol}.{child.name}" if symbol != "<module>" \
                    else child.name
                in_fn = True
            elif isinstance(child, ast.Lambda):
                in_fn = True
            elif isinstance(child, ast.ClassDef):
                sym = child.name
            elif isinstance(child, ast.Call):
                factory = _call_factory(child)
                if factory and child.args and \
                        isinstance(child.args[0], ast.Constant) and \
                        isinstance(child.args[0].value, str):
                    sites.append(_MetricSite(
                        child.args[0].value, path, child.lineno,
                        symbol, factory, not in_function))
            walk(child, path, sym, in_fn)

    for mi in ctx.modules:
        walk(mi.tree, mi.path, "<module>", False)
    return sites


# --------------------------------------------------------------------------
# wire registry

@dataclass
class _WireDecl:
    explicit: list          # [(name, line)] from the `for cls in (...)` loop
    bases: list             # [name] from the subclass-walk loop
    line: int = 0


def _parse_registry(ctx: AnalysisContext) -> _WireDecl | None:
    mi = ctx.module(ctx.wire_module)
    if mi is None:
        return None
    fn = next((n for n in mi.tree.body
               if isinstance(n, ast.FunctionDef)
               and n.name == "_build_registry"), None)
    if fn is None:
        return None
    decl = _WireDecl([], [], fn.lineno)

    def names_of(it):
        out = []
        if isinstance(it, ast.Tuple):
            for e in it.elts:
                if isinstance(e, ast.Name):
                    out.append((e.id, e.lineno))
                elif isinstance(e, ast.Attribute):
                    out.append((e.attr, e.lineno))
        return out

    for node in ast.walk(fn):
        if isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name):
            if node.target.id == "cls":
                decl.explicit.extend(names_of(node.iter))
            elif node.target.id == "base":
                decl.bases.extend(n for n, _ in names_of(node.iter))
    return decl


@dataclass
class _ClassDecl:
    name: str
    path: str
    line: int
    bases: list
    is_dataclass: bool
    has_wire_fields: bool
    field_type_names: set = field(default_factory=set)


def _index_classes(ctx: AnalysisContext) -> dict[str, _ClassDecl]:
    idx: dict[str, _ClassDecl] = {}
    for mi in ctx.modules:
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            base_names = []
            for b in node.bases:
                if isinstance(b, ast.Name):
                    base_names.append(b.id)
                elif isinstance(b, ast.Attribute):
                    base_names.append(b.attr)
            is_dc = any(
                (isinstance(d, ast.Name) and d.id == "dataclass")
                or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
                or (isinstance(d, ast.Call) and _decname(d.func)
                    == "dataclass")
                for d in node.decorator_list)
            has_wf = any(
                isinstance(s, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__wire_fields__"
                    for t in s.targets)
                for s in node.body)
            types: set[str] = set()
            for s in node.body:
                if isinstance(s, ast.AnnAssign):
                    for sub in ast.walk(s.annotation):
                        if isinstance(sub, ast.Name):
                            types.add(sub.id)
                        elif isinstance(sub, ast.Attribute):
                            types.add(sub.attr)
                        elif isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            # string annotation: pull identifiers
                            types.update(re.findall(r"[A-Za-z_]\w*",
                                                    sub.value))
            # first definition wins; duplicates across modules are rare
            idx.setdefault(node.name, _ClassDecl(
                node.name, mi.path, node.lineno, base_names, is_dc,
                has_wf, types))
    return idx


def _registered_closure(decl: _WireDecl,
                        classes: dict[str, _ClassDecl]) -> set[str]:
    reg = {n for n, _ in decl.explicit} | set(decl.bases)
    children: dict[str, set[str]] = {}
    for c in classes.values():
        for b in c.bases:
            children.setdefault(b, set()).add(c.name)
    frontier = list(decl.bases)
    while frontier:
        cur = frontier.pop()
        for kid in children.get(cur, ()):
            if kid not in reg:
                reg.add(kid)
                frontier.append(kid)
    return reg


def _check_wire(ctx: AnalysisContext, out: list[Finding]) -> None:
    decl = _parse_registry(ctx)
    if decl is None:
        out.append(Finding(
            "PR202", ctx.wire_module, 1, "<module>", "_build_registry",
            "could not locate _build_registry(); wire parity unchecked"))
        return
    classes = _index_classes(ctx)
    registered = _registered_closure(decl, classes)

    for name, line in decl.explicit:
        if name not in classes:
            out.append(Finding(
                "PR202", ctx.wire_module, line, "_build_registry", name,
                f"registry names {name} but no class of that name "
                f"exists in the package"))

    # closure: field annotations of registered classes must resolve to
    # registered classes whenever they name a package dataclass
    for name in sorted(registered):
        c = classes.get(name)
        if c is None:
            continue
        for t in sorted(c.field_type_names):
            ref = classes.get(t)
            if ref is None or t in registered or t == name:
                continue
            if ref.is_dataclass or ref.has_wire_fields:
                out.append(Finding(
                    "PR201", ref.path, ref.line, ref.name, ref.name,
                    f"{ref.name} is carried in wire-registered "
                    f"{name}.{'<field>'} but is not itself registered "
                    f"in coordinator/wire.py"))

    # a class declaring __wire_fields__ has exactly one purpose — ship
    # on the wire — so an unregistered one is always a bug
    for c in classes.values():
        if c.has_wire_fields and c.name not in registered:
            out.append(Finding(
                "PR201", c.path, c.line, c.name, c.name,
                f"{c.name} declares __wire_fields__ but is not "
                f"registered in coordinator/wire.py"))


# --------------------------------------------------------------------------
# metric parity

def _scrape_expected(ctx: AnalysisContext) -> tuple[set[str], int] | None:
    mi = ctx.read(ctx.scrape_test)
    if mi is None:
        return None
    names: set[str] = set()
    first_line = 1
    for node in mi.tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.List)):
            continue
        elts = node.value.elts
        if not elts or not all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in elts):
            continue
        first_line = first_line if names else node.lineno
        names.update(e.value for e in elts)
    return names, first_line


def _check_metrics(ctx: AnalysisContext, out: list[Finding]) -> None:
    sites = _collect_metric_sites(ctx)

    for s in sites:
        if not _PROM_NAME_RE.match(s.name):
            out.append(Finding(
                "PR205", s.path, s.line, s.symbol, s.name,
                f"metric name {s.name!r} violates the Prometheus "
                f"charset [a-zA-Z_:][a-zA-Z0-9_:]*"))

    got = _scrape_expected(ctx)
    if got is None:
        out.append(Finding(
            "PR204", ctx.scrape_test, 1, "<module>", "<missing>",
            "scrape test not found; metric parity unchecked"))
        return
    expected, list_line = got
    expected_filodb = {n for n in expected if n.startswith("filodb_")}

    # PR203: import-time filodb_* metric not covered by the breadth test.
    # GaugeFn is exempt: a callback returning None drops the series from
    # the exposition, so the family is allowed to be conditional and the
    # breadth test cannot assert it unconditionally.
    for s in sites:
        if not s.module_level or not s.name.startswith("filodb_") \
                or s.factory == "GaugeFn":
            continue
        missing = [e for e in s.exposed if e not in expected]
        for e in missing:
            out.append(Finding(
                "PR203", s.path, s.line, s.symbol, e,
                f"import-time metric {s.name!r} renders family {e!r} "
                f"which no expected-name list in {ctx.scrape_test} "
                f"asserts"))

    # PR206: ingest/selfmon freshness families must be breadth-tested no
    # matter how they register. Lazy registration (shard start) and
    # GaugeFn conditionality do not exempt them: the scrape fixture boots
    # shards and drives ingest, so every family here renders, and these
    # are the series the _meta self-monitoring loop alerts on.
    seen206: set[tuple[str, str]] = set()
    for s in sites:
        if not s.name.startswith(("filodb_ingest_", "filodb_selfmon_")):
            continue
        for e in s.exposed:
            if e in expected or (s.name, e) in seen206:
                continue
            seen206.add((s.name, e))
            out.append(Finding(
                "PR206", s.path, s.line, s.symbol, e,
                f"freshness-telemetry metric {s.name!r} renders family "
                f"{e!r} which no expected-name list in "
                f"{ctx.scrape_test} asserts (the lazy/GaugeFn "
                f"exemptions do not apply to ingest/selfmon families)"))

    # PR207: aggregate-pyramid families must be breadth-tested the same
    # way — they carry the cold-tier zero-payload accounting, register at
    # import (objectstore imports pyramid), and render at zero before any
    # cold fold, so neither the lazy nor the GaugeFn exemption applies.
    seen207: set[tuple[str, str]] = set()
    for s in sites:
        if not s.name.startswith("filodb_pyramid_"):
            continue
        for e in s.exposed:
            if e in expected or (s.name, e) in seen207:
                continue
            seen207.add((s.name, e))
            out.append(Finding(
                "PR207", s.path, s.line, s.symbol, e,
                f"aggregate-pyramid metric {s.name!r} renders family "
                f"{e!r} which no expected-name list in "
                f"{ctx.scrape_test} asserts (pyramid families carry the "
                f"zero-payload accounting and register at import; no "
                f"exemptions apply)"))

    # PR204: asserted name no creation site produces (lazy sites count)
    produced: set[str] = set()
    for s in sites:
        produced.update(s.exposed)
    for name in sorted(expected_filodb - produced):
        out.append(Finding(
            "PR204", ctx.scrape_test, list_line, "<module>", name,
            f"scrape test expects family {name!r} but no metric "
            f"creation in filodb_tpu/ produces it"))


def _decname(fn) -> str | None:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    _check_wire(ctx, out)
    _check_metrics(ctx, out)
    return out
