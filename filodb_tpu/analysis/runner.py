"""Parse-once analysis context and the pass runner.

Every pass consumes :class:`AnalysisContext` — the repo's source files
parsed a single time into ``(path, ast, source_lines)`` records — so
adding a pass costs one AST walk, not a re-read of the tree.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from filodb_tpu.analysis.model import Finding, suppressed

# directories under the package root whose files are analyzed; tools/
# and tests/ are consumers of the analyzer, not subjects (the parity
# pass reads the scrape test separately, as data)
_SKIP_PARTS = {"__pycache__"}


@dataclass
class ModuleInfo:
    path: str                 # repo-relative posix path
    tree: ast.Module
    lines: list[str]


@dataclass
class AnalysisContext:
    root: str                             # repo root (absolute)
    modules: list[ModuleInfo] = field(default_factory=list)
    scrape_test: str = os.path.join("tests", "test_metrics_scrape.py")
    wire_module: str = os.path.join("filodb_tpu", "coordinator", "wire.py")
    errors: list[str] = field(default_factory=list)

    @classmethod
    def build(cls, root: str, package: str = "filodb_tpu"
              ) -> "AnalysisContext":
        ctx = cls(root=os.path.abspath(root))
        pkg_root = os.path.join(ctx.root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_PARTS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    ctx.add_file(os.path.join(dirpath, name))
        return ctx

    def add_file(self, abspath: str) -> None:
        rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=rel)
        except (OSError, SyntaxError) as e:
            self.errors.append(f"{rel}: {e}")
            return
        self.modules.append(ModuleInfo(rel, tree, src.splitlines()))

    def module(self, rel_path: str) -> ModuleInfo | None:
        rel = rel_path.replace(os.sep, "/")
        for m in self.modules:
            if m.path == rel:
                return m
        return None

    def read(self, rel_path: str) -> ModuleInfo | None:
        """Parse a file outside the package set (e.g. the scrape test)."""
        abspath = os.path.join(self.root, rel_path)
        if not os.path.exists(abspath):
            return None
        try:
            with open(abspath, encoding="utf-8") as f:
                src = f.read()
            return ModuleInfo(rel_path.replace(os.sep, "/"),
                              ast.parse(src, filename=rel_path),
                              src.splitlines())
        except (OSError, SyntaxError) as e:
            self.errors.append(f"{rel_path}: {e}")
            return None


def run_all(root: str, passes=None) -> list[Finding]:
    """Run every pass over the tree at ``root``; inline-suppressed
    findings are dropped here so passes never special-case comments."""
    from filodb_tpu.analysis import (chokepoint, decisionparity, hotpath,
                                     lifecycle, lockdiscipline, parity)

    ctx = AnalysisContext.build(root)
    findings: list[Finding] = []
    for mod in (passes or (lockdiscipline, lifecycle, chokepoint,
                           parity, hotpath, decisionparity)):
        findings.extend(mod.run(ctx))
    by_path = {m.path: m.lines for m in ctx.modules}
    out = []
    for f in findings:
        lines = by_path.get(f.path)
        if lines is None:
            mi = ctx.module(f.path) or ctx.read(f.path)
            lines = mi.lines if mi else []
            by_path[f.path] = lines
        if not suppressed(lines, f.line, f.code):
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.code, f.detail))
    # identity is line-free, so two sites with the same key are ONE
    # finding (e.g. two recv calls in the same helper); keep the first
    seen: set[str] = set()
    deduped = []
    for f in out:
        if f.key not in seen:
            seen.add(f.key)
            deduped.append(f)
    return deduped
