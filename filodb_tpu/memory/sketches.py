"""Mergeable sketches for the approximate query lane: top-k and
count-distinct over the part-key population of a segment/bucket.

Counterparts of the "Building Wavelet Histograms on Large Data in
MapReduce" merge algebra (PAPERS.md): every sketch here is a commutative
monoid — ``merge(a, b)`` of two sketches over disjoint data equals the
sketch of the union — so pyramid levels (chunk → segment → bucket →
query) can fold them bottom-up without revisiting payloads.  The value
histograms themselves ride as the log2 sketches in ``memory/chunk.py``;
this module adds the population sketches a `topk(k, ...)` or a
series-cardinality estimate needs:

- :class:`TopKSketch` — per-key running max with capacity pruning.  For
  the pyramid each part key lives in exactly one storage bucket, so
  merging per-bucket sketches of capacity ≥ k yields the EXACT global
  top-k of per-series maxima; the lane still declares the result
  approximate (``FILODB_SIDECAR_APPROX``) because pruning makes the
  general merge lossy.
- :class:`HLLSketch` — classic HyperLogLog (p=10, 1024 byte registers,
  σ ≈ 3.25%) over part-key blobs for count-distinct.

Both serialize to small byte strings that ride in pyramid object
footers (``core/store/pyramid.py``); neither imports the object store.
"""

from __future__ import annotations

import hashlib
import struct

import numpy as np


def _hash64(blob: bytes) -> int:
    """Stable 64-bit hash of a key blob (blake2b — stdlib, keyed runs
    reproduce across processes, unlike ``hash()``)."""
    return int.from_bytes(
        hashlib.blake2b(blob, digest_size=8).digest(), "little")


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uint64 -> uint64 well-mixed bits
    (for benchmark-scale synthetic key populations where per-key blake2b
    would dominate the measurement)."""
    x = np.asarray(x, np.uint64)
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> np.uint64(30)))
         * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> np.uint64(27)))
         * np.uint64(0x94D049BB133111EB)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    return x ^ (x >> np.uint64(31))


class TopKSketch:
    """Top-k of per-key maxima: ``{key_blob: running max}`` pruned to
    ``capacity`` entries (keep the largest).  Merge is union-max then
    prune — exact while every key's full contribution lands in one
    sketch (the pyramid's per-bucket partitioning guarantees that)."""

    __slots__ = ("capacity", "entries")

    def __init__(self, capacity: int = 64,
                 entries: dict[bytes, float] | None = None):
        self.capacity = capacity
        self.entries: dict[bytes, float] = entries or {}

    def update(self, key: bytes, value: float) -> None:
        v = float(value)
        if v != v:  # NaN never competes
            return
        cur = self.entries.get(key)
        if cur is None or v > cur:
            self.entries[key] = v
            if len(self.entries) > 2 * self.capacity:
                self._prune()

    def _prune(self) -> None:
        if len(self.entries) > self.capacity:
            keep = sorted(self.entries.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:self.capacity]
            self.entries = dict(keep)

    def merge(self, other: "TopKSketch") -> "TopKSketch":
        for k, v in other.entries.items():
            cur = self.entries.get(k)
            if cur is None or v > cur:
                self.entries[k] = v
        self._prune()
        return self

    def top(self, k: int) -> list[tuple[bytes, float]]:
        self._prune()
        return sorted(self.entries.items(),
                      key=lambda kv: (-kv[1], kv[0]))[:k]

    def serialize(self) -> bytes:
        self._prune()
        parts = [struct.pack("<II", self.capacity, len(self.entries))]
        for k, v in sorted(self.entries.items()):
            parts.append(struct.pack("<H", len(k)))
            parts.append(k)
            parts.append(struct.pack("<d", v))
        return b"".join(parts)

    @staticmethod
    def deserialize(data: bytes, off: int = 0) -> tuple["TopKSketch", int]:
        cap, n = struct.unpack_from("<II", data, off)
        off += 8
        entries: dict[bytes, float] = {}
        for _ in range(n):
            (klen,) = struct.unpack_from("<H", data, off)
            off += 2
            k = bytes(data[off:off + klen])
            off += klen
            (v,) = struct.unpack_from("<d", data, off)
            off += 8
            entries[k] = v
        return TopKSketch(cap, entries), off


# HLL bias constant for m = 2^p registers (p >= 7: 0.7213/(1+1.079/m))
_HLL_P = 10
_HLL_M = 1 << _HLL_P


class HLLSketch:
    """HyperLogLog count-distinct, p=10 (1024 uint8 registers, standard
    error 1.04/sqrt(1024) ≈ 3.25%).  Merge = elementwise register max."""

    __slots__ = ("registers",)

    def __init__(self, registers: np.ndarray | None = None):
        self.registers = (np.zeros(_HLL_M, np.uint8) if registers is None
                          else np.asarray(registers, np.uint8))

    def add(self, blob: bytes) -> None:
        self.update_hashes(np.array([_hash64(blob)], np.uint64))

    def update_hashes(self, h: np.ndarray) -> None:
        """Fold pre-hashed uint64 values (vectorized bulk path)."""
        h = np.asarray(h, np.uint64)
        if h.size == 0:
            return
        idx = (h & np.uint64(_HLL_M - 1)).astype(np.int64)
        w = h >> np.uint64(_HLL_P)
        # rank = 1 + leading zeros of the remaining 54 bits
        nbits = 64 - _HLL_P
        rank = np.full(h.shape, nbits + 1, np.uint8)
        wk = w.copy()
        bits = np.zeros(h.shape, np.int64)
        for shift in (32, 16, 8, 4, 2, 1):
            m = wk >= (np.uint64(1) << np.uint64(shift))
            bits[m] += shift
            wk[m] >>= np.uint64(shift)
        nz = w != 0
        rank[nz] = (nbits - bits[nz]).astype(np.uint8)
        np.maximum.at(self.registers, idx, rank)

    def merge(self, other: "HLLSketch") -> "HLLSketch":
        np.maximum(self.registers, other.registers, out=self.registers)
        return self

    def estimate(self) -> float:
        regs = self.registers.astype(np.float64)
        alpha = 0.7213 / (1.0 + 1.079 / _HLL_M)
        est = alpha * _HLL_M * _HLL_M / np.sum(2.0 ** -regs)
        if est <= 2.5 * _HLL_M:
            zeros = int(np.count_nonzero(self.registers == 0))
            if zeros:
                return _HLL_M * np.log(_HLL_M / zeros)
        return float(est)

    def serialize(self) -> bytes:
        return self.registers.astype("<u1").tobytes()

    @staticmethod
    def deserialize(data: bytes, off: int = 0) -> tuple["HLLSketch", int]:
        regs = np.frombuffer(data, "<u1", _HLL_M, off).copy()
        return HLLSketch(regs), off + _HLL_M
