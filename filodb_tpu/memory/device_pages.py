"""Device-decodable chunk pages: bit-packed columns that decode ON the TPU.

The NibblePack wire format (byte-granular, data-dependent layout) is ideal
for host/C++ decode but hostile to SIMD/TPU lanes. For the query hot path we
re-encode chunks into **device pages**: fixed 128-value blocks (one VPU lane
row) with per-block fixed bit widths — decode is pure shifts/masks/prefix
sums with no data-dependent control flow, implemented twice:

- ``decode_*_jax``   — pure jnp (works everywhere, XLA-fused)
- ``decode_*_pallas``— Pallas TPU kernel (grid over blocks, VMEM tiles),
  with ``interpret=True`` fallback used in CPU tests

Timestamp layout (delta-delta, reference ``DeltaDeltaVector`` semantics):
  per block: base i64, slope i32, width w; 128 zigzag residuals bit-packed
  into ``ceil(128*w/32)`` u32 words. value[i] = base + slope*i + zz(resid).

Float layout (XOR against block's first value, f32 lanes):
  per block: first u32 bit pattern, width w; 128 XOR deltas bit-packed.
  Unlike the reference's f64 stream XOR, deltas XOR against the *block
  first* value, not the previous sample — this removes the sequential
  dependency so lanes decode independently (trailing zero bits dropped).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 128
WORDS_PER_BLOCK_MAX = BLOCK  # at w=32: 128*32/32


@dataclass
class DevicePage:
    """One column encoded for device decode."""

    n: int                      # valid values
    kind: str                   # "ts" | "f32"
    bases: np.ndarray           # ts: int64 [nb]; f32: uint32 [nb]
    slopes: np.ndarray          # ts: int32 [nb]; f32: zeros
    widths: np.ndarray          # int32 [nb], bits per packed value
    words: np.ndarray           # uint32 [nb, words_per_block] (padded)

    @property
    def num_blocks(self) -> int:
        return len(self.bases)

    @property
    def nbytes(self) -> int:
        return (self.bases.nbytes + self.slopes.nbytes + self.widths.nbytes
                + self.words.nbytes)


def _bit_width_u32(x: np.ndarray) -> int:
    m = int(x.max()) if len(x) else 0
    return int(m).bit_length()


def _pack_block(vals_u32: np.ndarray, w: int) -> np.ndarray:
    """Pack BLOCK u32 values of width w into ceil(BLOCK*w/32) u32 words."""
    nwords = -(-BLOCK * w // 32) if w else 0
    out = np.zeros(WORDS_PER_BLOCK_MAX, np.uint32)
    if w == 0:
        return out
    acc = 0
    accbits = 0
    wi = 0
    mask = (1 << w) - 1
    for v in vals_u32:
        acc |= (int(v) & mask) << accbits
        accbits += w
        while accbits >= 32:
            out[wi] = acc & 0xFFFFFFFF
            acc >>= 32
            accbits -= 32
            wi += 1
    if accbits:
        out[wi] = acc & 0xFFFFFFFF
    return out


def encode_ts_page(ts: np.ndarray) -> DevicePage:
    """Delta-delta encode timestamps into device blocks."""
    ts = np.ascontiguousarray(ts, np.int64)
    n = len(ts)
    nb = max(-(-n // BLOCK), 1)
    bases = np.zeros(nb, np.int64)
    slopes = np.zeros(nb, np.int32)
    widths = np.zeros(nb, np.int32)
    words = np.zeros((nb, WORDS_PER_BLOCK_MAX), np.uint32)
    for b in range(nb):
        seg = ts[b * BLOCK : (b + 1) * BLOCK]
        if len(seg) == 0:
            continue
        base = int(seg[0])
        slope = int((int(seg[-1]) - base) // max(len(seg) - 1, 1))
        resid = seg - (base + slope * np.arange(len(seg), dtype=np.int64))
        zz = ((resid << 1) ^ (resid >> 63)).astype(np.uint64)
        assert zz.max(initial=0) < 2**32, "residual too large for ts page"
        zz32 = zz.astype(np.uint32)
        pad = np.zeros(BLOCK, np.uint32)
        pad[: len(seg)] = zz32
        w = _bit_width_u32(zz32)
        bases[b], slopes[b], widths[b] = base, slope, w
        words[b] = _pack_block(pad, w)
    return DevicePage(n, "ts", bases, slopes, widths, words)


def encode_f32_page(vals: np.ndarray) -> DevicePage:
    """XOR-vs-block-first encode float32 values into device blocks."""
    v = np.ascontiguousarray(vals, np.float32)
    n = len(v)
    nb = max(-(-n // BLOCK), 1)
    bases = np.zeros(nb, np.uint32)
    slopes = np.zeros(nb, np.int32)
    widths = np.zeros(nb, np.int32)
    words = np.zeros((nb, WORDS_PER_BLOCK_MAX), np.uint32)
    for b in range(nb):
        seg = v[b * BLOCK : (b + 1) * BLOCK]
        if len(seg) == 0:
            continue
        bits = seg.view(np.uint32)
        first = bits[0]
        xored = bits ^ first
        # drop common trailing zero bits across the block
        nz = xored[xored != 0]
        tz = 32
        for x in nz:
            xi = int(x)
            t = (xi & -xi).bit_length() - 1
            tz = min(tz, t)
            if tz == 0:
                break
        if len(nz) == 0:
            tz = 32
        shifted = (xored >> np.uint32(tz % 32)) if tz < 32 else \
            np.zeros_like(xored)
        w = _bit_width_u32(shifted)
        pad = np.zeros(BLOCK, np.uint32)
        pad[: len(seg)] = shifted
        bases[b] = first
        slopes[b] = tz  # reuse the slope slot for the shift amount
        widths[b] = w
        words[b] = _pack_block(pad, w)
    return DevicePage(n, "f32", bases, slopes, widths, words)


# ---------------------------------------------------------------------------
# pure-jax decode (used everywhere; XLA fuses into downstream kernels)

def _unpack_block_jax(words, w):
    """words u32 [nwords]; returns u32 [BLOCK] of width-w fields.
    No data-dependent shapes: lane i reads bits [i*w, i*w+w)."""
    i = jnp.arange(BLOCK, dtype=jnp.uint32)
    bit0 = i * w.astype(jnp.uint32)
    word_idx = (bit0 >> 5).astype(jnp.int32)
    bit_off = bit0 & 31
    lo = words[jnp.clip(word_idx, 0, words.shape[0] - 1)]
    hi = words[jnp.clip(word_idx + 1, 0, words.shape[0] - 1)]
    mask = jnp.where(w >= 32, jnp.uint32(0xFFFFFFFF),
                     (jnp.uint32(1) << w.astype(jnp.uint32)) - 1)
    lo_part = lo >> bit_off
    hi_part = jnp.where(bit_off > 0, hi << (32 - bit_off), 0).astype(
        jnp.uint32)
    out = (lo_part | hi_part) & mask
    return jnp.where(w == 0, 0, out).astype(jnp.uint32)


@jax.jit
def decode_ts_page_jax(bases, slopes, widths, words):
    """→ int64-equivalent timestamps as int32 relative... returns int64 when
    x64 enabled, else float64-safe int32 path is caller's concern. Here we
    produce int64 via two int32 halves when x64 is off is unnecessary —
    callers rebase to the batch base; we return (nb, BLOCK) int32 offsets
    from each block base plus the int64 bases."""
    def one(base, slope, w, wd):
        zz = _unpack_block_jax(wd, w)
        resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
        pred = slope * jnp.arange(BLOCK, dtype=jnp.int32)
        return pred + resid  # offsets from block base

    return jax.vmap(one)(bases, slopes, widths, words)


@jax.jit
def decode_f32_page_jax(bases, shifts, widths, words):
    def one(first, tz, w, wd):
        x = _unpack_block_jax(wd, w)
        xored = jnp.where(tz >= 32, jnp.uint32(0),
                          x << tz.astype(jnp.uint32))
        bits = xored ^ first
        return jax.lax.bitcast_convert_type(bits, jnp.float32)

    return jax.vmap(one)(bases, shifts, widths, words)


# ---------------------------------------------------------------------------
# pallas decode kernels
#
# Mosaic (real-TPU) lowering constraints shape the design (validated on a
# live v5e, tools/tpu_pallas_check.py):
#   - rank-1 blocks and (1, N) tiles don't lower → grid steps cover ROWS=8
#     blocks at a time with (8, 128)-tiled VMEM blocks (native sublane×lane
#     tile for 32-bit types);
#   - SMEM only serves scalar reads → per-block width/slope/first scalars
#     ride as scalar-prefetch operands, read with an unrolled 8-scalar loop;
#   - lane-dim gather (`take_along_axis`) and per-lane variable shifts DO
#     lower, so the bit-unpack stays a gather + shift/mask program.

ROWS = 8  # blocks decoded per grid step


def _unpack_tile(w_col, words, out_dtype=jnp.uint32):
    """Shared (ROWS, BLOCK) bit-unpack: width-w_col fields from words."""
    col = jax.lax.broadcasted_iota(jnp.uint32, (ROWS, BLOCK), 1)
    bit0 = col * w_col
    word_idx = (bit0 >> 5).astype(jnp.int32)
    bit_off = bit0 & 31
    lo = jnp.take_along_axis(words, word_idx, axis=1)
    hi = jnp.take_along_axis(
        words, jnp.minimum(word_idx + 1, WORDS_PER_BLOCK_MAX - 1), axis=1)
    mask = jnp.where(w_col >= 32, jnp.uint32(0xFFFFFFFF),
                     (jnp.uint32(1) << w_col) - jnp.uint32(1))
    val = ((lo >> bit_off)
           | jnp.where(bit_off > 0, hi << (32 - bit_off), 0).astype(
               jnp.uint32)) & mask
    return jnp.where(w_col == 0, jnp.uint32(0), val)


def _smem_col(ref, base, dtype=None):
    """Read ROWS consecutive SMEM scalars into an (ROWS, 1) vector."""
    vals = [ref[base + r] for r in range(ROWS)]
    v = jnp.stack(vals).reshape(ROWS, 1)
    return v if dtype is None else v.astype(dtype)


def _ts_kernel(slopes_ref, widths_ref, words_ref, out_ref):
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    w_col = _smem_col(widths_ref, g * ROWS, jnp.uint32)
    slope_col = _smem_col(slopes_ref, g * ROWS)
    zz = _unpack_tile(w_col, words_ref[...])
    resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
    pred = slope_col * jax.lax.broadcasted_iota(jnp.int32, (ROWS, BLOCK), 1)
    out_ref[...] = pred + resid


def _pad_blocks(arrs, nb):
    """Pad leading (block) dim of each array to a multiple of ROWS."""
    nb_pad = -(-nb // ROWS) * ROWS
    if nb_pad == nb:
        return arrs, nb_pad
    return [jnp.pad(a, [(0, nb_pad - nb)] + [(0, 0)] * (a.ndim - 1))
            for a in arrs], nb_pad


def decode_ts_page_pallas(slopes, widths, words, interpret: bool = False):
    """Pallas grid over 8-block tiles: per-block offsets from the block base
    (reference hot-path decode `DeltaDeltaDataReader` semantics, on device)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb = slopes.shape[0]
    (slopes, widths, words), nb_pad = _pad_blocks(
        [slopes, widths, words], nb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb_pad // ROWS,),
        in_specs=[pl.BlockSpec((ROWS, WORDS_PER_BLOCK_MAX),
                               lambda g, *_: (g, 0))],
        out_specs=pl.BlockSpec((ROWS, BLOCK), lambda g, *_: (g, 0)),
    )
    out = pl.pallas_call(
        _ts_kernel,
        out_shape=jax.ShapeDtypeStruct((nb_pad, BLOCK), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(slopes, widths, words)
    return out[:nb]


def page_to_arrays(page: DevicePage):
    """Device arrays for the decode kernels."""
    return (jnp.asarray(page.bases), jnp.asarray(page.slopes),
            jnp.asarray(page.widths), jnp.asarray(page.words))


def _f32_kernel(firsts_ref, shifts_ref, widths_ref, words_ref, out_ref):
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    w_col = _smem_col(widths_ref, g * ROWS, jnp.uint32)
    tz_col = _smem_col(shifts_ref, g * ROWS, jnp.uint32)
    first_col = jax.lax.bitcast_convert_type(
        _smem_col(firsts_ref, g * ROWS), jnp.uint32)
    x = _unpack_tile(w_col, words_ref[...])
    xored = jnp.where(tz_col >= 32, jnp.uint32(0), x << tz_col)
    bits = xored ^ first_col
    out_ref[...] = jax.lax.bitcast_convert_type(bits, jnp.float32)


def decode_f32_page_pallas(firsts, shifts, widths, words,
                           interpret: bool = False):
    """Pallas grid over 8-block tiles: XOR-vs-first float decode on device."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb = firsts.shape[0]
    # SMEM carries i32 scalars; ship the u32 bit patterns bitcast to i32.
    firsts_i32 = jax.lax.bitcast_convert_type(
        jnp.asarray(firsts), jnp.int32)
    (firsts_i32, shifts, widths, words), nb_pad = _pad_blocks(
        [firsts_i32, shifts, widths, words], nb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nb_pad // ROWS,),
        in_specs=[pl.BlockSpec((ROWS, WORDS_PER_BLOCK_MAX),
                               lambda g, *_: (g, 0))],
        out_specs=pl.BlockSpec((ROWS, BLOCK), lambda g, *_: (g, 0)),
    )
    out = pl.pallas_call(
        _f32_kernel,
        out_shape=jax.ShapeDtypeStruct((nb_pad, BLOCK), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(firsts_i32, shifts, widths, words)
    return out[:nb]
