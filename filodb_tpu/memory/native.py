"""ctypes bindings to the C++ native runtime (``native/filodb_native.cpp``).

Builds the shared library on demand (cached by source mtime) and exposes:
- fast NibblePack pack/unpack, zigzag, XOR-double prep — byte-identical to
  the numpy reference implementations; used by the ingest/flush hot path.
- the block arena (reference ``BlockManager`` semantics).

Falls back gracefully (``HAVE_NATIVE = False``) when no compiler is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libfilodb_native.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "filodb_native.cpp")

_lib = None
_lock = threading.Lock()
HAVE_NATIVE = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception as e:  # pragma: no cover - toolchain missing
        log.warning("native build failed, using numpy codecs: %s", e)
        return False


def _load():
    global _lib, HAVE_NATIVE
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO_PATH)
                or os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:  # pragma: no cover
            log.warning("native load failed: %s", e)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        i64 = ctypes.c_int64
        lib.nibble_pack.argtypes = [u64p, i64, u8p]
        lib.nibble_pack.restype = i64
        lib.nibble_unpack.argtypes = [u8p, i64, u64p, i64]
        lib.nibble_unpack.restype = i64
        lib.murmur3_32.argtypes = [u8p, i64, ctypes.c_uint32]
        lib.murmur3_32.restype = ctypes.c_uint32
        lib.zigzag_encode_i64.argtypes = [i64p, u64p, i64]
        lib.zigzag_decode_u64.argtypes = [u64p, i64p, i64]
        lib.xor_encode_f64.argtypes = [f64p, u64p, i64]
        lib.xor_decode_f64.argtypes = [u64p, f64p, i64]
        lib.delta_delta_residuals.argtypes = [i64p, i64, i64, i64, i64p]
        lib.delta_delta_residuals.restype = ctypes.c_int
        lib.delta_delta_reconstruct.argtypes = [i64p, i64, i64, i64, i64p]
        lib.arena_create.argtypes = [i64]
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_alloc_block.argtypes = [ctypes.c_void_p, i64]
        lib.arena_alloc_block.restype = ctypes.c_void_p
        lib.block_alloc.argtypes = [ctypes.c_void_p, i64]
        lib.block_alloc.restype = i64
        lib.block_data.argtypes = [ctypes.c_void_p]
        lib.block_data.restype = u8p
        lib.block_remaining.argtypes = [ctypes.c_void_p]
        lib.block_remaining.restype = i64
        lib.arena_reclaim_owner.argtypes = [ctypes.c_void_p, i64]
        lib.arena_reclaim_owner.restype = i64
        lib.arena_stats.argtypes = [ctypes.c_void_p, i64]
        lib.arena_stats.restype = i64
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        # shard ingest core
        vp, i32 = ctypes.c_void_p, ctypes.c_int32
        lib.shard_core_create.argtypes = [i32, i32]
        lib.shard_core_create.restype = vp
        lib.shard_core_destroy.argtypes = [vp]
        lib.shard_core_set_watermark.argtypes = [vp, i32, i64]
        lib.shard_core_ingest.argtypes = [vp, ctypes.c_char_p, i64, i64]
        lib.shard_core_ingest.restype = i64
        lib.shard_core_stat.argtypes = [vp, i32]
        lib.shard_core_stat.restype = i64
        lib.shard_core_drain_new.argtypes = [vp, ctypes.POINTER(i32), i32]
        lib.shard_core_drain_new.restype = i32
        lib.shard_core_create_part.argtypes = [vp, u8p, i32,
                                               ctypes.c_uint32, i32]
        lib.shard_core_create_part.restype = i32
        lib.shard_core_lookup.argtypes = [vp, u8p, i32]
        lib.shard_core_lookup.restype = i32
        lib.shard_core_bootstrap.argtypes = [vp, ctypes.c_char_p, i64]
        lib.shard_core_bootstrap.restype = i64
        lib.shard_core_seed_floors.argtypes = [vp, ctypes.POINTER(i32), i64p,
                                               i64]
        lib.part_floor.argtypes = [vp, i32]
        lib.part_floor.restype = i64
        lib.shard_core_floors.argtypes = [vp, i64p, i64]
        lib.shard_core_export_size.argtypes = [vp]
        lib.shard_core_export_size.restype = i64
        lib.shard_core_chunk_bytes.argtypes = [vp]
        lib.shard_core_chunk_bytes.restype = i64
        lib.shard_core_export.argtypes = [vp, u8p, i64p,
                                          ctypes.POINTER(i32)]
        lib.shard_core_key_len.argtypes = [vp, i32]
        lib.shard_core_key_len.restype = i32
        lib.shard_core_key_copy.argtypes = [vp, i32, u8p]
        lib.shard_core_part_hash.argtypes = [vp, i32]
        lib.shard_core_part_hash.restype = ctypes.c_uint32
        lib.part_append.argtypes = [vp, i32, i64, f64p, i32]
        lib.part_append.restype = i64
        for fn in ("part_latest_ts", "part_first_ts", "part_earliest_ts",
                   "part_num_samples", "part_version", "part_flushed_id",
                   "part_chunk_bytes"):
            getattr(lib, fn).argtypes = [vp, i32]
            getattr(lib, fn).restype = i64
        for fn in ("part_buf_count", "part_ncols", "part_num_sealed"):
            getattr(lib, fn).argtypes = [vp, i32]
            getattr(lib, fn).restype = i32
        lib.part_buf_copy.argtypes = [vp, i32, i32, i64p, f64p]
        lib.part_buf_copy.restype = i32
        lib.part_seal_buffer.argtypes = [vp, i32]
        lib.part_seal_buffer.restype = i32
        lib.part_sealed_meta.argtypes = [vp, i32, i32, i64p]
        lib.part_sealed_veclen.argtypes = [vp, i32, i32, i32]
        lib.part_sealed_veclen.restype = i64
        lib.part_sealed_veccopy.argtypes = [vp, i32, i32, i32, u8p]
        lib.part_mark_flushed.argtypes = [vp, i32, i64]
        lib.part_evict_flushed.argtypes = [vp, i32]
        lib.part_evict_flushed.restype = i32
        lib.part_seed_floor.argtypes = [vp, i32, i64]
        lib.part_free.argtypes = [vp, i32]
        _lib = lib
        HAVE_NATIVE = True
        return lib


def get_lib():
    return _load()


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def murmur3_32_native(data: bytes, seed: int = 0) -> int | None:
    lib = _load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
        else (ctypes.c_uint8 * 1)()
    return int(lib.murmur3_32(buf, len(data), seed))


def nibble_pack_native(values: np.ndarray) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(vals)
    out = np.empty(2 + 10 * max(n, 8), np.uint8)
    written = lib.nibble_pack(_as_ptr(vals, ctypes.c_uint64), n,
                              _as_ptr(out, ctypes.c_uint8))
    return out[:written].tobytes()


def nibble_unpack_native(data: bytes, count: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, np.uint64)
    consumed = lib.nibble_unpack(_as_ptr(buf, ctypes.c_uint8), len(buf),
                                 _as_ptr(out, ctypes.c_uint64), count)
    if consumed < 0:
        raise ValueError("truncated NibblePack stream")
    return out


def xor_encode_native(values: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.float64)
    out = np.empty(len(v), np.uint64)
    lib.xor_encode_f64(_as_ptr(v, ctypes.c_double),
                       _as_ptr(out, ctypes.c_uint64), len(v))
    return out


def xor_decode_native(xored: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(xored, dtype=np.uint64)
    out = np.empty(len(x), np.float64)
    lib.xor_decode_f64(_as_ptr(x, ctypes.c_uint64),
                       _as_ptr(out, ctypes.c_double), len(x))
    return out


class NativeArena:
    """Block arena handle (reference ``PageAlignedBlockManager``)."""

    def __init__(self, block_size: int = 1 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._arena = lib.arena_create(block_size)
        self.block_size = block_size

    def alloc_block(self, owner: int) -> ctypes.c_void_p:
        return ctypes.c_void_p(self._lib.arena_alloc_block(self._arena, owner))

    def block_alloc(self, block, nbytes: int) -> int:
        return self._lib.block_alloc(block, nbytes)

    def block_remaining(self, block) -> int:
        return self._lib.block_remaining(block)

    def write(self, block, offset: int, data: bytes) -> None:
        ptr = self._lib.block_data(block)
        ctypes.memmove(ctypes.addressof(ptr.contents) + offset, data,
                       len(data))

    def read(self, block, offset: int, n: int) -> bytes:
        ptr = self._lib.block_data(block)
        return ctypes.string_at(ctypes.addressof(ptr.contents) + offset, n)

    def reclaim_owner(self, owner: int) -> int:
        return self._lib.arena_reclaim_owner(self._arena, owner)

    @property
    def stats(self) -> dict:
        return {
            "allocated_blocks": self._lib.arena_stats(self._arena, 0),
            "reclaimed_blocks": self._lib.arena_stats(self._arena, 1),
            "bytes_in_use": self._lib.arena_stats(self._arena, 2),
        }

    def close(self):
        if self._arena:
            self._lib.arena_destroy(self._arena)
            self._arena = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
