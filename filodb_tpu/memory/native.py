"""ctypes bindings to the C++ native runtime (``native/filodb_native.cpp``).

Builds the shared library on demand (cached by source mtime) and exposes:
- fast NibblePack pack/unpack, zigzag, XOR-double prep — byte-identical to
  the numpy reference implementations; used by the ingest/flush hot path.
- the block arena (reference ``BlockManager`` semantics).

Falls back gracefully (``HAVE_NATIVE = False``) when no compiler is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libfilodb_native.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "filodb_native.cpp")

_lib = None
_lock = threading.Lock()
HAVE_NATIVE = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception as e:  # pragma: no cover - toolchain missing
        log.warning("native build failed, using numpy codecs: %s", e)
        return False


def _load():
    global _lib, HAVE_NATIVE
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO_PATH)
                or os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:  # pragma: no cover
            log.warning("native load failed: %s", e)
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        i64 = ctypes.c_int64
        lib.nibble_pack.argtypes = [u64p, i64, u8p]
        lib.nibble_pack.restype = i64
        lib.nibble_unpack.argtypes = [u8p, i64, u64p, i64]
        lib.nibble_unpack.restype = i64
        lib.murmur3_32.argtypes = [u8p, i64, ctypes.c_uint32]
        lib.murmur3_32.restype = ctypes.c_uint32
        lib.zigzag_encode_i64.argtypes = [i64p, u64p, i64]
        lib.zigzag_decode_u64.argtypes = [u64p, i64p, i64]
        lib.xor_encode_f64.argtypes = [f64p, u64p, i64]
        lib.xor_decode_f64.argtypes = [u64p, f64p, i64]
        lib.delta_delta_residuals.argtypes = [i64p, i64, i64, i64, i64p]
        lib.delta_delta_residuals.restype = ctypes.c_int
        lib.delta_delta_reconstruct.argtypes = [i64p, i64, i64, i64, i64p]
        lib.arena_create.argtypes = [i64]
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_alloc_block.argtypes = [ctypes.c_void_p, i64]
        lib.arena_alloc_block.restype = ctypes.c_void_p
        lib.block_alloc.argtypes = [ctypes.c_void_p, i64]
        lib.block_alloc.restype = i64
        lib.block_data.argtypes = [ctypes.c_void_p]
        lib.block_data.restype = u8p
        lib.block_remaining.argtypes = [ctypes.c_void_p]
        lib.block_remaining.restype = i64
        lib.arena_reclaim_owner.argtypes = [ctypes.c_void_p, i64]
        lib.arena_reclaim_owner.restype = i64
        lib.arena_stats.argtypes = [ctypes.c_void_p, i64]
        lib.arena_stats.restype = i64
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        # shard ingest core
        vp, i32 = ctypes.c_void_p, ctypes.c_int32
        lib.shard_core_create.argtypes = [i32, i32]
        lib.shard_core_create.restype = vp
        lib.shard_core_destroy.argtypes = [vp]
        lib.shard_core_set_watermark.argtypes = [vp, i32, i64]
        lib.shard_core_ingest.argtypes = [vp, ctypes.c_char_p, i64, i64]
        lib.shard_core_ingest.restype = i64
        lib.shard_core_stat.argtypes = [vp, i32]
        lib.shard_core_stat.restype = i64
        lib.shard_core_drain_new.argtypes = [vp, ctypes.POINTER(i32), i32]
        lib.shard_core_drain_new.restype = i32
        lib.shard_core_create_part.argtypes = [vp, u8p, i32,
                                               ctypes.c_uint32, i32]
        lib.shard_core_create_part.restype = i32
        lib.shard_core_lookup.argtypes = [vp, u8p, i32]
        lib.shard_core_lookup.restype = i32
        lib.shard_core_bootstrap.argtypes = [vp, ctypes.c_char_p, i64]
        lib.shard_core_bootstrap.restype = i64
        lib.shard_core_seed_floors.argtypes = [vp, ctypes.POINTER(i32), i64p,
                                               i64]
        lib.part_floor.argtypes = [vp, i32]
        lib.part_floor.restype = i64
        lib.shard_core_floors.argtypes = [vp, i64p, i64]
        lib.shard_core_export_size.argtypes = [vp]
        lib.shard_core_export_size.restype = i64
        lib.shard_core_chunk_bytes.argtypes = [vp]
        lib.shard_core_chunk_bytes.restype = i64
        lib.shard_core_export.argtypes = [vp, u8p, i64p,
                                          ctypes.POINTER(i32)]
        lib.shard_core_key_len.argtypes = [vp, i32]
        lib.shard_core_key_len.restype = i32
        lib.shard_core_key_copy.argtypes = [vp, i32, u8p]
        lib.shard_core_part_hash.argtypes = [vp, i32]
        lib.shard_core_part_hash.restype = ctypes.c_uint32
        lib.part_append.argtypes = [vp, i32, i64, f64p, i32]
        lib.part_append.restype = i64
        lib.part_append_hist.argtypes = [vp, i32, i64, f64p, i32, f64p,
                                         i64p, i32, i32]
        lib.part_append_hist.restype = i64
        lib.part_hist_col.argtypes = [vp, i32]
        lib.part_hist_col.restype = i32
        lib.part_hist_nb.argtypes = [vp, i32]
        lib.part_hist_nb.restype = i32
        lib.part_hist_les.argtypes = [vp, i32, f64p]
        lib.part_buf_hist_copy.argtypes = [vp, i32, i32, i64p]
        lib.part_buf_hist_copy.restype = i32
        for fn in ("part_latest_ts", "part_first_ts", "part_earliest_ts",
                   "part_num_samples", "part_version", "part_flushed_id",
                   "part_chunk_bytes"):
            getattr(lib, fn).argtypes = [vp, i32]
            getattr(lib, fn).restype = i64
        for fn in ("part_buf_count", "part_ncols", "part_num_sealed"):
            getattr(lib, fn).argtypes = [vp, i32]
            getattr(lib, fn).restype = i32
        lib.part_buf_copy.argtypes = [vp, i32, i32, i64p, f64p]
        lib.part_buf_copy.restype = i32
        lib.part_seal_buffer.argtypes = [vp, i32]
        lib.part_seal_buffer.restype = i32
        lib.part_sealed_meta.argtypes = [vp, i32, i32, i64p]
        lib.part_sealed_veclen.argtypes = [vp, i32, i32, i32]
        lib.part_sealed_veclen.restype = i64
        lib.part_sealed_veccopy.argtypes = [vp, i32, i32, i32, u8p]
        lib.part_mark_flushed.argtypes = [vp, i32, i64]
        lib.part_evict_flushed.argtypes = [vp, i32]
        lib.part_evict_flushed.restype = i32
        lib.part_seed_floor.argtypes = [vp, i32, i64]
        lib.part_free.argtypes = [vp, i32]
        # batched buffer window fold (sidecar lane); absent on .so builds
        # older than the sidecar PR — callers must hasattr-gate
        if hasattr(lib, "shard_buf_fold"):
            lib.shard_buf_fold.argtypes = [vp, ctypes.POINTER(i32), i32,
                                           i64p, i64p, i32, i32, f64p,
                                           ctypes.POINTER(i32)]
            lib.shard_buf_fold.restype = i32
        # TagIndex (native part-key inverted index hot paths)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        cp = ctypes.c_char_p
        lib.tagindex_create.restype = vp
        lib.tagindex_destroy.argtypes = [vp]
        lib.tagindex_add.argtypes = [vp, i32, u8p, i32]
        lib.tagindex_add.restype = i32
        lib.tagindex_purge_pid.argtypes = [vp, i32]
        lib.tagindex_add_batch.argtypes = [vp, ctypes.POINTER(i32), i64,
                                           u8p, i64p]
        lib.tagindex_add_batch.restype = i32
        lib.tagindex_equals.argtypes = [vp, cp, i64, cp, i64, i32p, i64]
        lib.tagindex_equals.restype = i64
        # raw-address args: the equals fast path passes cached integer
        # pointers to skip per-call ctypes marshalling
        lib.tagindex_query_equals.argtypes = [vp, ctypes.c_void_p, i32,
                                              ctypes.c_void_p,
                                              ctypes.c_void_p,
                                              i64, i64, i64,
                                              ctypes.c_void_p, i64]
        lib.tagindex_query_equals.restype = i64
        lib.tagindex_query_equals_allow.argtypes = [
            vp, ctypes.c_void_p, i32, ctypes.c_void_p, i64,
            ctypes.c_void_p, ctypes.c_void_p, i64, i64, i64,
            ctypes.c_void_p, i64]
        lib.tagindex_query_equals_allow.restype = i64
        lib.tagindex_intersect_equals.argtypes = [vp, u8p, i32, i32p, i64]
        lib.tagindex_intersect_equals.restype = i64
        lib.tagindex_label_all.argtypes = [vp, cp, i64, i32p, i64]
        lib.tagindex_label_all.restype = i64
        lib.tagindex_values_size.argtypes = [vp, cp, i64]
        lib.tagindex_values_size.restype = i64
        lib.tagindex_values.argtypes = [vp, cp, i64, u8p]
        lib.tagindex_union_values.argtypes = [vp, cp, i64, i32p, i64, i32p,
                                              i64]
        lib.tagindex_union_values.restype = i64
        lib.tagindex_num_labels.argtypes = [vp]
        lib.tagindex_num_labels.restype = i64
        lib.tagindex_labels_size.argtypes = [vp]
        lib.tagindex_labels_size.restype = i64
        lib.tagindex_labels.argtypes = [vp, u8p]
        lib.tagindex_export_sizes.argtypes = [vp, cp, i64, i32p, i64, i64p]
        lib.tagindex_export_sizes.restype = i64
        lib.tagindex_export_label.argtypes = [vp, u32p, u8p, i64p, i32p]
        lib.tagindex_load_label.argtypes = [vp, cp, i64, u32p, i64, u8p, i64,
                                            i64p, i32p, i64]
        _lib = lib
        HAVE_NATIVE = True
        return lib


def get_lib():
    return _load()


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def murmur3_32_native(data: bytes, seed: int = 0) -> int | None:
    lib = _load()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
        else (ctypes.c_uint8 * 1)()
    return int(lib.murmur3_32(buf, len(data), seed))


def nibble_pack_native(values: np.ndarray) -> bytes | None:
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(vals)
    out = np.empty(2 + 10 * max(n, 8), np.uint8)
    written = lib.nibble_pack(_as_ptr(vals, ctypes.c_uint64), n,
                              _as_ptr(out, ctypes.c_uint8))
    return out[:written].tobytes()


def nibble_unpack_native(data: bytes, count: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, np.uint64)
    consumed = lib.nibble_unpack(_as_ptr(buf, ctypes.c_uint8), len(buf),
                                 _as_ptr(out, ctypes.c_uint64), count)
    if consumed < 0:
        raise ValueError("truncated NibblePack stream")
    return out


def xor_encode_native(values: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.float64)
    out = np.empty(len(v), np.uint64)
    lib.xor_encode_f64(_as_ptr(v, ctypes.c_double),
                       _as_ptr(out, ctypes.c_uint64), len(v))
    return out


def xor_decode_native(xored: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    x = np.ascontiguousarray(xored, dtype=np.uint64)
    out = np.empty(len(x), np.float64)
    lib.xor_decode_f64(_as_ptr(x, ctypes.c_uint64),
                       _as_ptr(out, ctypes.c_double), len(x))
    return out


class NativeArena:
    """Block arena handle (reference ``PageAlignedBlockManager``)."""

    def __init__(self, block_size: int = 1 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._arena = lib.arena_create(block_size)
        self.block_size = block_size

    def alloc_block(self, owner: int) -> ctypes.c_void_p:
        return ctypes.c_void_p(self._lib.arena_alloc_block(self._arena, owner))

    def block_alloc(self, block, nbytes: int) -> int:
        return self._lib.block_alloc(block, nbytes)

    def block_remaining(self, block) -> int:
        return self._lib.block_remaining(block)

    def write(self, block, offset: int, data: bytes) -> None:
        ptr = self._lib.block_data(block)
        ctypes.memmove(ctypes.addressof(ptr.contents) + offset, data,
                       len(data))

    def read(self, block, offset: int, n: int) -> bytes:
        ptr = self._lib.block_data(block)
        return ctypes.string_at(ctypes.addressof(ptr.contents) + offset, n)

    def reclaim_owner(self, owner: int) -> int:
        return self._lib.arena_reclaim_owner(self._arena, owner)

    @property
    def stats(self) -> dict:
        return {
            "allocated_blocks": self._lib.arena_stats(self._arena, 0),
            "reclaimed_blocks": self._lib.arena_stats(self._arena, 1),
            "bytes_in_use": self._lib.arena_stats(self._arena, 2),
        }

    def close(self):
        if self._arena:
            self._lib.arena_destroy(self._arena)
            self._arena = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TagIndexNative:
    """Handle on a C++ TagIndex — the postings store behind PartKeyIndex
    (reference ``PartKeyLuceneIndex`` postings + query hot paths,
    ``PartKeyLuceneIndex.scala:455,494``). Times and tombstones stay on the
    Python side; this holds label→value→pid postings only."""

    __slots__ = ("_lib", "_h", "_buf", "_buf_addr", "_lock", "_pend",
                 "generation")

    _FLUSH_AT = 4096

    def __init__(self):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.tagindex_create()
        self._buf = np.empty(4096, np.int32)
        self._buf_addr = self._buf.ctypes.data
        # ctypes releases the GIL and the C++ maps are not concurrent-safe
        # (ingest thread writes while query threads read) — serialize calls,
        # the native analog of ChunkMap's read/write latch
        self._lock = threading.Lock()
        # buffered adds, flushed in one native batch call on any read (the
        # Lucene analog: IndexWriter RAM buffer + NRT refresh — here with
        # strict read-your-writes, PartKeyLuceneIndex.startFlushThread:167).
        # One list of (pid, blob) tuples: a single GIL-atomic append per add
        # lets the single-writer ingest thread skip the lock entirely.
        self._pend: list[tuple[int, bytes]] = []
        # bumps on every postings mutation; callers key value-scan caches
        self.generation = 0

    def close(self):
        if self._h:
            self._lib.tagindex_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def add(self, pid: int, key_blob: bytes) -> None:
        self._pend.append((pid, key_blob))
        self.generation += 1
        if len(self._pend) >= self._FLUSH_AT:
            with self._lock:
                self._flush()

    def _flush(self) -> None:
        """Push buffered adds into the native index (caller holds _lock)."""
        if not self._pend:
            return
        pend, self._pend = self._pend, []  # atomic swap vs concurrent adds
        pids = np.fromiter((p for p, _ in pend), np.int32, len(pend))
        blob = b"".join(b for _, b in pend)
        offs = np.zeros(len(pend) + 1, np.int64)
        np.cumsum([len(b) for _, b in pend], out=offs[1:])
        rc = self._lib.tagindex_add_batch(
            self._h, _as_ptr(pids, ctypes.c_int32), len(pids),
            ctypes.cast(blob, ctypes.POINTER(ctypes.c_uint8)),
            _as_ptr(offs, ctypes.c_int64))
        if rc != 0:
            raise ValueError("malformed part-key blob in batch")

    def purge_pid(self, pid: int) -> None:
        with self._lock:
            self._flush()
            self.generation += 1
            self._lib.tagindex_purge_pid(self._h, pid)

    def _out_locked(self, fn, *args) -> np.ndarray:
        n = fn(self._h, *args, _as_ptr(self._buf, ctypes.c_int32),
               len(self._buf))
        if n < 0:
            self._buf = np.empty(int(-n) + 64, np.int32)
            self._buf_addr = self._buf.ctypes.data
            n = fn(self._h, *args, _as_ptr(self._buf, ctypes.c_int32),
                   len(self._buf))
        return self._buf[: int(n)].copy()

    def equals(self, label: str, value: str) -> np.ndarray:
        with self._lock:
            self._flush()
            lb, vb = label.encode(), value.encode()
            return self._out_locked(self._lib.tagindex_equals,
                                    lb, len(lb), vb, len(vb))

    @staticmethod
    def encode_pairs(pairs: list[tuple[str, str]]) -> bytes:
        import struct
        buf = bytearray()
        for k, v in pairs:
            kb, vb = k.encode(), v.encode()
            buf += struct.pack("<H", len(kb)) + kb
            buf += struct.pack("<H", len(vb)) + vb
        return bytes(buf)

    @staticmethod
    def addr_of(buf) -> int:
        """Stable raw address of a bytes object / numpy array (caller must
        keep the object alive for as long as the address is used)."""
        if isinstance(buf, bytes):
            return ctypes.cast(buf, ctypes.c_void_p).value or 0
        return buf.ctypes.data

    def intersect_equals(self, pairs: list[tuple[str, str]]) -> np.ndarray:
        with self._lock:
            self._flush()
            bb = self.encode_pairs(pairs)
            return self._out_locked(
                lambda h, o, c: self._lib.tagindex_intersect_equals(
                    h, ctypes.cast(bb, ctypes.POINTER(ctypes.c_uint8)),
                    len(pairs), o, c))

    def query_equals(self, pairs_addr: int, npairs: int,
                     starts_addr: int, ends_addr: int, bounds_len: int,
                     start_t: int, end_t: int) -> list[int]:
        """Full equals fast path: postings intersection + time predicate in
        one native call; returns live pids as a list. Callers pass raw
        addresses (``addr_of``) and must keep the backing objects alive."""
        with self._lock:
            if self._pend:
                self._flush()
            n = self._lib.tagindex_query_equals(
                self._h, pairs_addr, npairs, starts_addr, ends_addr,
                bounds_len, start_t, end_t, self._buf_addr, len(self._buf))
            if n < 0:
                self._buf = np.empty(int(-n) + 64, np.int32)
                self._buf_addr = self._buf.ctypes.data
                n = self._lib.tagindex_query_equals(
                    self._h, pairs_addr, npairs, starts_addr, ends_addr,
                    bounds_len, start_t, end_t, self._buf_addr,
                    len(self._buf))
            return self._buf[: int(n)].tolist()

    def query_equals_allow(self, pairs_addr: int, npairs: int,
                           allow: np.ndarray, starts_addr: int,
                           ends_addr: int, bounds_len: int,
                           start_t: int, end_t: int) -> list[int]:
        """Equals postings ∩ sorted allow-list (cached regex postings) ∩
        time predicate, one native call — the regex-filter fast path."""
        allow = np.ascontiguousarray(allow, np.int32)
        aptr = allow.ctypes.data
        with self._lock:
            if self._pend:
                self._flush()
            n = self._lib.tagindex_query_equals_allow(
                self._h, pairs_addr, npairs, aptr, len(allow), starts_addr,
                ends_addr, bounds_len, start_t, end_t, self._buf_addr,
                len(self._buf))
            if n < 0:
                self._buf = np.empty(int(-n) + 64, np.int32)
                self._buf_addr = self._buf.ctypes.data
                n = self._lib.tagindex_query_equals_allow(
                    self._h, pairs_addr, npairs, aptr, len(allow),
                    starts_addr, ends_addr, bounds_len, start_t, end_t,
                    self._buf_addr, len(self._buf))
            return self._buf[: int(n)].tolist()

    def label_all(self, label: str) -> np.ndarray:
        with self._lock:
            self._flush()
            lb = label.encode()
            return self._out_locked(self._lib.tagindex_label_all, lb, len(lb))

    def values(self, label: str) -> list[str]:
        with self._lock:
            self._flush()
            lb = label.encode()
            sz = self._lib.tagindex_values_size(self._h, lb, len(lb))
            if sz == 0:
                return []
            raw = np.empty(int(sz), np.uint8)
            self._lib.tagindex_values(self._h, lb, len(lb),
                                      _as_ptr(raw, ctypes.c_uint8))
            out = []
            data = raw.tobytes()
            off = 0
            while off < len(data):
                n = int.from_bytes(data[off : off + 4], "little")
                off += 4
                out.append(data[off : off + n].decode())
                off += n
            return out

    def union_values(self, label: str, vids: np.ndarray) -> np.ndarray:
        with self._lock:
            self._flush()
            lb = label.encode()
            vids = np.ascontiguousarray(vids, np.int32)
            return self._out_locked(
                lambda h, o, c: self._lib.tagindex_union_values(
                    h, lb, len(lb), _as_ptr(vids, ctypes.c_int32), len(vids),
                    o, c))

    def labels(self) -> list[str]:
        with self._lock:
            self._flush()
            sz = self._lib.tagindex_labels_size(self._h)
            if sz == 0:
                return []
            raw = np.empty(int(sz), np.uint8)
            self._lib.tagindex_labels(self._h, _as_ptr(raw, ctypes.c_uint8))
            out = []
            data = raw.tobytes()
            off = 0
            while off < len(data):
                n = int.from_bytes(data[off : off + 4], "little")
                off += 4
                out.append(data[off : off + n].decode())
                off += n
            return out

    def export_label(self, label: str, deleted: np.ndarray):
        """(voff, vblob, poff, pids) snapshot arrays for one label, with
        ``deleted`` (sorted int32) pids dropped. Empty labels yield nv=0."""
        with self._lock:
            self._flush()
            lb = label.encode()
            deleted = np.ascontiguousarray(deleted, np.int32)
            sizes = np.empty(3, np.int64)
            self._lib.tagindex_export_sizes(
                self._h, lb, len(lb), _as_ptr(deleted, ctypes.c_int32),
                len(deleted), _as_ptr(sizes, ctypes.c_int64))
            nv, vlen, npids = (int(x) for x in sizes)
            voff = np.empty(nv + 1, np.uint32)
            vblob = np.empty(vlen, np.uint8)
            poff = np.empty(nv + 1, np.int64)
            pids = np.empty(npids, np.int32)
            self._lib.tagindex_export_label(
                self._h, _as_ptr(voff, ctypes.c_uint32),
                _as_ptr(vblob, ctypes.c_uint8), _as_ptr(poff, ctypes.c_int64),
                _as_ptr(pids, ctypes.c_int32))
            return voff, vblob.tobytes(), poff, pids

    def load_label(self, label: str, voff, vblob: bytes, poff, pids) -> None:
        with self._lock:
            lb = label.encode()
            voff = np.ascontiguousarray(voff, np.uint32)
            poff = np.ascontiguousarray(poff, np.int64)
            pids = np.ascontiguousarray(pids, np.int32)
            self._lib.tagindex_load_label(
                self._h, lb, len(lb), _as_ptr(voff, ctypes.c_uint32),
                len(voff) - 1,
                ctypes.cast(vblob, ctypes.POINTER(ctypes.c_uint8)), len(vblob),
                _as_ptr(poff, ctypes.c_int64), _as_ptr(pids, ctypes.c_int32),
                len(pids))
