"""NibblePack: nibble-granularity bit packing of u64 streams.

Technique parity with the reference's NibblePack
(``memory/src/main/scala/filodb.memory/format/NibblePack.scala:12``, spec in
``doc/compression.md:35-128``): values are packed in groups of 8; each group
stores a 1-byte nonzero bitmap, and — if any value is nonzero — a 1-byte
nibble descriptor (number of nibbles kept, number of trailing zero nibbles,
shared across the group) followed by the kept nibbles of each nonzero value,
packed little-endian.

This is our own wire format (we never exchange bytes with the JVM reference);
layout chosen to be simple to decode both in C++ and vectorized numpy.

Group layout:
  byte 0: bitmap, bit i set => value i != 0
  if bitmap != 0:
    byte 1: ((num_nibbles - 1) << 4) | trailing_zero_nibbles
    then ceil(popcount(bitmap) * num_nibbles / 2) bytes of nibble data:
      for each nonzero value (in index order), its ``num_nibbles`` nibbles
      (after right-shifting away ``trailing_zero_nibbles`` nibbles), written
      low-nibble-first into a little-endian byte stream.
"""

from __future__ import annotations

import numpy as np

_U64 = np.uint64


def zigzag_encode(values: np.ndarray) -> np.ndarray:
    """Map signed int64 -> uint64 with small magnitudes near zero."""
    v = values.astype(np.int64)
    return ((v << np.int64(1)) ^ (v >> np.int64(63))).astype(np.uint64)


def zigzag_decode(values: np.ndarray) -> np.ndarray:
    u = values.astype(np.uint64)
    return ((u >> _U64(1)).astype(np.int64)) ^ (-(u & _U64(1)).astype(np.int64))


def _nibble_width(x: int) -> int:
    """Number of nibbles needed to represent x (>=1 even for 0)."""
    if x == 0:
        return 1
    return (x.bit_length() + 3) // 4


def _trailing_zero_nibbles(x: int) -> int:
    if x == 0:
        return 16
    tz = 0
    while x & 0xF == 0:
        tz += 1
        x >>= 4
    return tz


def nibble_pack(values: np.ndarray) -> bytes:
    """Pack an array of uint64 into NibblePack bytes (native fast path when
    the C++ library is available, byte-identical output)."""
    from filodb_tpu.memory import native

    out = native.nibble_pack_native(values)
    if out is not None:
        return out
    return nibble_pack_py(values)


def nibble_pack_py(values: np.ndarray) -> bytes:
    """Pure-python reference implementation."""
    vals = np.ascontiguousarray(values, dtype=np.uint64)
    out = bytearray()
    n = len(vals)
    for g in range(0, n, 8):
        group = vals[g : g + 8]
        ints = [int(x) for x in group]
        # pad group to 8 with zeros (decoder trims via total count)
        while len(ints) < 8:
            ints.append(0)
        bitmap = 0
        for i, x in enumerate(ints):
            if x != 0:
                bitmap |= 1 << i
        out.append(bitmap)
        if bitmap == 0:
            continue
        nz = [x for x in ints if x != 0]
        tz = min(_trailing_zero_nibbles(x) for x in nz)
        lead_width = max(_nibble_width(x) for x in nz)
        num_nibbles = lead_width - tz
        out.append(((num_nibbles - 1) << 4) | tz)
        # pack nibbles of nonzero values consecutively, low-nibble first
        acc = 0
        acc_bits = 0
        for x in nz:
            x >>= 4 * tz
            acc |= (x & ((1 << (4 * num_nibbles)) - 1)) << acc_bits
            acc_bits += 4 * num_nibbles
            while acc_bits >= 8:
                out.append(acc & 0xFF)
                acc >>= 8
                acc_bits -= 8
        if acc_bits > 0:
            out.append(acc & 0xFF)
    return bytes(out)


def nibble_unpack(data: bytes, count: int) -> np.ndarray:
    """Unpack ``count`` uint64 values from NibblePack bytes."""
    from filodb_tpu.memory import native

    out = native.nibble_unpack_native(data, count)
    if out is not None:
        return out
    return nibble_unpack_py(data, count)


def nibble_unpack_py(data: bytes, count: int) -> np.ndarray:
    """Pure-python reference implementation."""
    out = np.zeros(count, dtype=np.uint64)
    pos = 0
    idx = 0
    mv = data
    while idx < count:
        bitmap = mv[pos]
        pos += 1
        if bitmap == 0:
            idx += 8
            continue
        desc = mv[pos]
        pos += 1
        num_nibbles = (desc >> 4) + 1
        tz = desc & 0xF
        nnz = bin(bitmap).count("1")
        nbytes = (nnz * num_nibbles + 1) // 2
        chunk = int.from_bytes(mv[pos : pos + nbytes], "little")
        pos += nbytes
        mask = (1 << (4 * num_nibbles)) - 1
        shift = 0
        for i in range(8):
            if bitmap & (1 << i):
                val = ((chunk >> shift) & mask) << (4 * tz)
                if idx + i < count:
                    out[idx + i] = val
                shift += 4 * num_nibbles
        idx += 8
    return out
