"""Memory/format layer: columnar codecs and chunk page format.

Counterpart of the reference's ``memory/`` module (off-heap BinaryVectors,
NibblePack, delta-delta, XOR-double and 2D-delta histogram compression —
``memory/src/main/scala/filodb.memory/format/``). Here the codecs are
implemented twice with byte-identical output:

- ``nibblepack.py`` / ``codecs.py`` — numpy reference implementation,
  always available, used for correctness tests.
- ``native/codecs.cpp`` via ``native.py`` (ctypes) — the fast host path used
  by the ingest runtime, mirroring the reference's off-heap Scala+Unsafe tier.
"""

from filodb_tpu.memory.nibblepack import nibble_pack, nibble_unpack  # noqa: F401
