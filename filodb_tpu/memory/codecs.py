"""Columnar vector codecs over NibblePack.

Technique parity with the reference's BinaryVector encoders
(``memory/src/main/scala/filodb.memory/format/vectors/``):

- ``DeltaDeltaCodec``   — timestamps/longs as a sloped line predictor + per-sample
  zigzag residuals (reference ``DeltaDeltaVector.scala:28``); an all-zero residual
  stream collapses to a const-slope representation
  (``DeltaDeltaConstDataReader:237``).
- ``XorDoubleCodec``    — doubles XORed against the previous value, bit patterns
  NibblePacked (reference ``DoubleVector.scala`` + ``doc/compression.md:25-98``).
- ``Hist2DDeltaCodec``  — histogram bucket rows stored as delta-across-buckets then
  delta-across-time, NibblePacked row-major (reference
  ``HistogramVector.scala:189``, ``Appendable2DDeltaHistVector:378``).
- ``DictStringCodec``   — dictionary-encoded strings (reference
  ``DictUTF8Vector.scala``).

Wire format per vector: a small struct header (magic codec id, count, codec
params) followed by NibblePack payload. Headers are our own layout.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from filodb_tpu.memory.nibblepack import (
    nibble_pack,
    nibble_unpack,
    zigzag_decode,
    zigzag_encode,
)

# codec ids (first byte of every encoded vector)
CODEC_DELTA_DELTA = 1
CODEC_DELTA_DELTA_CONST = 2
CODEC_XOR_DOUBLE = 3
CODEC_HIST_2D_DELTA = 4
CODEC_DICT_STRING = 5          # legacy: NUL-separated dictionary (decode only)
CODEC_RAW_DOUBLE = 6
CODEC_DICT_STRING_LP = 7       # u32-length-prefixed dictionary entries


def encode_delta_delta(values: np.ndarray) -> bytes:
    """Encode int64s with a sloped-line predictor: pred[i] = base + slope*i."""
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    if n == 0:
        return struct.pack("<BIqq", CODEC_DELTA_DELTA_CONST, 0, 0, 0)
    base = int(v[0])
    slope = int((int(v[-1]) - base) // (n - 1)) if n > 1 else 0
    pred = base + slope * np.arange(n, dtype=np.int64)
    resid = v - pred
    if not resid.any():
        return struct.pack("<BIqq", CODEC_DELTA_DELTA_CONST, n, base, slope)
    packed = nibble_pack(zigzag_encode(resid))
    return struct.pack("<BIqq", CODEC_DELTA_DELTA, n, base, slope) + packed


def decode_delta_delta(data: bytes) -> np.ndarray:
    codec, n, base, slope = struct.unpack_from("<BIqq", data, 0)
    pred = base + slope * np.arange(n, dtype=np.int64)
    if codec == CODEC_DELTA_DELTA_CONST:
        return pred
    assert codec == CODEC_DELTA_DELTA, f"bad codec {codec}"
    resid = zigzag_decode(nibble_unpack(data[struct.calcsize("<BIqq") :], n))
    return pred + resid


def encode_xor_double(values: np.ndarray) -> bytes:
    """Encode float64s: XOR against previous value's bit pattern, NibblePack."""
    v = np.ascontiguousarray(values, dtype=np.float64)
    n = len(v)
    bits = v.view(np.uint64)
    prev = np.concatenate([[np.uint64(0)], bits[:-1]])
    xored = bits ^ prev
    packed = nibble_pack(xored)
    return struct.pack("<BI", CODEC_XOR_DOUBLE, n) + packed


def decode_xor_double(data: bytes) -> np.ndarray:
    codec, n = struct.unpack_from("<BI", data, 0)
    assert codec == CODEC_XOR_DOUBLE, f"bad codec {codec}"
    xored = nibble_unpack(data[struct.calcsize("<BI") :], n)
    bits = np.bitwise_xor.accumulate(xored)
    return bits.view(np.float64)


@dataclass(frozen=True)
class HistogramColumn:
    """Decoded histogram vector: bucket upper bounds + cumulative count rows."""

    les: np.ndarray  # (nb,) float64 bucket upper bounds ("le" values)
    rows: np.ndarray  # (n, nb) int64 cumulative counts per row


def encode_hist_2d_delta(rows: np.ndarray, les: np.ndarray | None = None) -> bytes:
    """Encode histogram rows [n, num_buckets] (cumulative bucket counts, int64)
    plus the shared bucket-bound scheme.

    2D delta: within a row take deltas across buckets (cumulative -> per-bucket),
    then across time subtract the previous row's bucket deltas. Residuals can be
    negative only for counter resets; zigzag handles that.
    """
    r = np.ascontiguousarray(rows, dtype=np.int64)
    n, nb = r.shape if r.ndim == 2 else (0, 0)
    if les is None:
        les = np.zeros(nb, dtype=np.float64)
    les = np.ascontiguousarray(les, dtype=np.float64)
    head = struct.pack("<BII", CODEC_HIST_2D_DELTA, n, nb) + les.tobytes()
    if n == 0:
        return head
    bucket_deltas = np.diff(r, axis=1, prepend=0)
    time_deltas = np.diff(bucket_deltas, axis=0, prepend=np.zeros((1, nb), np.int64))
    return head + nibble_pack(zigzag_encode(time_deltas.ravel()))


def decode_hist_2d_delta(data: bytes) -> HistogramColumn:
    codec, n, nb = struct.unpack_from("<BII", data, 0)
    assert codec == CODEC_HIST_2D_DELTA, f"bad codec {codec}"
    off = struct.calcsize("<BII")
    les = np.frombuffer(data, dtype=np.float64, count=nb, offset=off).copy()
    off += nb * 8
    if n == 0:
        return HistogramColumn(les, np.zeros((0, nb), dtype=np.int64))
    flat = zigzag_decode(nibble_unpack(data[off:], n * nb))
    time_deltas = flat.reshape(n, nb)
    bucket_deltas = np.cumsum(time_deltas, axis=0)
    return HistogramColumn(les, np.cumsum(bucket_deltas, axis=1))


def encode_dict_string(values: list[str]) -> bytes:
    """Dictionary-encode a string column: unique blob table + int codes.
    Dictionary entries are u32-length-prefixed (not NUL-separated) so values
    containing ``\\x00`` round-trip."""
    uniq: dict[str, int] = {}
    codes = np.empty(len(values), dtype=np.int64)
    for i, s in enumerate(values):
        codes[i] = uniq.setdefault(s, len(uniq))
    parts = []
    for s in uniq:
        b = s.encode("utf-8")
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    blob = b"".join(parts)
    packed_codes = nibble_pack(codes.astype(np.uint64))
    return (
        struct.pack("<BIII", CODEC_DICT_STRING_LP, len(values), len(uniq),
                    len(blob))
        + blob
        + packed_codes
    )


def decode_dict_string(data: bytes) -> list[str]:
    codec, n, nuniq, bloblen = struct.unpack_from("<BIII", data, 0)
    assert codec in (CODEC_DICT_STRING, CODEC_DICT_STRING_LP), \
        f"bad codec {codec}"
    off = struct.calcsize("<BIII")
    end = off + bloblen
    table: list[str] = []
    if codec == CODEC_DICT_STRING:
        # legacy on-disk chunks: NUL-separated dictionary (cannot hold NULs)
        blob = data[off:end]
        table = [s.decode("utf-8") for s in blob.split(b"\x00")] if nuniq \
            else []
    else:
        while off < end:
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            table.append(data[off : off + ln].decode("utf-8"))
            off += ln
    assert len(table) == nuniq, f"dict table {len(table)} != {nuniq}"
    codes = nibble_unpack(data[end:], n)
    return [table[int(c)] for c in codes]


def encode_raw_double(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.float64)
    return struct.pack("<BI", CODEC_RAW_DOUBLE, len(v)) + v.tobytes()


def decode_raw_double(data: bytes) -> np.ndarray:
    codec, n = struct.unpack_from("<BI", data, 0)
    assert codec == CODEC_RAW_DOUBLE, f"bad codec {codec}"
    off = struct.calcsize("<BI")
    return np.frombuffer(data, dtype=np.float64, count=n, offset=off).copy()


@dataclass(frozen=True)
class DecodedVector:
    """A decoded column vector (host-side)."""

    values: np.ndarray  # int64 / float64 / (n, nb) int64 for histograms

    def __len__(self) -> int:
        return len(self.values)


def decode_any(data: bytes) -> np.ndarray | list[str]:
    """Dispatch on the leading codec id (reference: WireFormat word dispatch,
    ``BinaryVector.scala:526``)."""
    codec = data[0]
    if codec in (CODEC_DELTA_DELTA, CODEC_DELTA_DELTA_CONST):
        return decode_delta_delta(data)
    if codec == CODEC_XOR_DOUBLE:
        return decode_xor_double(data)
    if codec == CODEC_HIST_2D_DELTA:
        return decode_hist_2d_delta(data)
    if codec in (CODEC_DICT_STRING, CODEC_DICT_STRING_LP):
        return decode_dict_string(data)
    if codec == CODEC_RAW_DOUBLE:
        return decode_raw_double(data)
    raise ValueError(f"unknown codec id {codec}")
