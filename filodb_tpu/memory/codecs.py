"""Columnar vector codecs over NibblePack.

Technique parity with the reference's BinaryVector encoders
(``memory/src/main/scala/filodb.memory/format/vectors/``):

- ``DeltaDeltaCodec``   — timestamps/longs as a sloped line predictor + per-sample
  zigzag residuals (reference ``DeltaDeltaVector.scala:28``); an all-zero residual
  stream collapses to a const-slope representation
  (``DeltaDeltaConstDataReader:237``).
- ``XorDoubleCodec``    — doubles XORed against the previous value, bit patterns
  NibblePacked (reference ``DoubleVector.scala`` + ``doc/compression.md:25-98``).
- ``Hist2DDeltaCodec``  — histogram bucket rows stored as delta-across-buckets then
  delta-across-time, NibblePacked row-major (reference
  ``HistogramVector.scala:189``, ``Appendable2DDeltaHistVector:378``).
- ``DictStringCodec``   — dictionary-encoded strings (reference
  ``DictUTF8Vector.scala``).

Wire format per vector: a small struct header (magic codec id, count, codec
params) followed by NibblePack payload. Headers are our own layout.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from filodb_tpu.memory.nibblepack import (
    nibble_pack,
    nibble_unpack,
    zigzag_decode,
    zigzag_encode,
)

# codec ids (first byte of every encoded vector)
CODEC_DELTA_DELTA = 1
CODEC_DELTA_DELTA_CONST = 2
CODEC_XOR_DOUBLE = 3
CODEC_HIST_2D_DELTA = 4
CODEC_DICT_STRING = 5          # legacy: NUL-separated dictionary (decode only)
CODEC_RAW_DOUBLE = 6
CODEC_DICT_STRING_LP = 7       # u32-length-prefixed dictionary entries
CODEC_CONST_DOUBLE = 8         # ConstVector analog for doubles
CODEC_PACKED_INT = 9           # frame-of-reference bit-packed ints/longs
CODEC_UTF8 = 10                # raw UTF8 vector: i32 offsets + blob
CODEC_MAP = 11                 # map<string,string> column (dict over blobs)


def encode_delta_delta(values: np.ndarray) -> bytes:
    """Encode int64s with a sloped-line predictor: pred[i] = base + slope*i."""
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    if n == 0:
        return struct.pack("<BIqq", CODEC_DELTA_DELTA_CONST, 0, 0, 0)
    base = int(v[0])
    slope = int((int(v[-1]) - base) // (n - 1)) if n > 1 else 0
    pred = base + slope * np.arange(n, dtype=np.int64)
    resid = v - pred
    if not resid.any():
        return struct.pack("<BIqq", CODEC_DELTA_DELTA_CONST, n, base, slope)
    packed = nibble_pack(zigzag_encode(resid))
    return struct.pack("<BIqq", CODEC_DELTA_DELTA, n, base, slope) + packed


def decode_delta_delta(data: bytes) -> np.ndarray:
    codec, n, base, slope = struct.unpack_from("<BIqq", data, 0)
    pred = base + slope * np.arange(n, dtype=np.int64)
    if codec == CODEC_DELTA_DELTA_CONST:
        return pred
    assert codec == CODEC_DELTA_DELTA, f"bad codec {codec}"
    resid = zigzag_decode(nibble_unpack(data[struct.calcsize("<BIqq") :], n))
    return pred + resid


def encode_xor_double(values: np.ndarray) -> bytes:
    """Encode float64s: XOR against previous value's bit pattern, NibblePack."""
    v = np.ascontiguousarray(values, dtype=np.float64)
    n = len(v)
    bits = v.view(np.uint64)
    prev = np.concatenate([[np.uint64(0)], bits[:-1]])
    xored = bits ^ prev
    packed = nibble_pack(xored)
    return struct.pack("<BI", CODEC_XOR_DOUBLE, n) + packed


def decode_xor_double(data: bytes) -> np.ndarray:
    codec, n = struct.unpack_from("<BI", data, 0)
    assert codec == CODEC_XOR_DOUBLE, f"bad codec {codec}"
    xored = nibble_unpack(data[struct.calcsize("<BI") :], n)
    bits = np.bitwise_xor.accumulate(xored)
    return bits.view(np.float64)


@dataclass(frozen=True)
class HistogramColumn:
    """Decoded histogram vector: bucket upper bounds + cumulative count rows."""

    les: np.ndarray  # (nb,) float64 bucket upper bounds ("le" values)
    rows: np.ndarray  # (n, nb) int64 cumulative counts per row


def encode_hist_2d_delta(rows: np.ndarray, les: np.ndarray | None = None) -> bytes:
    """Encode histogram rows [n, num_buckets] (cumulative bucket counts, int64)
    plus the shared bucket-bound scheme.

    2D delta: within a row take deltas across buckets (cumulative -> per-bucket),
    then across time subtract the previous row's bucket deltas. Residuals can be
    negative only for counter resets; zigzag handles that.
    """
    r = np.ascontiguousarray(rows, dtype=np.int64)
    n, nb = r.shape if r.ndim == 2 else (0, 0)
    if les is None:
        les = np.zeros(nb, dtype=np.float64)
    les = np.ascontiguousarray(les, dtype=np.float64)
    head = struct.pack("<BII", CODEC_HIST_2D_DELTA, n, nb) + les.tobytes()
    if n == 0:
        return head
    bucket_deltas = np.diff(r, axis=1, prepend=0)
    time_deltas = np.diff(bucket_deltas, axis=0, prepend=np.zeros((1, nb), np.int64))
    return head + nibble_pack(zigzag_encode(time_deltas.ravel()))


def decode_hist_2d_delta(data: bytes) -> HistogramColumn:
    codec, n, nb = struct.unpack_from("<BII", data, 0)
    assert codec == CODEC_HIST_2D_DELTA, f"bad codec {codec}"
    off = struct.calcsize("<BII")
    les = np.frombuffer(data, dtype=np.float64, count=nb, offset=off).copy()
    off += nb * 8
    if n == 0:
        return HistogramColumn(les, np.zeros((0, nb), dtype=np.int64))
    flat = zigzag_decode(nibble_unpack(data[off:], n * nb))
    time_deltas = flat.reshape(n, nb)
    bucket_deltas = np.cumsum(time_deltas, axis=0)
    return HistogramColumn(les, np.cumsum(bucket_deltas, axis=1))


def encode_dict_string(values: list[str]) -> bytes:
    """Dictionary-encode a string column: unique blob table + int codes.
    Dictionary entries are u32-length-prefixed (not NUL-separated) so values
    containing ``\\x00`` round-trip."""
    uniq: dict[str, int] = {}
    codes = np.empty(len(values), dtype=np.int64)
    for i, s in enumerate(values):
        codes[i] = uniq.setdefault(s, len(uniq))
    parts = []
    for s in uniq:
        b = s.encode("utf-8")
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    blob = b"".join(parts)
    packed_codes = nibble_pack(codes.astype(np.uint64))
    return (
        struct.pack("<BIII", CODEC_DICT_STRING_LP, len(values), len(uniq),
                    len(blob))
        + blob
        + packed_codes
    )


def decode_dict_string(data: bytes) -> list[str]:
    codec, n, nuniq, bloblen = struct.unpack_from("<BIII", data, 0)
    assert codec in (CODEC_DICT_STRING, CODEC_DICT_STRING_LP), \
        f"bad codec {codec}"
    off = struct.calcsize("<BIII")
    end = off + bloblen
    table: list[str] = []
    if codec == CODEC_DICT_STRING:
        # legacy on-disk chunks: NUL-separated dictionary (cannot hold NULs)
        blob = data[off:end]
        table = [s.decode("utf-8") for s in blob.split(b"\x00")] if nuniq \
            else []
    else:
        while off < end:
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            table.append(data[off : off + ln].decode("utf-8"))
            off += ln
    assert len(table) == nuniq, f"dict table {len(table)} != {nuniq}"
    codes = nibble_unpack(data[end:], n)
    return [table[int(c)] for c in codes]


def encode_const_double(value: float, n: int) -> bytes:
    """All-rows-equal double vector (reference ``ConstVector.scala``: repeats
    one stored value ``numRows`` times)."""
    return struct.pack("<BId", CODEC_CONST_DOUBLE, n, value)


def decode_const_double(data: bytes) -> np.ndarray:
    codec, n, value = struct.unpack_from("<BId", data, 0)
    assert codec == CODEC_CONST_DOUBLE, f"bad codec {codec}"
    return np.full(n, value, dtype=np.float64)


def encode_double(values: np.ndarray) -> bytes:
    """Encode a double column with automatic codec selection: const when all
    rows carry one value (bitwise, so NaN==NaN), XOR+NibblePack otherwise
    (reference ``DoubleVector.optimize`` → ConstVector / DeltaDeltaDouble)."""
    v = np.ascontiguousarray(values, dtype=np.float64)
    if len(v) and (v.view(np.uint64) == v.view(np.uint64)[0]).all():
        return encode_const_double(float(v[0]), len(v))
    return encode_xor_double(v)


# frame-of-reference bit widths tried in order (reference IntBinaryVector
# supports nbits 2/4/8/16/32; we add 1 and 64 at the extremes)
_PACK_WIDTHS = (1, 2, 4, 8, 16, 32, 64)


def encode_packed_int(values: np.ndarray) -> bytes:
    """Frame-of-reference bit-packed integer vector.

    Values are rebased against their minimum, then packed at the smallest
    bit width in {1,2,4,8,16,32,64} that holds ``max - min``; an all-equal
    vector collapses to width 0 (ConstVector analog). Counterpart of the
    reference's minimal-nbits int vectors (``IntBinaryVector.scala:56-120``,
    ``IntBinaryVector.optimize``) and ``LongBinaryVector``/``ConstVector``.
    """
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = len(v)
    if n == 0:
        return struct.pack("<BIqB", CODEC_PACKED_INT, 0, 0, 0)
    base = int(v.min())
    spread = int(v.max()) - base  # fits u64: int64 range spread
    if spread == 0:
        return struct.pack("<BIqB", CODEC_PACKED_INT, n, base, 0)
    rebased = (v - base).astype(np.uint64)
    nbits = next(w for w in _PACK_WIDTHS if spread < (1 << w) or w == 64)
    head = struct.pack("<BIqB", CODEC_PACKED_INT, n, base, nbits)
    if nbits >= 8:
        return head + rebased.astype(f"<u{nbits // 8}").tobytes()
    # sub-byte widths: pack per-value bits little-endian within each byte
    per_byte = 8 // nbits
    pad = (-n) % per_byte
    r = np.concatenate([rebased, np.zeros(pad, np.uint64)]) \
        .reshape(-1, per_byte).astype(np.uint8)
    shifts = (np.arange(per_byte, dtype=np.uint8) * nbits).astype(np.uint8)
    packed = (r << shifts).astype(np.uint8)
    return head + np.bitwise_or.reduce(packed, axis=1).tobytes()


def decode_packed_int(data: bytes) -> np.ndarray:
    codec, n, base, nbits = struct.unpack_from("<BIqB", data, 0)
    assert codec == CODEC_PACKED_INT, f"bad codec {codec}"
    off = struct.calcsize("<BIqB")
    if n == 0:
        return np.array([], np.int64)
    if nbits == 0:
        return np.full(n, base, dtype=np.int64)
    if nbits >= 8:
        raw = np.frombuffer(data, dtype=f"<u{nbits // 8}", count=n, offset=off)
        return base + raw.astype(np.int64)
    per_byte = 8 // nbits
    nbytes = (n + per_byte - 1) // per_byte
    b = np.frombuffer(data, dtype=np.uint8, count=nbytes, offset=off)
    shifts = (np.arange(per_byte, dtype=np.uint8) * nbits).astype(np.uint8)
    mask = np.uint8((1 << nbits) - 1)
    vals = ((b[:, None] >> shifts) & mask).reshape(-1)[:n]
    return base + vals.astype(np.int64)


def encode_int(values: np.ndarray) -> bytes:
    """Encode an int/long column picking the smaller of frame-of-reference
    bit packing and delta-delta+NibblePack (the reference's ``optimize`` step
    likewise picks the best encoding per chunk)."""
    packed = encode_packed_int(values)
    dd = encode_delta_delta(values)
    return packed if len(packed) <= len(dd) else dd


def _encode_blob_vector(codec_id: int, blobs: list[bytes]) -> bytes:
    """Shared layout for UTF8/MAP vectors: i32 end-offsets + concatenated
    blob (reference ``UTF8Vector.scala`` fixed-offset layout)."""
    offsets = np.zeros(len(blobs), dtype=np.uint32)
    total = 0
    for i, b in enumerate(blobs):
        total += len(b)
        offsets[i] = total
    return (struct.pack("<BII", codec_id, len(blobs), total)
            + offsets.tobytes() + b"".join(blobs))


def _decode_blob_vector(data: bytes, expect_codec: int) -> list[bytes]:
    codec, n, total = struct.unpack_from("<BII", data, 0)
    assert codec == expect_codec, f"bad codec {codec}"
    off = struct.calcsize("<BII")
    ends = np.frombuffer(data, dtype=np.uint32, count=n, offset=off)
    blob_off = off + 4 * n
    blob = data[blob_off : blob_off + total]
    out, start = [], 0
    for e in ends:
        out.append(blob[start : int(e)])
        start = int(e)
    return out


def encode_utf8(values: list[str]) -> bytes:
    """Raw (non-dict) UTF8 string vector — offsets + blob, values may contain
    any bytes including NULs (reference ``UTF8Vector.scala``)."""
    return _encode_blob_vector(CODEC_UTF8, [s.encode("utf-8") for s in values])


def decode_utf8(data: bytes) -> list[str]:
    return [b.decode("utf-8") for b in _decode_blob_vector(data, CODEC_UTF8)]


def encode_string(values: list[str]) -> bytes:
    """Encode a string column with dict-vs-raw auto-selection: dictionary
    when cardinality is low enough to pay off (reference
    ``DictUTF8Vector.shouldMakeDict`` samples for uniqueness the same way)."""
    uniq = len(set(values))
    if len(values) and uniq <= max(1, len(values) // 2):
        return encode_dict_string(values)
    return encode_utf8(values)


def _ser_map(m: dict) -> bytes:
    """Canonical binary form of one map row: sorted u16-length-prefixed
    key/value UTF8 pairs."""
    parts = [struct.pack("<H", len(m))]
    for k in sorted(m):
        kb, vb = k.encode("utf-8"), str(m[k]).encode("utf-8")
        parts.append(struct.pack("<HH", len(kb), len(vb)))
        parts.append(kb)
        parts.append(vb)
    return b"".join(parts)


def _deser_map(b: bytes) -> dict:
    (npairs,) = struct.unpack_from("<H", b, 0)
    off, out = 2, {}
    for _ in range(npairs):
        kl, vl = struct.unpack_from("<HH", b, off)
        off += 4
        k = b[off : off + kl].decode("utf-8")
        off += kl
        out[k] = b[off : off + vl].decode("utf-8")
        off += vl
    return out


def encode_map(values: list[dict]) -> bytes:
    """Map<string,string> column: rows serialized canonically, then
    dictionary-encoded over whole-row blobs (map rows repeat heavily —
    reference ``Column.MapColumn`` stores per-row label maps)."""
    blobs = [_ser_map(m or {}) for m in values]
    uniq: dict[bytes, int] = {}
    codes = np.empty(len(blobs), dtype=np.uint64)
    for i, b in enumerate(blobs):
        codes[i] = uniq.setdefault(b, len(uniq))
    table = list(uniq)
    head = _encode_blob_vector(CODEC_MAP, table)
    return head + struct.pack("<I", len(values)) + bytes(nibble_pack(codes))


def decode_map(data: bytes) -> list[dict]:
    codec, nuniq, total = struct.unpack_from("<BII", data, 0)
    assert codec == CODEC_MAP, f"bad codec {codec}"
    table_end = struct.calcsize("<BII") + 4 * nuniq + total
    table = [_deser_map(b) for b in _decode_blob_vector(data, CODEC_MAP)]
    (n,) = struct.unpack_from("<I", data, table_end)
    codes = nibble_unpack(data[table_end + 4 :], n)
    return [dict(table[int(c)]) for c in codes]


def encode_raw_double(values: np.ndarray) -> bytes:
    v = np.ascontiguousarray(values, dtype=np.float64)
    return struct.pack("<BI", CODEC_RAW_DOUBLE, len(v)) + v.tobytes()


def decode_raw_double(data: bytes) -> np.ndarray:
    codec, n = struct.unpack_from("<BI", data, 0)
    assert codec == CODEC_RAW_DOUBLE, f"bad codec {codec}"
    off = struct.calcsize("<BI")
    return np.frombuffer(data, dtype=np.float64, count=n, offset=off).copy()


@dataclass(frozen=True)
class DecodedVector:
    """A decoded column vector (host-side)."""

    values: np.ndarray  # int64 / float64 / (n, nb) int64 for histograms

    def __len__(self) -> int:
        return len(self.values)


def decode_any(data: bytes) -> np.ndarray | list[str]:
    """Dispatch on the leading codec id (reference: WireFormat word dispatch,
    ``BinaryVector.scala:526``)."""
    codec = data[0]
    if codec in (CODEC_DELTA_DELTA, CODEC_DELTA_DELTA_CONST):
        return decode_delta_delta(data)
    if codec == CODEC_XOR_DOUBLE:
        return decode_xor_double(data)
    if codec == CODEC_HIST_2D_DELTA:
        return decode_hist_2d_delta(data)
    if codec in (CODEC_DICT_STRING, CODEC_DICT_STRING_LP):
        return decode_dict_string(data)
    if codec == CODEC_RAW_DOUBLE:
        return decode_raw_double(data)
    if codec == CODEC_CONST_DOUBLE:
        return decode_const_double(data)
    if codec == CODEC_PACKED_INT:
        return decode_packed_int(data)
    if codec == CODEC_UTF8:
        return decode_utf8(data)
    if codec == CODEC_MAP:
        return decode_map(data)
    raise ValueError(f"unknown codec id {codec}")
