"""Chunk page format: an immutable, compressed set of rows for one partition.

Counterpart of the reference's ChunkSet/ChunkSetInfo
(``core/src/main/scala/filodb.core/store/ChunkSetInfo.scala:31,60``): a chunk
is one encoded vector per data column plus metadata (id, numRows, startTime,
endTime). Chunk ids are derived from the first timestamp so they sort by time
(reference ``ChunkSetInfo.chunkID``).

Serialization is a simple length-prefixed layout used by the column store and
the wire protocol.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.core.schemas import ColumnType, Schema
from filodb_tpu.memory import codecs
from filodb_tpu.utils.metrics import Counter

# chunks whose summary was computed after the fact (compaction of a
# pre-sidecar segment, lazy reads of natively-sealed chunks)
SIDECAR_BACKFILLED = Counter(
    "filodb_sidecar_backfilled",
    help="chunk summaries computed after seal (old segments, native seals)")


def chunk_id(start_time: int, ingestion_seq: int = 0) -> int:
    """Time-sortable chunk id: millis in high bits, sequence in low 12 bits."""
    return (start_time << 12) | (ingestion_seq & 0xFFF)


# ---------------------------------------------------------------------------
# aggregate sidecars (chunk-level summaries)
#
# Per scalar column, a 12-slot float64 stats vector computed once at seal
# time with strictly SEQUENTIAL accumulation (np.cumsum semantics — the same
# addition order a plain left-to-right loop produces), so a summary
# recomputed from the decoded vector is bitwise identical to the stored one
# (codecs are lossless):
#
#   0 count      non-NaN samples
#   1 sum        Σv            (sequential)
#   2 sumsq      Σv²           (sequential)
#   3 min / 4 max
#   5 first_ts / 6 first_val   first non-NaN sample
#   7 last_ts  / 8 last_val    last non-NaN sample
#   9 resets     count of drops v[i] < v[i-1] over the non-NaN sequence
#  10 corr       Σ prev at drop points (Prometheus reset correction, seq.)
#  11 changes    count of v[i] != v[i-1]
#
# plus an optional mergeable log2-bucket sketch (uint16[64]) for
# quantile/topk at declared approximation.

STATS_WIDTH = 12
(S_COUNT, S_SUM, S_SUMSQ, S_MIN, S_MAX, S_FIRST_TS, S_FIRST_VAL, S_LAST_TS,
 S_LAST_VAL, S_RESETS, S_CORR, S_CHANGES) = range(STATS_WIDTH)

SKETCH_BUCKETS = 64
_SC_MAGIC = b"SC01"


@dataclass(frozen=True, eq=False)
class ColumnSummary:
    """Fixed-size aggregate sidecar for one scalar column (see layout above).

    Registered on the wire (rides inside ``Chunk``); ``eq`` disabled —
    ndarray comparison is ambiguous and identity is what callers need."""

    stats: np.ndarray  # float64 [STATS_WIDTH]
    sketch: np.ndarray | None = None  # uint16 [SKETCH_BUCKETS]


def _sketch_values(vals: np.ndarray) -> np.ndarray:
    """Symmetric log2 histogram: bucket 32 = zero, 33..63 positive magnitudes
    by exponent (clipped), 31..1 negative mirrored, 0/63 overflow."""
    sk = np.zeros(SKETCH_BUCKETS, np.uint16)
    if vals.size == 0:
        return sk
    _, e = np.frexp(vals)  # |v| = m * 2^e, 0.5 <= |m| < 1
    mag = np.clip(e - 1 + 16, 0, 30)  # exponent -16..14 usable
    b = np.where(vals == 0, 32, np.where(vals > 0, 33 + mag, 31 - mag))
    np.add.at(sk, b.astype(np.int64), 1)
    return sk


def summarize_values(ts: np.ndarray, vals: np.ndarray,
                     with_sketch: bool = True) -> ColumnSummary:
    """Summarize one column of one chunk (or any time slice of it).

    NaN samples are excluded exactly like the decode lane
    (``engine/batch.build_batch`` filters them before the kernels see data).
    All accumulations are sequential (cumsum) so recomputation from a
    losslessly-decoded vector reproduces the stored bits."""
    vals = np.asarray(vals, np.float64)
    ts = np.asarray(ts, np.int64)
    stats = np.zeros(STATS_WIDTH, np.float64)
    m = ~np.isnan(vals)
    vv = vals[m]
    if vv.size == 0:
        stats[S_MIN:S_LAST_VAL + 1] = np.nan
        return ColumnSummary(stats, _sketch_values(vv) if with_sketch
                             else None)
    tv = ts[m]
    stats[S_COUNT] = vv.size
    stats[S_SUM] = np.cumsum(vv)[-1]
    stats[S_SUMSQ] = np.cumsum(vv * vv)[-1]
    stats[S_MIN] = np.min(vv)
    stats[S_MAX] = np.max(vv)
    stats[S_FIRST_TS] = tv[0]
    stats[S_FIRST_VAL] = vv[0]
    stats[S_LAST_TS] = tv[-1]
    stats[S_LAST_VAL] = vv[-1]
    if vv.size > 1:
        prev, cur = vv[:-1], vv[1:]
        drop = cur < prev
        stats[S_RESETS] = drop.sum()
        stats[S_CORR] = np.cumsum(np.where(drop, prev, 0.0))[-1]
        stats[S_CHANGES] = (cur != prev).sum()
    return ColumnSummary(stats, _sketch_values(vv) if with_sketch else None)


def summarize_columns(schema: Schema, ts: np.ndarray,
                      columns: list) -> tuple:
    """Per-vector summary tuple for a chunk being sealed from raw appender
    arrays (entry 0 is the timestamp column: None; non-scalar columns:
    None)."""
    out: list[ColumnSummary | None] = [None]
    for col, data in zip(schema.data.columns[1:], columns):
        if col.ctype in (ColumnType.DOUBLE, ColumnType.LONG, ColumnType.INT,
                         ColumnType.TIMESTAMP):
            out.append(summarize_values(ts, np.asarray(data, np.float64)))
        else:
            out.append(None)
    return tuple(out)


def ensure_summary(chunk: "Chunk", backfill: bool = False):
    """Return the chunk's summary tuple, computing it from the decoded
    vectors if absent (lazy path for natively-sealed chunks; backfill path
    for pre-sidecar segments met during compaction). Memoized on the chunk —
    they are immutable."""
    if chunk.summary is not None:
        return chunk.summary
    # best-effort: a vector this build can't decode (legacy codec, corrupt
    # bytes) yields no summary rather than failing the caller — compaction
    # must rewrite such chunks unchanged, and queries bypass to the decode
    # lane where CorruptVectorError surfaces with full forensic context
    try:
        ts = np.asarray(chunk.decode_column(0), np.int64)
    except CorruptVectorError:
        return None
    out: list[ColumnSummary | None] = [None]
    computed = False
    for i in range(1, len(chunk.vectors)):
        try:
            dec = chunk.decode_column(i)
        except CorruptVectorError:
            out.append(None)
            continue
        if isinstance(dec, np.ndarray) and dec.ndim == 1 \
                and dec.dtype.kind in "fiu" and len(dec) == len(ts):
            out.append(summarize_values(ts, dec))
            computed = True
        else:
            out.append(None)
    summary = tuple(out)
    object.__setattr__(chunk, "summary", summary)
    if backfill and computed:
        SIDECAR_BACKFILLED.inc()
    return summary


@dataclass(frozen=True)
class Chunk:
    """One encoded chunkset for a partition."""

    id: int
    num_rows: int
    start_time: int
    end_time: int
    vectors: tuple[bytes, ...]  # one encoded vector per data column
    # aggregate sidecar: one ColumnSummary|None per vector. Derived data —
    # excluded from equality (recomputable bit-for-bit from the vectors)
    summary: tuple | None = field(default=None, compare=False)

    @property
    def nbytes(self) -> int:
        return sum(len(v) for v in self.vectors)

    def decode_column(self, i: int):
        """Decode one column; memoized — chunks are immutable, and queries
        with overlapping ranges re-read the same chunks (the reference keeps
        decoded-adjacent state in block memory; here the decode cache plays
        that role). Decode failures raise CorruptVectorError with forensic
        context (reference ``CorruptVectorException`` analysis,
        ``MemStore.scala:220``)."""
        cache = self.__dict__.get("_decoded")
        if cache is None:
            object.__setattr__(self, "_decoded", {})
            cache = self.__dict__["_decoded"]
        out = cache.get(i)
        if out is None:
            try:
                out = cache[i] = codecs.decode_any(self.vectors[i])
            except Exception as e:
                raise CorruptVectorError(self, i, e) from e
        return out


    def serialize(self) -> bytes:
        head = struct.pack("<qIqqI", self.id, self.num_rows, self.start_time,
                           self.end_time, len(self.vectors))
        parts = [head]
        for v in self.vectors:
            parts.append(struct.pack("<I", len(v)))
            parts.append(v)
        # sidecar rides as a trailing section: pre-sidecar deserializers
        # stop after the declared vectors and never see it
        if self.summary is not None:
            parts.append(_SC_MAGIC)
            parts.append(struct.pack("<B", len(self.summary)))
            for cs in self.summary:
                if cs is None:
                    parts.append(b"\x00")
                elif cs.sketch is None:
                    parts.append(b"\x01")
                    parts.append(cs.stats.astype("<f8").tobytes())
                else:
                    parts.append(b"\x02")
                    parts.append(cs.stats.astype("<f8").tobytes())
                    parts.append(cs.sketch.astype("<u2").tobytes())
        return b"".join(parts)

    @staticmethod
    def deserialize(data: bytes) -> "Chunk":
        cid, rows, st, et, nvec = struct.unpack_from("<qIqqI", data, 0)
        off = struct.calcsize("<qIqqI")
        vectors = []
        for _ in range(nvec):
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            vectors.append(data[off : off + ln])
            off += ln
        summary = None
        if data[off : off + 4] == _SC_MAGIC:
            off += 4
            nents = data[off]
            off += 1
            ents: list[ColumnSummary | None] = []
            for _ in range(nents):
                kind = data[off]
                off += 1
                if kind == 0:
                    ents.append(None)
                    continue
                stats = np.frombuffer(data, "<f8", STATS_WIDTH, off).copy()
                off += STATS_WIDTH * 8
                sketch = None
                if kind == 2:
                    sketch = np.frombuffer(data, "<u2", SKETCH_BUCKETS,
                                           off).copy()
                    off += SKETCH_BUCKETS * 2
                ents.append(ColumnSummary(stats, sketch))
            summary = tuple(ents)
        return Chunk(cid, rows, st, et, tuple(vectors), summary)


class CorruptVectorError(RuntimeError):
    """A chunk vector failed to decode — data corruption tripwire.

    The reference halts the process on corruption
    (``Shutdown.haltAndCatchFire``, ``TimeSeriesShard.scala:349``); here the
    error carries chunk forensics and the shard marks itself errored via the
    standard error path (a Python process has no partially-written off-heap
    state worth halting for)."""

    def __init__(self, chunk: "Chunk", column: int, cause: Exception):
        head = chunk.vectors[column][:16].hex() if chunk.vectors else ""
        super().__init__(
            f"corrupt vector: chunk id={chunk.id} rows={chunk.num_rows} "
            f"range=[{chunk.start_time},{chunk.end_time}] column={column} "
            f"head16={head} cause={cause!r}")
        self.chunk_id = chunk.id
        self.column = column


def encode_chunk(schema: Schema, ts: np.ndarray, columns: list, seq: int = 0,
                 with_summary: bool = True) -> Chunk:
    """Encode one chunkset from appender contents.

    ``columns`` holds one array per non-timestamp data column, in schema order:
    float64 arrays for DOUBLE, int64 for LONG/INT, (n, nb) int64 for HISTOGRAM,
    list[str] for STRING.

    ``with_summary`` attaches the aggregate sidecar, computed from the raw
    arrays (bitwise identical to recomputing from the decoded vectors —
    the codecs are lossless). Pass False on hot transient paths (the live
    write-buffer pseudo-chunk) where the summary would be thrown away.
    """
    assert len(ts) > 0
    vectors: list[bytes] = [codecs.encode_delta_delta(ts)]
    for col, data in zip(schema.data.columns[1:], columns):
        if col.ctype == ColumnType.DOUBLE:
            vectors.append(codecs.encode_double(np.asarray(data, np.float64)))
        elif col.ctype in (ColumnType.LONG, ColumnType.INT, ColumnType.TIMESTAMP):
            vectors.append(codecs.encode_int(np.asarray(data, np.int64)))
        elif col.ctype == ColumnType.HISTOGRAM:
            if isinstance(data, codecs.HistogramColumn):
                vectors.append(codecs.encode_hist_2d_delta(data.rows, data.les))
            else:
                vectors.append(codecs.encode_hist_2d_delta(np.asarray(data, np.int64)))
        elif col.ctype == ColumnType.STRING:
            vectors.append(codecs.encode_string(list(data)))
        elif col.ctype == ColumnType.MAP:
            vectors.append(codecs.encode_map(list(data)))
        else:
            raise ValueError(f"unsupported column type {col.ctype}")
    summary = summarize_columns(schema, ts, columns) if with_summary else None
    return Chunk(chunk_id(int(ts[0]), seq), len(ts), int(ts[0]), int(ts[-1]),
                 tuple(vectors), summary)
