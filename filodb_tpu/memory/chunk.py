"""Chunk page format: an immutable, compressed set of rows for one partition.

Counterpart of the reference's ChunkSet/ChunkSetInfo
(``core/src/main/scala/filodb.core/store/ChunkSetInfo.scala:31,60``): a chunk
is one encoded vector per data column plus metadata (id, numRows, startTime,
endTime). Chunk ids are derived from the first timestamp so they sort by time
(reference ``ChunkSetInfo.chunkID``).

Serialization is a simple length-prefixed layout used by the column store and
the wire protocol.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from filodb_tpu.core.schemas import ColumnType, Schema
from filodb_tpu.memory import codecs


def chunk_id(start_time: int, ingestion_seq: int = 0) -> int:
    """Time-sortable chunk id: millis in high bits, sequence in low 12 bits."""
    return (start_time << 12) | (ingestion_seq & 0xFFF)


@dataclass(frozen=True)
class Chunk:
    """One encoded chunkset for a partition."""

    id: int
    num_rows: int
    start_time: int
    end_time: int
    vectors: tuple[bytes, ...]  # one encoded vector per data column

    @property
    def nbytes(self) -> int:
        return sum(len(v) for v in self.vectors)

    def decode_column(self, i: int):
        """Decode one column; memoized — chunks are immutable, and queries
        with overlapping ranges re-read the same chunks (the reference keeps
        decoded-adjacent state in block memory; here the decode cache plays
        that role). Decode failures raise CorruptVectorError with forensic
        context (reference ``CorruptVectorException`` analysis,
        ``MemStore.scala:220``)."""
        cache = self.__dict__.get("_decoded")
        if cache is None:
            object.__setattr__(self, "_decoded", {})
            cache = self.__dict__["_decoded"]
        out = cache.get(i)
        if out is None:
            try:
                out = cache[i] = codecs.decode_any(self.vectors[i])
            except Exception as e:
                raise CorruptVectorError(self, i, e) from e
        return out


    def serialize(self) -> bytes:
        head = struct.pack("<qIqqI", self.id, self.num_rows, self.start_time,
                           self.end_time, len(self.vectors))
        parts = [head]
        for v in self.vectors:
            parts.append(struct.pack("<I", len(v)))
            parts.append(v)
        return b"".join(parts)

    @staticmethod
    def deserialize(data: bytes) -> "Chunk":
        cid, rows, st, et, nvec = struct.unpack_from("<qIqqI", data, 0)
        off = struct.calcsize("<qIqqI")
        vectors = []
        for _ in range(nvec):
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            vectors.append(data[off : off + ln])
            off += ln
        return Chunk(cid, rows, st, et, tuple(vectors))


class CorruptVectorError(RuntimeError):
    """A chunk vector failed to decode — data corruption tripwire.

    The reference halts the process on corruption
    (``Shutdown.haltAndCatchFire``, ``TimeSeriesShard.scala:349``); here the
    error carries chunk forensics and the shard marks itself errored via the
    standard error path (a Python process has no partially-written off-heap
    state worth halting for)."""

    def __init__(self, chunk: "Chunk", column: int, cause: Exception):
        head = chunk.vectors[column][:16].hex() if chunk.vectors else ""
        super().__init__(
            f"corrupt vector: chunk id={chunk.id} rows={chunk.num_rows} "
            f"range=[{chunk.start_time},{chunk.end_time}] column={column} "
            f"head16={head} cause={cause!r}")
        self.chunk_id = chunk.id
        self.column = column


def encode_chunk(schema: Schema, ts: np.ndarray, columns: list, seq: int = 0) -> Chunk:
    """Encode one chunkset from appender contents.

    ``columns`` holds one array per non-timestamp data column, in schema order:
    float64 arrays for DOUBLE, int64 for LONG/INT, (n, nb) int64 for HISTOGRAM,
    list[str] for STRING.
    """
    assert len(ts) > 0
    vectors: list[bytes] = [codecs.encode_delta_delta(ts)]
    for col, data in zip(schema.data.columns[1:], columns):
        if col.ctype == ColumnType.DOUBLE:
            vectors.append(codecs.encode_double(np.asarray(data, np.float64)))
        elif col.ctype in (ColumnType.LONG, ColumnType.INT, ColumnType.TIMESTAMP):
            vectors.append(codecs.encode_int(np.asarray(data, np.int64)))
        elif col.ctype == ColumnType.HISTOGRAM:
            if isinstance(data, codecs.HistogramColumn):
                vectors.append(codecs.encode_hist_2d_delta(data.rows, data.les))
            else:
                vectors.append(codecs.encode_hist_2d_delta(np.asarray(data, np.int64)))
        elif col.ctype == ColumnType.STRING:
            vectors.append(codecs.encode_string(list(data)))
        elif col.ctype == ColumnType.MAP:
            vectors.append(codecs.encode_map(list(data)))
        else:
            raise ValueError(f"unsupported column type {col.ctype}")
    return Chunk(chunk_id(int(ts[0]), seq), len(ts), int(ts[0]), int(ts[-1]),
                 tuple(vectors))
