"""Kafka wire-protocol client + ingestion adapter.

Counterpart of the reference's Kafka module
(``kafka/src/main/scala/filodb.kafka/KafkaIngestionStream.scala:24,63``):
shards consume an EXTERNAL Kafka broker — one topic partition per shard,
message values are binary RecordContainer bytes, Kafka offsets are the
ingestion offsets that flush-group checkpoints record.

This is a real wire-protocol implementation (not a fake transport): framed
requests with the v0/v1 header, ApiVersions/Metadata/ListOffsets/Fetch/
Produce at protocol version 0, and MessageSet v0 entries with CRC-checked
messages — the subset every Kafka broker since 0.8 speaks. No external
client library; the environment has no egress, so tests run against
``FakeKafkaBroker`` (same module), which implements the same wire format
server-side; pointing ``KafkaReplayLog`` at a real broker is a host:port
change.

``KafkaReplayLog`` adapts the protocol client to the ``ReplayLog`` SPI
(``kafka/log.py``) — the consumer SPI's second, external-broker
implementation beside ``RemoteLog``/``SegmentedFileLog``.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import zlib
from dataclasses import dataclass

from filodb_tpu.core.record import BytesContainer, RecordContainer, SomeData
from filodb_tpu.kafka.log import ReplayLog
from filodb_tpu.kafka.log_server import LogOpError

log = logging.getLogger(__name__)

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_VERSIONS = 18

ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC = 3

_TS_LATEST = -1
_TS_EARLIEST = -2


# ---------------------------------------------------------------------------
# primitive codec


class _Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def i8(self, v):
        self.parts.append(struct.pack(">b", v))
        return self

    def i16(self, v):
        self.parts.append(struct.pack(">h", v))
        return self

    def i32(self, v):
        self.parts.append(struct.pack(">i", v))
        return self

    def i64(self, v):
        self.parts.append(struct.pack(">q", v))
        return self

    def string(self, s: str | None):
        if s is None:
            return self.i16(-1)
        b = s.encode("utf-8")
        self.i16(len(b))
        self.parts.append(b)
        return self

    def bytes_(self, b: bytes | None):
        if b is None:
            return self.i32(-1)
        self.i32(len(b))
        self.parts.append(b)
        return self

    def raw(self, b: bytes):
        self.parts.append(b)
        return self

    def done(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def i8(self):
        v = struct.unpack_from(">b", self.d, self.o)[0]
        self.o += 1
        return v

    def i16(self):
        v = struct.unpack_from(">h", self.d, self.o)[0]
        self.o += 2
        return v

    def i32(self):
        v = struct.unpack_from(">i", self.d, self.o)[0]
        self.o += 4
        return v

    def i64(self):
        v = struct.unpack_from(">q", self.d, self.o)[0]
        self.o += 8
        return v

    def string(self) -> str | None:
        n = self.i16()
        if n < 0:
            return None
        s = self.d[self.o : self.o + n].decode("utf-8")
        self.o += n
        return s

    def bytes_(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        b = self.d[self.o : self.o + n]
        self.o += n
        return b

    def raw(self, n: int) -> bytes:
        b = self.d[self.o : self.o + n]
        self.o += n
        return b

    @property
    def remaining(self) -> int:
        return len(self.d) - self.o


# ---------------------------------------------------------------------------
# MessageSet v0


def encode_message(key: bytes | None, value: bytes | None) -> bytes:
    """One Message v0: crc | magic=0 | attributes=0 | key | value."""
    body = _Writer().i8(0).i8(0).bytes_(key).bytes_(value).done()
    return struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body


def encode_message_set(entries: list[tuple[int, bytes | None, bytes | None]]
                       ) -> bytes:
    """[(offset, key, value)] -> MessageSet v0 bytes."""
    w = _Writer()
    for off, key, value in entries:
        msg = encode_message(key, value)
        w.i64(off).i32(len(msg)).raw(msg)
    return w.done()


def decode_message_set(data: bytes) -> list[tuple[int, bytes | None,
                                                  bytes | None]]:
    """MessageSet v0 bytes -> [(offset, key, value)]; a trailing partial
    message (Kafka truncates at max_bytes) is ignored."""
    out = []
    r = _Reader(data)
    while r.remaining >= 12:
        off = r.i64()
        size = r.i32()
        if size < 14 or r.remaining < size:
            break  # partial trailing message
        msg = r.raw(size)
        (crc,) = struct.unpack_from(">I", msg, 0)
        body = msg[4:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError(f"kafka message crc mismatch at offset {off}")
        mr = _Reader(body)
        magic = mr.i8()
        mr.i8()  # attributes (no compression support needed)
        if magic != 0:
            raise ValueError(f"unsupported message magic {magic}")
        key = mr.bytes_()
        value = mr.bytes_()
        out.append((off, key, value))
    return out


# ---------------------------------------------------------------------------
# client


class KafkaProtocolError(RuntimeError):
    def __init__(self, code: int, where: str):
        super().__init__(f"kafka error {code} in {where}")
        self.code = code


class KafkaProtocolClient:
    """Minimal blocking Kafka client: one broker connection, v0 APIs."""

    def __init__(self, host: str, port: int, client_id: str = "filodb",
                 timeout: float = 30.0):
        self.host = host
        self.port = port
        self.client_id = client_id
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._corr = 0
        self._lock = threading.Lock()

    # -- transport --

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            # the fd is owned-but-unpublished until self._sock = s
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except BaseException:
                try:
                    s.close()
                except OSError:
                    pass
                raise
            self._sock = s
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _roundtrip(self, api_key: int, api_version: int, body: bytes
                   ) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = _Writer().i16(api_key).i16(api_version).i32(corr) \
                .string(self.client_id).done()
            frame = header + body
            try:
                sock = self._conn_locked()
                sock.sendall(struct.pack(">i", len(frame)) + frame)
                resp = self._read_frame(sock)
            except (ConnectionError, OSError):
                self.close()
                raise
        r = _Reader(resp)
        got_corr = r.i32()
        if got_corr != corr:
            # response-stream desync: transport-class failure (a fresh
            # connection may recover), not a deterministic server answer
            self.close()
            raise ConnectionError(
                f"correlation id mismatch {got_corr} != {corr}")
        return r

    @staticmethod
    def _read_frame(sock: socket.socket) -> bytes:
        head = b""
        while len(head) < 4:
            chunk = sock.recv(4 - len(head))
            if not chunk:
                raise ConnectionError("kafka broker closed connection")
            head += chunk
        (size,) = struct.unpack(">i", head)
        if size < 0 or size > 1 << 30:
            raise ConnectionError(f"bad kafka frame size {size}")
        buf = bytearray()
        while len(buf) < size:
            chunk = sock.recv(min(1 << 20, size - len(buf)))
            if not chunk:
                raise ConnectionError("kafka broker closed mid-frame")
            buf.extend(chunk)
        return bytes(buf)

    # -- APIs (all protocol version 0) --

    def api_versions(self) -> dict[int, tuple[int, int]]:
        r = self._roundtrip(API_VERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise KafkaProtocolError(err, "api_versions")
        out = {}
        for _ in range(r.i32()):
            k, lo, hi = r.i16(), r.i16(), r.i16()
            out[k] = (lo, hi)
        return out

    def metadata(self, topics: list[str] | None = None):
        w = _Writer()
        topics = topics or []
        w.i32(len(topics))
        for t in topics:
            w.string(t)
        r = self._roundtrip(API_METADATA, 0, w.done())
        brokers = []
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            brokers.append((node, host, port))
        out_topics = {}
        for _ in range(r.i32()):
            terr = r.i16()
            name = r.string()
            parts = {}
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                replicas = [r.i32() for _ in range(r.i32())]
                isr = [r.i32() for _ in range(r.i32())]
                parts[pid] = {"error": perr, "leader": leader,
                              "replicas": replicas, "isr": isr}
            out_topics[name] = {"error": terr, "partitions": parts}
        return {"brokers": brokers, "topics": out_topics}

    def list_offsets(self, topic: str, partition: int,
                     timestamp: int = _TS_LATEST) -> int:
        """Earliest (-2) or latest (-1, = next offset to be assigned)."""
        w = _Writer().i32(-1).i32(1)
        w.string(topic).i32(1).i32(partition).i64(timestamp).i32(1)
        r = self._roundtrip(API_LIST_OFFSETS, 0, w.done())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                offs = [r.i64() for _ in range(r.i32())]
                if err:
                    raise KafkaProtocolError(err, "list_offsets")
                return offs[0] if offs else 0
        raise ConnectionError("empty list_offsets response")

    def produce(self, topic: str, partition: int,
                entries: list[tuple[bytes | None, bytes]],
                acks: int = 1, timeout_ms: int = 10_000) -> int:
        """Append [(key, value)]; returns the base offset assigned."""
        mset = encode_message_set([(0, k, v) for k, v in entries])
        w = _Writer().i16(acks).i32(timeout_ms).i32(1)
        w.string(topic).i32(1).i32(partition).i32(len(mset)).raw(mset)
        r = self._roundtrip(API_PRODUCE, 0, w.done())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                base = r.i64()
                if err:
                    raise KafkaProtocolError(err, "produce")
                return base
        raise ConnectionError("empty produce response")

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20, max_wait_ms: int = 100,
              min_bytes: int = 1) -> tuple[int, list]:
        """-> (high_watermark, [(offset, key, value)])."""
        w = _Writer().i32(-1).i32(max_wait_ms).i32(min_bytes).i32(1)
        w.string(topic).i32(1).i32(partition).i64(offset).i32(max_bytes)
        r = self._roundtrip(API_FETCH, 0, w.done())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                hw = r.i64()
                mset = r.bytes_() or b""
                if err:
                    raise KafkaProtocolError(err, "fetch")
                return hw, decode_message_set(mset)
        raise ConnectionError("empty fetch response")


# ---------------------------------------------------------------------------
# ReplayLog adapter (the KafkaIngestionStream analog)


class KafkaReplayLog(ReplayLog):
    """One shard's ingest log backed by one Kafka topic partition.

    Mirrors the reference's stream contract
    (``KafkaIngestionStream.scala:63``): partition == shard, message value
    == RecordContainer bytes, Kafka offset == checkpointed ingest offset.
    """

    def __init__(self, host: str, port: int, topic: str, partition: int,
                 client_id: str = "filodb-ingest", fetch_bytes: int = 1 << 20):
        self.topic = topic
        self.partition = partition
        self.fetch_bytes = fetch_bytes
        # separate producer and consumer connections (as real Kafka
        # clients use): a fetch long-poll must not block appends behind
        # the shared per-connection lock
        self.client = KafkaProtocolClient(host, port, client_id)
        self._consumer = KafkaProtocolClient(host, port,
                                             client_id + "-consumer")

    def append(self, container: RecordContainer) -> int:
        try:
            return self.client.produce(self.topic, self.partition,
                                       [(None, container.serialize())])
        except KafkaProtocolError as e:
            raise LogOpError(f"kafka produce failed: {e}") from e

    def read_from(self, offset: int):
        cur = max(offset, 0)
        while True:
            try:
                hw, msgs = self._consumer.fetch(self.topic, self.partition,
                                                cur,
                                                max_bytes=self.fetch_bytes)
            except KafkaProtocolError as e:
                if e.code == ERR_OFFSET_OUT_OF_RANGE:
                    earliest = self._consumer.list_offsets(
                        self.topic, self.partition, _TS_EARLIEST)
                    if earliest > cur:
                        cur = earliest  # log head truncated past us
                        continue
                    return
                # deterministic broker answer (missing topic, ...) — the
                # ingest worker's LogOpError path must see it, not retry
                # it as a transport flap
                raise LogOpError(f"kafka fetch failed: {e}") from e
            except ValueError as e:  # corrupt message set (CRC)
                raise LogOpError(f"kafka fetch corrupt: {e}") from e
            if not msgs:
                return
            for off, _key, value in msgs:
                # cur advances for EVERY decoded message — a tombstone or
                # duplicate must not wedge the poll loop on one offset
                advanced = max(cur, off + 1)
                if off >= cur and value is not None:
                    yield SomeData(BytesContainer(value), off)
                cur = advanced

    @property
    def latest_offset(self) -> int:
        try:
            # Kafka "latest" is the NEXT offset; ReplayLog wants the last
            return self.client.list_offsets(self.topic, self.partition,
                                            _TS_LATEST) - 1
        except KafkaProtocolError as e:
            raise LogOpError(f"kafka list_offsets failed: {e}") from e

    def align_after(self, offset: int) -> None:
        """No-op: the broker assigns strictly increasing offsets and never
        reuses them, so checkpointed offsets cannot collide after a crash
        (the property SegmentedFileLog must enforce by rolling segments)."""

    def close(self) -> None:
        self.client.close()
        self._consumer.close()


# ---------------------------------------------------------------------------
# protocol-level fake broker (tests; no egress in this environment)


@dataclass
class _PartitionLog:
    entries: list  # [(key, value)]
    base: int = 0  # earliest retained offset


class FakeKafkaBroker:
    """In-process TCP server speaking the same v0 wire protocol.

    This is a PROTOCOL fake, not a transport fake: it parses real request
    frames and emits real responses (CRC'd MessageSet v0 and all), so the
    client code it validates works against an actual broker unchanged.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._logs: dict[tuple[str, int], _PartitionLog] = {}
        self._lock = threading.Lock()
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(16)
        self.host, self.port = self._listen.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)

    def start(self) -> "FakeKafkaBroker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listen.close()
        except OSError:
            pass

    def create_topic(self, topic: str, partitions: int) -> None:
        with self._lock:
            for p in range(partitions):
                self._logs.setdefault((topic, p), _PartitionLog([]))

    def truncate_before(self, topic: str, partition: int,
                        offset: int) -> None:
        """Simulate retention: drop entries below ``offset``."""
        with self._lock:
            lg = self._logs[(topic, partition)]
            drop = max(0, min(offset - lg.base, len(lg.entries)))
            lg.entries = lg.entries[drop:]
            lg.base += drop

    # -- server loop --

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                try:
                    frame = KafkaProtocolClient._read_frame(conn)
                except (ConnectionError, ValueError, OSError):
                    return
                r = _Reader(frame)
                api_key = r.i16()
                api_version = r.i16()
                corr = r.i32()
                r.string()  # client_id
                if api_version != 0:
                    return  # v0-only fake: drop the connection
                body = self._dispatch(api_key, r)
                if body is None:
                    return
                resp = struct.pack(">i", len(body) + 4) \
                    + struct.pack(">i", corr) + body
                conn.sendall(resp)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, api_key: int, r: _Reader) -> bytes | None:
        if api_key == API_VERSIONS:
            w = _Writer().i16(0).i32(4)
            for k in (API_PRODUCE, API_FETCH, API_LIST_OFFSETS,
                      API_METADATA):
                w.i16(k).i16(0).i16(0)
            return w.done()
        if api_key == API_METADATA:
            n = r.i32()
            asked = [r.string() for _ in range(n)]
            with self._lock:
                names = {t for t, _ in self._logs}
            if asked:
                names &= set(asked)
            w = _Writer().i32(1).i32(0).string(self.host).i32(self.port)
            w.i32(len(names))
            for t in sorted(names):
                with self._lock:
                    parts = sorted(p for tt, p in self._logs if tt == t)
                w.i16(0).string(t).i32(len(parts))
                for p in parts:
                    w.i16(0).i32(p).i32(0).i32(1).i32(0).i32(1).i32(0)
            return w.done()
        if api_key == API_LIST_OFFSETS:
            r.i32()  # replica
            w = _Writer()
            n_topics = r.i32()
            w.i32(n_topics)
            for _ in range(n_topics):
                topic = r.string()
                nparts = r.i32()
                w.string(topic).i32(nparts)
                for _ in range(nparts):
                    pid = r.i32()
                    ts = r.i64()
                    r.i32()  # max offsets
                    with self._lock:
                        lg = self._logs.get((topic, pid))
                    if lg is None:
                        w.i32(pid).i16(ERR_UNKNOWN_TOPIC).i32(0)
                        continue
                    off = lg.base if ts == _TS_EARLIEST \
                        else lg.base + len(lg.entries)
                    w.i32(pid).i16(0).i32(1).i64(off)
            return w.done()
        if api_key == API_PRODUCE:
            r.i16()  # acks
            r.i32()  # timeout
            w = _Writer()
            n_topics = r.i32()
            w.i32(n_topics)
            for _ in range(n_topics):
                topic = r.string()
                nparts = r.i32()
                w.string(topic).i32(nparts)
                for _ in range(nparts):
                    pid = r.i32()
                    size = r.i32()
                    mset = r.raw(size)
                    try:
                        msgs = decode_message_set(mset)
                    except ValueError:
                        w.i32(pid).i16(2).i64(-1)  # CORRUPT_MESSAGE
                        continue
                    with self._lock:
                        lg = self._logs.setdefault((topic, pid),
                                                   _PartitionLog([]))
                        base = lg.base + len(lg.entries)
                        for _off, key, value in msgs:
                            lg.entries.append((key, value))
                    w.i32(pid).i16(0).i64(base)
            return w.done()
        if api_key == API_FETCH:
            r.i32()  # replica
            r.i32()  # max_wait
            r.i32()  # min_bytes
            w = _Writer()
            n_topics = r.i32()
            w.i32(n_topics)
            for _ in range(n_topics):
                topic = r.string()
                nparts = r.i32()
                w.string(topic).i32(nparts)
                for _ in range(nparts):
                    pid = r.i32()
                    off = r.i64()
                    max_bytes = r.i32()
                    with self._lock:
                        lg = self._logs.get((topic, pid))
                        if lg is None:
                            w.i32(pid).i16(ERR_UNKNOWN_TOPIC).i64(-1).i32(0)
                            continue
                        hw = lg.base + len(lg.entries)
                        if off < lg.base or off > hw:
                            w.i32(pid).i16(ERR_OFFSET_OUT_OF_RANGE) \
                                .i64(hw).i32(0)
                            continue
                        sel = []
                        total = 0
                        for i in range(off - lg.base, len(lg.entries)):
                            key, value = lg.entries[i]
                            sel.append((lg.base + i, key, value))
                            total += 26 + len(key or b"") + len(value or b"")
                            if total >= max_bytes:
                                break
                    mset = encode_message_set(sel)
                    w.i32(pid).i16(0).i64(hw).i32(len(mset)).raw(mset)
            return w.done()
        return None  # unknown api: drop connection
