"""Ingestion log transport: Kafka-compatible contract.

Counterpart of reference ``kafka/`` module (``KafkaIngestionStream.scala:24,63``):
one log partition == one shard; messages are serialized RecordContainers;
offsets are replayable for recovery. The broker is pluggable — in-memory and
file-backed logs here, a real Kafka client slots behind the same interface.
"""

from filodb_tpu.kafka.log import FileLog, InMemoryLog, ReplayLog  # noqa: F401
