"""Replayable ingestion logs.

The reference's durability story is "no data loss within Kafka retention":
shards checkpoint (group → offset) and, on restart, replay the log from
``min(checkpoints)`` skipping below-watermark rows (reference
``doc/ingestion.md:114``, ``TimeSeriesMemStore.recoverStream``). These logs
provide that contract in-process (tests) and on disk (standalone server).
"""

from __future__ import annotations

import os
import struct
import threading
from collections.abc import Iterator

from filodb_tpu.core.record import BytesContainer, RecordContainer, SomeData


class ReplayLog:
    """One shard's ordered, offset-addressed container log."""

    def append(self, container: RecordContainer) -> int:
        raise NotImplementedError

    def read_from(self, offset: int) -> Iterator[SomeData]:
        raise NotImplementedError

    @property
    def latest_offset(self) -> int:
        raise NotImplementedError

    def align_after(self, offset: int) -> None:
        """Ensure the next append is assigned an offset strictly greater
        than ``offset``. Recovery calls this with the max group checkpoint:
        a torn tail may have destroyed records whose offsets were already
        checkpointed, and reusing those offsets would make the watermark
        skip-check silently drop new acknowledged rows. Default no-op —
        in-process logs die with the process, so the collision cannot
        arise; ``SegmentedFileLog`` rolls a fresh segment past the offset.
        """

class InMemoryLog(ReplayLog):
    def __init__(self):
        self._entries: list[RecordContainer] = []
        self._lock = threading.Lock()

    def append(self, container: RecordContainer) -> int:
        with self._lock:
            self._entries.append(container)
            return len(self._entries) - 1

    def read_from(self, offset: int) -> Iterator[SomeData]:
        start = max(offset, 0)
        for i in range(start, len(self._entries)):
            yield SomeData(self._entries[i], i)

    @property
    def latest_offset(self) -> int:
        return len(self._entries) - 1


class FileLog(ReplayLog):
    """Append-only length-prefixed record log with a sparse offset index.

    Layout per entry: u32 length | container bytes. A side index file holds
    (offset, file_pos) every ``index_every`` entries for seek-on-replay.

    Durability: by default acknowledged appends survive *process* crashes
    (buffered write + flush) but not OS/power failure; pass ``fsync=True``
    to fsync every append (the reference delegates this to Kafka acks).
    """

    MAGIC = b"FLOG1"

    def __init__(self, path: str, index_every: int = 64,
                 fsync: bool = False):
        self.path = path
        self.index_every = index_every
        self.fsync = fsync
        self._lock = threading.Lock()
        self._count = 0
        self._index: list[tuple[int, int]] = []  # (offset, pos)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            self._recover_scan()
        else:
            with open(path, "wb") as f:
                f.write(self.MAGIC)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            if fsync:
                # the directory entry of a fresh segment must also be durable
                # or the whole file (incl. later fsync'd appends) can vanish
                # on power failure
                dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
        self._f = open(path, "ab")

    def _recover_scan(self):
        size = os.path.getsize(self.path)
        with open(self.path, "rb") as f:
            magic = f.read(5)
            assert magic == self.MAGIC, "bad log file"
            pos = 5
            while pos + 4 <= size:
                f.seek(pos)
                (ln,) = struct.unpack("<I", f.read(4))
                if pos + 4 + ln > size:
                    break  # truncated tail (torn write)
                if self._count % self.index_every == 0:
                    self._index.append((self._count, pos))
                pos += 4 + ln
                self._count += 1
        if pos < size:
            # Torn tail: records appended after reopening in append mode
            # would land after the garbage bytes and be unreadable, so cut
            # the file back to the last complete record.
            with open(self.path, "r+b") as f:
                f.truncate(pos)

    def append(self, container: RecordContainer) -> int:
        payload = container.serialize()
        with self._lock:
            pos = self._f.tell()
            if self._count % self.index_every == 0:
                self._index.append((self._count, pos))
            self._f.write(struct.pack("<I", len(payload)))
            self._f.write(payload)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            off = self._count
            self._count += 1
            return off

    def read_from(self, offset: int) -> Iterator[SomeData]:
        offset = max(offset, 0)
        with self._lock:
            self._f.flush()
            count = self._count
            # seek via sparse index
            seek_off, seek_pos = 0, 5
            for o, p in self._index:
                if o <= offset:
                    seek_off, seek_pos = o, p
                else:
                    break
        with open(self.path, "rb") as f:
            f.seek(seek_pos)
            cur = seek_off
            while cur < count:
                hdr = f.read(4)
                if len(hdr) < 4:
                    break
                (ln,) = struct.unpack("<I", hdr)
                data = f.read(ln)
                if cur >= offset:
                    yield SomeData(BytesContainer(data), cur)
                cur += 1

    @property
    def latest_offset(self) -> int:
        return self._count - 1

    def close(self):
        self._f.close()


class SegmentedFileLog(ReplayLog):
    """Segment-per-N-entries log with retention truncation (the Kafka
    segment/retention model): appends roll new segment files; whole segments
    wholly below the cluster's checkpoint watermark are deleted
    (``truncate_before``), bounding WAL growth without rewrite."""

    def __init__(self, directory: str, segment_entries: int = 4096,
                 index_every: int = 64, fsync: bool = False):
        self.dir = directory
        self.segment_entries = segment_entries
        self.index_every = index_every
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        self._segments: list[tuple[int, FileLog]] = []  # (first_offset, log)
        for name in sorted(os.listdir(directory)):
            if name.startswith("seg-") and name.endswith(".log"):
                first = int(name[4:-4])
                self._segments.append(
                    (first, FileLog(os.path.join(directory, name),
                                    index_every, fsync=fsync)))
        if not self._segments:
            self._roll(0)

    def _roll(self, first_offset: int) -> None:
        path = os.path.join(self.dir, f"seg-{first_offset:020d}.log")
        self._segments.append((first_offset, FileLog(path, self.index_every,
                                                     fsync=self.fsync)))

    def append(self, container: RecordContainer) -> int:
        with self._lock:
            first, seg = self._segments[-1]
            if seg.latest_offset + 1 >= self.segment_entries:
                first = first + seg.latest_offset + 1
                self._roll(first)
                first, seg = self._segments[-1]
            local = seg.append(container)
            return first + local

    def read_from(self, offset: int):
        offset = max(offset, 0)
        with self._lock:
            segments = list(self._segments)
        for first, seg in segments:
            last = first + seg.latest_offset
            if last < offset:
                continue
            for sd in seg.read_from(max(offset - first, 0)):
                yield SomeData(sd.container, first + sd.offset)

    @property
    def latest_offset(self) -> int:
        first, seg = self._segments[-1]
        return first + seg.latest_offset

    def align_after(self, offset: int) -> None:
        with self._lock:
            first, seg = self._segments[-1]
            if first + seg.latest_offset >= offset:
                return
            if first > offset and seg.latest_offset < 0:
                return  # empty segment already starts past the offset
            self._roll(offset + 1)

    def truncate_before(self, offset: int) -> int:
        """Delete whole segments entirely below ``offset``. Returns segments
        removed. The newest segment is always retained."""
        removed = 0
        with self._lock:
            while len(self._segments) > 1:
                first, seg = self._segments[0]
                if first + seg.latest_offset < offset:
                    seg.close()
                    os.remove(seg.path)
                    self._segments.pop(0)
                    removed += 1
                else:
                    break
        return removed

    @property
    def earliest_offset(self) -> int:
        return self._segments[0][0]

    def close(self):
        for _, seg in self._segments:
            seg.close()
