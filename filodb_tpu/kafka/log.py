"""Replayable ingestion logs.

The reference's durability story is "no data loss within Kafka retention":
shards checkpoint (group → offset) and, on restart, replay the log from
``min(checkpoints)`` skipping below-watermark rows (reference
``doc/ingestion.md:114``, ``TimeSeriesMemStore.recoverStream``). These logs
provide that contract in-process (tests) and on disk (standalone server).
"""

from __future__ import annotations

import os
import struct
import threading
from collections.abc import Iterator

from filodb_tpu.core.record import BytesContainer, RecordContainer, SomeData


class ReplayLog:
    """One shard's ordered, offset-addressed container log."""

    def append(self, container: RecordContainer) -> int:
        raise NotImplementedError

    def read_from(self, offset: int) -> Iterator[SomeData]:
        raise NotImplementedError

    @property
    def latest_offset(self) -> int:
        raise NotImplementedError

    def offset_lag(self, consumed: int) -> int:
        """Records appended but not yet consumed past ``consumed`` (the
        freshness gauge the coordinator exposes per shard). Clamped at 0:
        a consumer ahead of a freshly-rolled log is caught up, not
        negative."""
        return max(0, self.latest_offset - consumed)

    def align_after(self, offset: int) -> None:
        """Ensure the next append is assigned an offset strictly greater
        than ``offset``. Recovery calls this with the max group checkpoint:
        a torn tail may have destroyed records whose offsets were already
        checkpointed, and reusing those offsets would make the watermark
        skip-check silently drop new acknowledged rows. Default no-op —
        in-process logs die with the process, so the collision cannot
        arise; ``SegmentedFileLog`` rolls a fresh segment past the offset.
        """

class InMemoryLog(ReplayLog):
    def __init__(self):
        self._entries: list[RecordContainer] = []
        self._lock = threading.Lock()

    def append(self, container: RecordContainer) -> int:
        with self._lock:
            self._entries.append(container)
            return len(self._entries) - 1

    def read_from(self, offset: int) -> Iterator[SomeData]:
        start = max(offset, 0)
        # snapshot under the lock (taken at first next(), when the
        # generator body runs): replay sees a consistent prefix instead
        # of racing concurrent appends mid-iteration
        with self._lock:
            entries = self._entries[start:]
        for i, container in enumerate(entries):
            yield SomeData(container, start + i)

    @property
    def latest_offset(self) -> int:
        with self._lock:
            return len(self._entries) - 1


class FileLog(ReplayLog):
    """Append-only length-prefixed record log with a sparse offset index.

    Layout per entry: u32 length | container bytes. A side index file holds
    (offset, file_pos) every ``index_every`` entries for seek-on-replay.

    Durability: by default acknowledged appends survive *process* crashes
    (buffered write + flush) but not OS/power failure; pass ``fsync=True``
    to fsync every append (the reference delegates this to Kafka acks).
    """

    MAGIC = b"FLOG1"

    def __init__(self, path: str, index_every: int = 64,
                 fsync: bool = False, read_only: bool = False):
        """``read_only``: a shared-FS tailer's view of another process's
        live segment — never truncates, never opens a write handle (a
        tailer running the owner's torn-tail recovery would corrupt
        acknowledged data mid-append)."""
        self.path = path
        self.index_every = index_every
        self.fsync = fsync
        self.read_only = read_only
        self._lock = threading.Lock()
        self._count = 0
        self._index: list[tuple[int, int]] = []  # (offset, pos)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            self._recover_scan()
        elif read_only:
            raise FileNotFoundError(path)
        else:
            with open(path, "wb") as f:
                f.write(self.MAGIC)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            if fsync:
                # the directory entry of a fresh segment must also be durable
                # or the whole file (incl. later fsync'd appends) can vanish
                # on power failure
                dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
        self._f = None if read_only else open(path, "ab")

    def _recover_scan(self):
        # only called from __init__, but _count/_index are lock-guarded
        # everywhere else — hold it here too so the invariant is uniform
        # (and checkable) rather than "guarded except during recovery"
        with self._lock:
            self._recover_scan_locked()

    def _recover_scan_locked(self):
        size = os.path.getsize(self.path)
        with open(self.path, "rb") as f:
            magic = f.read(5)
            if magic != self.MAGIC:
                if self.read_only:
                    return  # half-created segment: skip, retry next poll
                raise ValueError(f"bad log file {self.path}")
            pos = 5
            while pos + 4 <= size:
                f.seek(pos)
                (ln,) = struct.unpack("<I", f.read(4))
                if pos + 4 + ln > size:
                    break  # truncated tail (torn write)
                if self._count % self.index_every == 0:
                    self._index.append((self._count, pos))
                pos += 4 + ln
                self._count += 1
        if pos < size and not self.read_only:
            # Torn tail: records appended after reopening in append mode
            # would land after the garbage bytes and be unreadable, so cut
            # the file back to the last complete record. (Tailers must NOT
            # do this — a partial record may be the owner's append in
            # flight.)
            with open(self.path, "r+b") as f:
                f.truncate(pos)

    def append(self, container: RecordContainer) -> int:
        if self.read_only:
            raise OSError(f"read-only tailer view of {self.path}")
        payload = container.serialize()
        with self._lock:
            pos = self._f.tell()
            if self._count % self.index_every == 0:
                self._index.append((self._count, pos))
            self._f.write(struct.pack("<I", len(payload)))
            self._f.write(payload)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            off = self._count
            self._count += 1
            return off

    def read_from(self, offset: int) -> Iterator[SomeData]:
        """Yield complete records from ``offset`` to end-of-file.

        Scans to EOF rather than to this instance's record count: a tailer
        in ANOTHER process (shard owner tailing a gateway-written log on a
        shared filesystem) must see records appended after it opened the
        file. A partial record at the tail (append in flight, or torn) ends
        the scan; the next poll retries it.
        """
        offset = max(offset, 0)
        with self._lock:
            if self._f is not None:
                self._f.flush()
            # seek via sparse index
            seek_off, seek_pos = 0, 5
            for o, p in self._index:
                if o <= offset:
                    seek_off, seek_pos = o, p
                else:
                    break
        with open(self.path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            f.seek(seek_pos)
            cur = seek_off
            pos = seek_pos
            while pos + 4 <= size:
                hdr = f.read(4)
                if len(hdr) < 4:
                    break
                (ln,) = struct.unpack("<I", hdr)
                if pos + 4 + ln > size:
                    break  # partial tail: append in flight or torn
                data = f.read(ln)
                if len(data) < ln:
                    break
                if cur >= offset:
                    yield SomeData(BytesContainer(data), cur)
                cur += 1
                pos += 4 + ln

    @property
    def latest_offset(self) -> int:
        with self._lock:
            return self._count - 1

    def close(self):
        if self._f is not None:
            self._f.close()


class SegmentedFileLog(ReplayLog):
    """Segment-per-N-entries log with retention truncation (the Kafka
    segment/retention model): appends roll new segment files; whole segments
    wholly below the cluster's checkpoint watermark are deleted
    (``truncate_before``), bounding WAL growth without rewrite."""

    def __init__(self, directory: str, segment_entries: int = 4096,
                 index_every: int = 64, fsync: bool = False,
                 read_only: bool = False):
        """``read_only``: a tailer's view of a log another process appends
        to (shard owner tailing the gateway's segments on a shared FS) —
        all segments open read-only, append/retention are forbidden, and
        no recovery truncation ever touches the appender's files."""
        self.dir = directory
        self.segment_entries = segment_entries
        self.index_every = index_every
        self.fsync = fsync
        self.read_only = read_only
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        self._segments: list[tuple[int, FileLog]] = []  # (first_offset, log)
        for name in sorted(os.listdir(directory)):
            if name.startswith("seg-") and name.endswith(".log"):
                first = int(name[4:-4])
                self._segments.append(
                    (first, FileLog(os.path.join(directory, name),
                                    index_every, fsync=fsync,
                                    read_only=read_only)))
        if not self._segments and not read_only:
            self._roll(0)

    def _roll(self, first_offset: int) -> None:
        path = os.path.join(self.dir, f"seg-{first_offset:020d}.log")
        self._segments.append((first_offset, FileLog(path, self.index_every,
                                                     fsync=self.fsync)))

    def append(self, container: RecordContainer) -> int:
        if self.read_only:
            raise OSError(f"read-only tailer view of {self.dir}")
        with self._lock:
            first, seg = self._segments[-1]
            if seg.latest_offset + 1 >= self.segment_entries:
                first = first + seg.latest_offset + 1
                self._roll(first)
                first, seg = self._segments[-1]
            local = seg.append(container)
            return first + local

    def _discover_segments(self) -> None:
        """Pick up segment files rolled by another process (shared-FS
        tailer): the appender may roll new files after we opened the dir.
        Discovered segments open READ-ONLY — they belong to the appender
        process; an append-mode open would run torn-tail truncation against
        a live file."""
        known = {first for first, _ in self._segments}
        for name in sorted(os.listdir(self.dir)):
            if name.startswith("seg-") and name.endswith(".log"):
                first = int(name[4:-4])
                if first not in known:
                    try:
                        self._segments.append(
                            (first, FileLog(os.path.join(self.dir, name),
                                            self.index_every,
                                            read_only=True)))
                    except FileNotFoundError:
                        pass  # raced a concurrent delete
        self._segments.sort(key=lambda t: t[0])

    def read_from(self, offset: int):
        offset = max(offset, 0)
        with self._lock:
            self._discover_segments()
            segments = list(self._segments)
        for i, (first, seg) in enumerate(segments):
            # a segment's upper bound is the NEXT segment's first offset —
            # this instance's record counts are stale for segments another
            # process appends to, so never skip on latest_offset alone
            if i + 1 < len(segments) and segments[i + 1][0] <= offset:
                continue
            try:
                for sd in seg.read_from(max(offset - first, 0)):
                    yield SomeData(sd.container, first + sd.offset)
            except FileNotFoundError:
                # another process truncated this flushed segment; its
                # records are below every checkpoint — drop our entry
                with self._lock:
                    self._segments = [(f, s) for f, s in self._segments
                                      if f != first]

    @property
    def latest_offset(self) -> int:
        with self._lock:
            if not self._segments:
                return -1
            first, seg = self._segments[-1]
        return first + seg.latest_offset

    def align_after(self, offset: int) -> None:
        if self.read_only:
            return  # offset assignment is the appender's concern
        with self._lock:
            first, seg = self._segments[-1]
            if first + seg.latest_offset >= offset:
                return
            if first > offset and seg.latest_offset < 0:
                return  # empty segment already starts past the offset
            self._roll(offset + 1)

    def truncate_before(self, offset: int) -> int:
        """Delete whole segments entirely below ``offset``. Returns segments
        removed. The newest segment is always retained.

        Allowed on read-only tailer views too: the shard OWNER drives
        retention (it knows the checkpoint watermark), and unlinking a
        wholly-flushed segment file is safe against the appender — the
        appender only writes to the newest segment, and POSIX keeps its
        open handles valid."""
        removed = 0
        with self._lock:
            self._discover_segments()
            while len(self._segments) > 1:
                first, seg = self._segments[0]
                # a segment's true upper bound is the NEXT segment's first
                # offset — this instance's record counts are stale for
                # segments another process appends to, so latest_offset
                # must never decide deletability
                next_first = self._segments[1][0]
                if next_first <= offset:
                    seg.close()
                    try:
                        os.remove(seg.path)
                    except FileNotFoundError:
                        pass  # another process already truncated it
                    self._segments.pop(0)
                    removed += 1
                else:
                    break
        return removed

    @property
    def earliest_offset(self) -> int:
        with self._lock:
            return self._segments[0][0] if self._segments else 0

    def close(self):
        for _, seg in self._segments:
            seg.close()
