"""Networked ingest log: the Kafka-contract transport.

Counterpart of the reference's Kafka ingestion path
(``kafka/src/main/scala/filodb/kafka/KafkaIngestionStream.scala:24,63``): one
log partition == one shard, messages are binary RecordContainer bytes, and
the gateway and shard owners talk to the log over the NETWORK — no shared
filesystem. ``LogServer`` fronts a directory of ``SegmentedFileLog``s (the
"broker"); ``RemoteLog`` implements the ``ReplayLog`` interface over the
framed, secret-authenticated transport shared with plan shipping
(``coordinator/remote.py``).

Protocol messages (typed wire codec):
    ("append", dataset, shard, container_bytes)      -> ("ok", offset)
    ("read",   dataset, shard, from_offset, max_n)   -> ("ok", [(off, bytes)])
    ("latest", dataset, shard)                       -> ("ok", offset)
    ("truncate", dataset, shard, before_offset)      -> ("ok", removed)
    ("align",  dataset, shard, offset)               -> ("ok", True)
"""

from __future__ import annotations

import logging
import os
import re
import socket
import socketserver
import threading

from filodb_tpu.coordinator.remote import (
    _recv_msg,
    _send_msg,
    cluster_secret,
    make_authed_handler,
)
from filodb_tpu.core.record import BytesContainer, RecordContainer, SomeData
from filodb_tpu.kafka.log import ReplayLog, SegmentedFileLog

log = logging.getLogger(__name__)

# Dataset names come off the wire; they become path components under the
# broker root, so anything outside this alphabet (especially "/" and "..")
# is rejected before the filesystem is touched.
_SAFE_NAME = re.compile(r"[A-Za-z0-9_.-]{1,128}\Z")

# one read reply is materialized fully in memory before send; cap it so a
# single request can't make the broker slurp an entire shard log
MAX_READ_BATCH = 4096


class LogOpError(RuntimeError):
    """A server-side ('err', ...) reply — deterministic, not a transport
    failure. Callers that retry transport errors (ConnectionError/OSError)
    must NOT retry these forever: the server will keep answering the same
    way (corrupt log file, rejected name, oversized read...)."""


def _validate_target(dataset, shard) -> str | None:
    if not isinstance(dataset, str) or not _SAFE_NAME.fullmatch(dataset) \
            or dataset in (".", ".."):
        return f"invalid dataset name {dataset!r}"
    if not isinstance(shard, int) or isinstance(shard, bool) or shard < 0 \
            or shard > 1_000_000:
        return f"invalid shard {shard!r}"
    return None


class LogServer:
    """Serves a WAL directory over TCP (the broker role)."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 segment_entries: int = 4096, fsync: bool = False,
                 secret: str | None = None):
        self.root = root
        self.secret = secret if secret is not None else cluster_secret()
        self._logs: dict[tuple[str, int], SegmentedFileLog] = {}
        self._lock = threading.Lock()
        self._segment_entries = segment_entries
        self._fsync = fsync
        Handler = make_authed_handler(lambda: self.secret, self._handle,
                                      "log server")

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        self.server = Server((host, port), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def _log(self, dataset: str, shard: int) -> SegmentedFileLog:
        key = (dataset, shard)
        with self._lock:
            lg = self._logs.get(key)
            if lg is None:
                lg = SegmentedFileLog(
                    os.path.join(self.root, dataset, f"shard-{shard}"),
                    segment_entries=self._segment_entries,
                    fsync=self._fsync)
                self._logs[key] = lg
            return lg

    def _handle(self, msg):
        kind = msg[0]
        try:
            if kind == "ping":
                return ("pong",)
            if kind in ("append", "read", "latest", "truncate", "align"):
                bad = _validate_target(msg[1], msg[2])
                if bad is not None:
                    return ("err", bad)
            if kind == "append":
                _, dataset, shard, raw = msg
                off = self._log(dataset, shard).append(BytesContainer(raw))
                return ("ok", off)
            if kind == "read":
                _, dataset, shard, from_off, max_n = msg
                if not isinstance(from_off, int) or not isinstance(max_n, int):
                    return ("err", "invalid read parameters")
                max_n = min(max_n, MAX_READ_BATCH)
                if max_n <= 0:
                    return ("ok", [])
                out = []
                for sd in self._log(dataset, shard).read_from(from_off):
                    out.append((sd.offset, sd.container.serialize()))
                    if len(out) >= max_n:
                        break
                return ("ok", out)
            if kind == "latest":
                _, dataset, shard = msg
                return ("ok", self._log(dataset, shard).latest_offset)
            if kind == "truncate":
                _, dataset, shard, before = msg
                return ("ok",
                        self._log(dataset, shard).truncate_before(before))
            if kind == "align":
                _, dataset, shard, offset = msg
                self._log(dataset, shard).align_after(offset)
                return ("ok", True)
            return ("err", f"unknown message {kind!r}")
        except Exception as e:
            from filodb_tpu.utils.metrics import get_counter
            topic = "?"
            if len(msg) >= 3 and isinstance(msg[1], str):
                topic = f"{msg[1]}/{msg[2]}"
            get_counter("filodb_log_server_errors",
                        {"op": str(kind), "topic": topic}).inc()
            log.exception("log op %s failed for topic %s", kind, topic)
            return ("err", repr(e))

    def start(self) -> "LogServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        with self._lock:
            for lg in self._logs.values():
                lg.close()
            self._logs.clear()


class RemoteLog(ReplayLog):
    """``ReplayLog`` over a ``LogServer`` — the KafkaIngestionStream analog:
    shard owners tail their partition, gateways produce to it, across
    hosts."""

    def __init__(self, host: str, port: int, dataset: str, shard: int,
                 timeout: float = 30.0, read_batch: int = 256):
        self.host = host
        self.port = port
        self.dataset = dataset
        self.shard = shard
        self.timeout = timeout
        # must not exceed the broker's reply cap: read_from detects end-of-
        # log by a short batch, so a client asking for more than the server
        # will ever send would mistake every capped reply for the end
        self.read_batch = min(read_batch, MAX_READ_BATCH)
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            # the fd is owned-but-unpublished until self._sock = s; any
            # exception before that (setsockopt, auth) must close it
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                secret = cluster_secret()
                if secret is not None:
                    _send_msg(s, ("auth", secret))
                    if _recv_msg(s)[0] != "ok":
                        raise ConnectionError("log server auth rejected")
            except BaseException:
                try:
                    s.close()
                except OSError:
                    pass
                raise
            self._sock = s
        return self._sock

    def _call(self, *msg):
        with self._lock:
            try:
                sock = self._conn_locked()
                _send_msg(sock, msg)
                resp = _recv_msg(sock)
            except (ConnectionError, OSError):
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                raise
        if resp[0] == "ok":
            return resp[1]
        if resp[0] == "pong":
            return True
        raise LogOpError(f"log op failed: {resp[1]}")

    def append(self, container: RecordContainer) -> int:
        return self._call("append", self.dataset, self.shard,
                          container.serialize())

    def read_from(self, offset: int):
        cur = max(offset, 0)
        while True:
            batch = self._call("read", self.dataset, self.shard, cur,
                               self.read_batch)
            for off, raw in batch:
                yield SomeData(BytesContainer(raw), off)
                cur = off + 1
            if len(batch) < self.read_batch:
                return

    @property
    def latest_offset(self) -> int:
        return self._call("latest", self.dataset, self.shard)

    def truncate_before(self, offset: int) -> int:
        return self._call("truncate", self.dataset, self.shard, offset)

    def align_after(self, offset: int) -> None:
        self._call("align", self.dataset, self.shard, offset)

    def ping(self) -> bool:
        try:
            return bool(self._call("ping"))
        except (ConnectionError, OSError, RuntimeError):
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
