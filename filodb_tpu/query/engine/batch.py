"""SeriesBatch: the dense tensor form of a set of time series.

The bridge between the host-side chunk store and the TPU kernels. Decoding
(host, C++/numpy codecs) happens once per query per partition; the result is
packed into padded arrays whose shapes are bucketed (next power of two) so XLA
compilation caches are reused across queries.

Timestamps are rebased to ``base_ts`` and stored as int32 milliseconds —
queries spanning more than ~24 days are split by the planner (reference analog:
time-split planning, ``SingleClusterPlanner.materializeTimeSplitPlan``).
NaN samples (staleness markers) are filtered host-side so kernels may assume
every in-count sample is valid.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.memory.codecs import HistogramColumn

TS_PAD = np.iinfo(np.int32).max


def _next_pow2(n: int, floor: int = 8) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


@dataclass
class SeriesBatch:
    """Padded batch of P series with up to S samples each.

    ``ts``/``vals`` are numpy here; kernels convert to device arrays. For
    histogram batches ``vals`` has shape [P, S, B] and ``les`` [B].
    """

    base_ts: int                      # epoch ms subtracted from all timestamps
    ts: np.ndarray                    # int32 [P, S], padded with TS_PAD
    vals: np.ndarray                  # float [P, S] or [P, S, B]
    counts: np.ndarray                # int32 [P]
    part_ids: list[int]               # originating partition ids (host metadata)
    les: np.ndarray | None = None     # [B] bucket bounds for histogram batches

    @property
    def num_series(self) -> int:
        return len(self.part_ids)

    @property
    def is_histogram(self) -> bool:
        return self.vals.ndim == 3

    def device_arrays(self):
        """(ts, vals, counts) as device arrays, uploaded once per batch —
        cached batches keep data resident on the TPU across queries."""
        dev = getattr(self, "_device", None)
        if dev is None:
            import jax.numpy as jnp

            dev = (jnp.asarray(self.ts), jnp.asarray(self.vals),
                   jnp.asarray(self.counts))
            self._device = dev
        return dev

    def delta_host(self, counter: bool):
        """Rebased values [P,S] f64 for the delta-family range functions
        (rate/increase/delta/irate/idelta/deriv).

        Values are counter-reset-corrected (when ``counter``) and then
        rebased by each series' first in-range value — all HOST-side in
        float64 — so the later float32 device cast only ever sees
        window-scale magnitudes. Without this, a long-lived counter
        ≥2^24 (~16.7M) loses per-window delta precision entirely on the
        f32 device path (reference RateFunctions.scala:1-303 runs in
        double throughout). Prometheus' extrapolate-to-zero clamp needs
        each window's RAW first sample, so kernels additionally take the
        raw value tensor (``device_arrays()[1]``) as a heuristic-only
        reference — f32 rounding there is irrelevant."""
        cache = getattr(self, "_delta_host", None)
        if cache is None:
            cache = self._delta_host = {}
        hit = cache.get(counter)
        if hit is not None:
            return hit
        vals = self.vals
        valid = ~np.isnan(vals)
        v = np.where(valid, vals, 0.0)
        if counter:
            prev = np.concatenate([v[:, :1], v[:, :-1]], axis=1)
            pvalid = np.concatenate(
                [np.zeros_like(valid[:, :1]), valid[:, :-1]], axis=1)
            dropped = (v < prev) & valid & pvalid
            v = v + np.cumsum(np.where(dropped, prev, 0.0), axis=1)
        # samples are packed contiguously from 0, so the first in-range
        # value is column 0 (corrected first == raw first: no prior reset)
        if v.ndim == 3:  # histogram: per-(series, bucket) rebase
            base = np.where(self.counts[:, None] > 0, v[:, 0], 0.0)
            rebased = np.where(valid, v - base[:, None, :], np.nan)
        else:
            base = np.where(self.counts > 0, v[:, 0], 0.0)
            rebased = np.where(valid, v - base[:, None], np.nan)
        cache[counter] = rebased
        return rebased

    def delta_arrays(self, counter: bool):
        """(ts, rebased_vals, counts, raw_vals) device arrays (cached) —
        the device twin of :meth:`delta_host` for the exec kernel path.
        ``raw_vals`` is the shared upload from :meth:`device_arrays`."""
        cache = getattr(self, "_delta_device", None)
        if cache is None:
            cache = self._delta_device = {}
        hit = cache.get(counter)
        if hit is None:
            import jax.numpy as jnp

            rebased = self.delta_host(counter)
            ts_d, raw_d, counts_d = self.device_arrays()
            hit = cache[counter] = (ts_d, jnp.asarray(rebased), counts_d,
                                    raw_d)
        return hit


def build_batch(partitions: list[TimeSeriesPartition], start: int, end: int,
                value_col: int | None = None, pad_series: bool = True,
                pad_samples: bool = True,
                extra_chunks: dict[int, list] | None = None,
                extra_by_obj: dict[int, list] | None = None) -> SeriesBatch:
    """Decode chunks overlapping [start, end] into a SeriesBatch.

    ``start`` already includes the lookback/window extension; ``base_ts`` is
    set to ``start`` so all in-range offsets are non-negative.
    ``extra_chunks`` maps part_id → ODP-paged chunks to merge (single-shard
    callers); ``extra_by_obj`` maps ``id(partition)`` → chunks for callers
    batching across shards, where part_ids are not unique.
    """
    per_ts: list[np.ndarray] = []
    per_vals: list = []
    les = None
    for p in partitions:
        extra = extra_by_obj.get(id(p)) if extra_by_obj else None
        if extra is None and extra_chunks:
            extra = extra_chunks.get(p.part_id)
        ts, vals = p.read_samples(start, end, value_col, extra_chunks=extra)
        if isinstance(vals, HistogramColumn):
            les = vals.les if les is None or len(vals.les) > len(les) else les
            rows = vals.rows.astype(np.float64)
            per_ts.append(ts)
            per_vals.append(rows)
        else:
            valid = ~np.isnan(vals)
            per_ts.append(ts[valid])
            per_vals.append(vals[valid])

    P = len(partitions)
    maxS = max((len(t) for t in per_ts), default=0)
    S = _next_pow2(maxS) if pad_samples else max(maxS, 1)
    Pp = _next_pow2(P) if pad_series else max(P, 1)
    ts_arr = np.full((Pp, S), TS_PAD, np.int32)
    if les is not None:
        B = len(les)
        vals_arr = np.zeros((Pp, S, B), np.float64)
    else:
        vals_arr = np.full((Pp, S), np.nan, np.float64)
    counts = np.zeros(Pp, np.int32)
    for i, (t, v) in enumerate(zip(per_ts, per_vals)):
        n = len(t)
        counts[i] = n
        if n:
            ts_arr[i, :n] = (t - start).astype(np.int32)
            if les is not None and v.shape[-1] != vals_arr.shape[-1]:
                vals_arr[i, :n, : v.shape[-1]] = v  # smaller historic scheme
            else:
                vals_arr[i, :n] = v
    return SeriesBatch(start, ts_arr, vals_arr, counts,
                       [p.part_id for p in partitions], les)


def empty_batch() -> SeriesBatch:
    return SeriesBatch(0, np.full((1, 1), TS_PAD, np.int32),
                       np.full((1, 1), np.nan, np.float64),
                       np.zeros(1, np.int32), [])
